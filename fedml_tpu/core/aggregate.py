"""Pytree-generic aggregation primitives.

Replaces the reference's four per-engine, per-layer-dict loops
(``ml/aggregator/agg_operator.py:18-141``) with ``jax.tree_util`` maps that
work for ANY parameter pytree (flax/haiku/dict-of-arrays).  Three shapes:

* list form — host-side aggregation of per-client pytrees (cross-silo server,
  SP simulator): ``weighted_mean(updates)``.
* stacked form — in-mesh aggregation where client updates live stacked on a
  leading axis in HBM (Parrot-XLA simulator): ``stacked_weighted_mean``.
  This is the TPU translation of ``fedml_nccl_reduce``
  (reference ``simulation/nccl/base_framework/common.py:196``): the weighted
  sum happens on-device and the cross-device combine is a ``lax.psum``.
* compiled plane — :mod:`fedml_tpu.parallel.agg_plane` runs the same
  reduction as ONE donated-buffer GSPMD program over a device mesh;
  :class:`FedMLAggOperator` routes to it when ``args.agg_plane ==
  "compiled"`` and the result is bit-exact vs. the list form in f32 mode.

Structure validation for multi-client pytrees lives in
:func:`flatten_checked`: every stacking/aggregation entry point names the
offending client and leaf instead of failing deep inside ``jnp.stack``.
"""

from __future__ import annotations

import functools
import time
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import obs

Pytree = Any


# ---------------------------------------------------------------------------
# structure validation (shared by tree_stack and the compiled plane)
# ---------------------------------------------------------------------------
def _key_name(key: Any) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(key, attr):
            return str(getattr(key, attr))
    return str(key)


@functools.lru_cache(maxsize=256)
def leaf_paths(treedef) -> Tuple[str, ...]:
    """``/``-joined path names for every leaf of ``treedef``, in flatten
    order — what the compiled plane's partition rules match against and
    what mismatch errors cite.  Cached per treedef (hashable, interned by
    jax), so the path walk happens once per model structure per process."""
    dummy = jax.tree_util.tree_unflatten(
        treedef, list(range(treedef.num_leaves)))
    names: List[str] = [""] * treedef.num_leaves
    for path, idx in jax.tree_util.tree_flatten_with_path(dummy)[0]:
        names[idx] = "/".join(_key_name(k) for k in path) or "<root>"
    return tuple(names)


def opt_leaf_indices(names: Sequence[str], dtypes: Sequence[Any]) -> List[int]:
    """Leaf indices the server optimizer applies to: floating leaves,
    restricted to the ``params`` collection when one exists — the sp/fedopt
    oracle optimizes only ``w_global["params"]`` and plainly averages the
    other collections (batch_stats etc.)."""
    floats = [i for i, dt in enumerate(dtypes)
              if jnp.issubdtype(jnp.dtype(dt), jnp.floating)]
    in_params = [i for i in floats
                 if names[i] == "params" or names[i].startswith("params/")]
    return in_params or floats


def flatten_checked(
        trees: Sequence[Pytree]) -> Tuple[List[List[Any]], Any]:
    """Flatten a list of per-client pytrees, validating that every client
    matches client 0 in structure and per-leaf shape.

    Returns ``(leaves_per_client, treedef)``.  On mismatch raises a
    ``ValueError`` naming the client index and the leaf path — previously
    this surfaced as an opaque shape error deep inside ``jnp.stack``.
    The expensive part of validation (leaf path naming) is computed lazily
    and cached via :func:`leaf_paths`; the per-call cost is one flatten and
    a shape-tuple comparison per client.
    """
    if not trees:
        raise ValueError("no pytrees to aggregate")
    leaves0, treedef0 = jax.tree_util.tree_flatten(trees[0])
    shapes0 = tuple(jnp.shape(l) for l in leaves0)
    out = [leaves0]
    for i, tree in enumerate(trees[1:], start=1):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if treedef != treedef0:
            raise ValueError(
                f"client {i} pytree structure differs from client 0: "
                f"{treedef} vs {treedef0}")
        for j, leaf in enumerate(leaves):
            shape = jnp.shape(leaf)
            if shape != shapes0[j]:
                raise ValueError(
                    f"client {i} leaf '{leaf_paths(treedef0)[j]}' has shape "
                    f"{shape} but client 0 has {shapes0[j]}")
        out.append(leaves)
    return out, treedef0


# ---------------------------------------------------------------------------
# list form (host path)
# ---------------------------------------------------------------------------
def tree_sum(trees: Sequence[Pytree]) -> Pytree:
    return jax.tree_util.tree_map(lambda *xs: sum(xs), *trees)


def tree_scale(tree: Pytree, scalar) -> Pytree:
    return jax.tree_util.tree_map(lambda x: x * scalar, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree_util.tree_map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree_util.tree_map(jnp.subtract, a, b)


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def weighted_mean(updates: Sequence[Tuple[float, Pytree]]) -> Pytree:
    """Sample-weighted average: sum_i (n_i / N) * params_i."""
    total = float(sum(n for n, _ in updates))
    if total <= 0:
        raise ValueError("total sample count must be positive")
    scaled = [tree_scale(p, n / total) for n, p in updates]
    return tree_sum(scaled)


def unweighted_sum(updates: Sequence[Tuple[float, Pytree]]) -> Pytree:
    """`FedAvg_seq` mode (reference agg_operator.py:32-39): plain sum."""
    return tree_sum([p for _, p in updates])


def partial_fold(updates: Sequence[Tuple[float, Pytree]],
                 total_weight: float, mode: str = "mean") -> Pytree:
    """One hierarchy block's share of the round fold (host leg).

    The edge-aggregator tier splits the flat reduction into per-block
    partials; this is a block's contribution with the arithmetic of the
    flat path preserved exactly: ``mean`` scales each update by
    ``n_i / total_weight`` (the GLOBAL total, so the per-leaf multiply is
    the same operand :func:`weighted_mean` would use) and sums
    left-to-right; ``sum`` is the plain left-to-right sum.  Combining
    block partials with :func:`combine_partials` therefore reproduces the
    blocked canonical fold bit-for-bit wherever it runs — the deployment
    topology decides WHERE each block folds, never WHAT is computed.
    """
    if mode not in ("mean", "sum"):
        raise ValueError(f"agg mode must be mean|sum (got {mode!r})")
    if not updates:
        raise ValueError("no updates to fold")
    if mode == "sum":
        return tree_sum([p for _, p in updates])
    total = float(total_weight)
    if total <= 0:
        raise ValueError("total sample count must be positive")
    return tree_sum([tree_scale(p, float(n) / total) for n, p in updates])


def combine_partials(partials: Sequence[Pytree]) -> Pytree:
    """Fold block partials into the round aggregate (host leg): a plain
    left-to-right sum, i.e. exactly the ``sum``-mode fold — partials are
    already scaled (``mean``) or raw sums (``sum``), so no tail math
    remains here."""
    if not partials:
        raise ValueError("no partials to combine")
    return tree_sum(list(partials))


def tree_stack(trees: Sequence[Pytree]) -> Pytree:
    """Stack a list of identically-shaped pytrees on a new leading axis.

    Structure/shape mismatches raise a clear :func:`flatten_checked` error
    naming the client and leaf.
    """
    leaves_list, treedef = flatten_checked(trees)
    stacked = [jnp.stack(cols, axis=0) for cols in zip(*leaves_list)]
    return jax.tree_util.tree_unflatten(treedef, stacked)


def tree_unstack(tree: Pytree, n: int) -> List[Pytree]:
    return [jax.tree_util.tree_map(lambda x: x[i], tree) for i in range(n)]


# ---------------------------------------------------------------------------
# stacked form (in-mesh path)
# ---------------------------------------------------------------------------
def stacked_weighted_sum(stacked: Pytree, weights: jnp.ndarray) -> Pytree:
    """``sum_i w_i * stacked[i]`` where every leaf has leading axis = clients.

    Pure and jit/shard_map-friendly; runs on the MXU via a tensordot-like
    broadcast-multiply + reduce XLA fuses into a single pass over HBM.
    """

    def _leaf(x):
        w = weights.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        return jnp.sum(x * w, axis=0)

    return jax.tree_util.tree_map(_leaf, stacked)


def stacked_weighted_mean(stacked: Pytree, sample_nums: jnp.ndarray) -> Pytree:
    """Sample-weighted average over the stacked leading axis.

    Raises on a non-positive total like :func:`weighted_mean` (the two forms
    used to disagree: this one silently clamped to 1e-12).  Under jit
    tracing the total is abstract and cannot be checked — there the
    defensive clamp remains, documented as traced-path behavior.
    """
    sample_nums = jnp.asarray(sample_nums)
    total = jnp.sum(sample_nums)
    try:
        concrete = float(total)
    except jax.errors.ConcretizationTypeError:
        return stacked_weighted_sum(
            stacked, sample_nums / jnp.maximum(total, 1e-12))
    if concrete <= 0:
        raise ValueError("total sample count must be positive")
    return stacked_weighted_sum(stacked, sample_nums / total)


# ---------------------------------------------------------------------------
# FedMLAggOperator parity facade (reference agg_operator.py:6-16, dispatch
# :130-141 — here one pytree implementation covers all engines)
# ---------------------------------------------------------------------------
class FedMLAggOperator:
    _SUM_MODE = {"FedAvg_seq", "FedOpt_seq"}

    @staticmethod
    def agg(args, raw_grad_list: Sequence[Tuple[float, Pytree]]) -> Pytree:
        opt = getattr(args, "federated_optimizer", "FedAvg")
        mode = "sum" if opt in FedMLAggOperator._SUM_MODE else "mean"
        if str(getattr(args, "agg_plane", "host") or "host") == "compiled":
            from ..parallel.agg_plane import plane_for

            return plane_for(args).aggregate(raw_grad_list, mode=mode)
        t0 = time.perf_counter()
        if mode == "sum":
            out = unweighted_sum(raw_grad_list)
        else:
            out = weighted_mean(raw_grad_list)
        obs.histogram_observe("agg.step_seconds", time.perf_counter() - t0,
                              labels={"path": "host", "mode": mode})
        return out

    @staticmethod
    def agg_mode(args) -> str:
        opt = getattr(args, "federated_optimizer", "FedAvg")
        return "sum" if opt in FedMLAggOperator._SUM_MODE else "mean"


# ---------------------------------------------------------------------------
# server round update: replicated host oracle + the sharded routing facade
# ---------------------------------------------------------------------------
def server_state_mode(args) -> str:
    """``replicated`` (host pytrees, the default) or ``sharded``
    (model-sharded device state, :mod:`fedml_tpu.parallel.agg_plane`)."""
    return str(getattr(args, "server_state", "replicated") or "replicated")


def make_host_round_step(tx):
    """Jitted host server-optimizer tail over (opt params, opt state, avg)
    leaf lists — the exact op chain of the sp/fedopt ``server_update``:
    pseudo-gradient = params − avg, one optax update, apply.  Build once
    and reuse so jit's cache keys on a stable function object."""
    import optax

    @jax.jit
    def _step(opt_params, opt_state, opt_avg):
        pseudo_grad = [p - a for p, a in zip(opt_params, opt_avg)]
        updates, new_state = tx.update(pseudo_grad, opt_state, opt_params)
        return optax.apply_updates(opt_params, updates), new_state

    return _step


def host_server_round_update(params_tree, updates, tx, opt_state,
                             mode: str = "mean", step=None):
    """The replicated host oracle for one round: list-form aggregation plus
    (when ``tx`` is not None) the server-optimizer tail applied to the
    ``params`` collection — bit-exact reference for the sharded round
    plane.  Returns ``(new_global_tree, new_opt_state)``."""
    avg = unweighted_sum(updates) if mode == "sum" else weighted_mean(updates)
    if tx is None:
        return avg, opt_state
    a_leaves, treedef = jax.tree_util.tree_flatten(avg)
    p_leaves, p_treedef = jax.tree_util.tree_flatten(params_tree)
    if p_treedef != treedef:
        raise ValueError(
            f"global params structure {p_treedef} differs from the "
            f"aggregate {treedef}")
    names = leaf_paths(treedef)
    idx = opt_leaf_indices(names, [jnp.result_type(l) for l in p_leaves])
    if step is None:
        step = make_host_round_step(tx)
    out_dtypes = [jnp.result_type(l) for l in a_leaves]
    stepped, new_state = step(
        [jnp.asarray(p_leaves[i]).astype(out_dtypes[i]) for i in idx],
        opt_state, [a_leaves[i] for i in idx])
    out = list(a_leaves)
    for i, v in zip(idx, stepped):
        out[i] = v
    return jax.tree_util.tree_unflatten(treedef, out), new_state


class ServerRoundUpdater:
    """Routing facade for ``server_state=sharded``: owns the per-aggregator
    :class:`~fedml_tpu.parallel.agg_plane.ShardedRoundPlane` (lazily built
    so replicated runs never touch the parallel plane) and exposes the
    snapshot/restore surface the recovery mixin hooks into."""

    def __init__(self, args):
        self.args = args
        self._plane = None
        self._round_idx = 0

    @property
    def plane(self):
        if self._plane is None:
            from ..parallel.agg_plane import make_round_plane
            self._plane = make_round_plane(self.args)
        return self._plane

    def round_update(self, params_tree, raw_grad_list, obs_parent=None,
                     client_ids=None):
        """One sharded round update.  When the plane carries compiled
        security stages the round counter, the participant ids, and this
        round's accountant-granted noise scale ride along as runtime
        inputs; the DP budget is spent here (once per participant) exactly
        like the host mechanism's ``add_noise`` would."""
        plane = self.plane
        dp_sigma = 0.0
        if plane.dp is not None:
            from ..parallel.sec_plane import dp_runtime_sigma
            from .dp.fedml_differential_privacy import FedMLDifferentialPrivacy
            acct = FedMLDifferentialPrivacy.get_instance()
            if acct.is_dp_enabled:  # attribute, set by init()
                dp_sigma = acct.noise_scale()
                acct.spend_budget(len(raw_grad_list))
            else:
                dp_sigma = dp_runtime_sigma(self.args)
        out = plane.round_update(
            params_tree, raw_grad_list,
            mode=FedMLAggOperator.agg_mode(self.args), obs_parent=obs_parent,
            round_idx=self._round_idx, client_ids=client_ids,
            dp_sigma=dp_sigma)
        self._round_idx += 1
        return out

    def export_state(self):
        """Numpy snapshot of the sharded server state (None before the
        first round update)."""
        return self.plane.export_state() if self._plane is not None else None

    def restore_state(self, params_tree, state):
        """Install ``params_tree`` then overwrite leaves + optimizer state
        from a snapshot, bit-identically.  The plane was built over the
        CURRENT live topology, so a snapshot taken on a different mesh
        re-shards onto this one through the portable codec."""
        self.plane.install(params_tree)
        self.plane.load_state(state)

    def mesh_key(self):
        """Fingerprint of the plane's mesh, or None before the plane
        exists (nothing resident — nothing to re-shard)."""
        return self._plane.mesh_key if self._plane is not None else None

    def remesh(self, devices=None):
        """Rebuild the round mesh from the currently-live devices and move
        the resident state onto it through the portable snapshot codec.
        Retries with exponential backoff (``remesh_max_retries`` /
        ``remesh_backoff_s`` knobs, defaults 3 / 0.05s) — device
        enumeration during an in-progress topology change can be
        transiently inconsistent, and each retry re-enumerates.  Returns
        the plane's stats dict, or None when no state is resident yet (the
        next round lazily builds on the live mesh anyway)."""
        if self._plane is None:
            return None
        from ..parallel.agg_plane import round_mesh_for
        retries = max(1, int(getattr(self.args, "remesh_max_retries", 3) or 1))
        backoff = float(getattr(self.args, "remesh_backoff_s", 0.05) or 0.0)
        last_err = None
        for attempt in range(retries):
            try:
                return self.plane.remesh(round_mesh_for(self.args, devices))
            except Exception as e:  # noqa: BLE001 — retried, then re-raised
                last_err = e
                if attempt + 1 < retries and backoff > 0:
                    time.sleep(backoff * (2 ** attempt))
        raise last_err
