"""Pytree-generic aggregation primitives.

Replaces the reference's four per-engine, per-layer-dict loops
(``ml/aggregator/agg_operator.py:18-141``) with ``jax.tree_util`` maps that
work for ANY parameter pytree (flax/haiku/dict-of-arrays).  Two shapes:

* list form — host-side aggregation of per-client pytrees (cross-silo server,
  SP simulator): ``weighted_mean(updates)``.
* stacked form — in-mesh aggregation where client updates live stacked on a
  leading axis in HBM (Parrot-XLA simulator): ``stacked_weighted_mean``.
  This is the TPU translation of ``fedml_nccl_reduce``
  (reference ``simulation/nccl/base_framework/common.py:196``): the weighted
  sum happens on-device and the cross-device combine is a ``lax.psum``.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp

Pytree = Any


# ---------------------------------------------------------------------------
# list form (host path)
# ---------------------------------------------------------------------------
def tree_sum(trees: Sequence[Pytree]) -> Pytree:
    return jax.tree_util.tree_map(lambda *xs: sum(xs), *trees)


def tree_scale(tree: Pytree, scalar) -> Pytree:
    return jax.tree_util.tree_map(lambda x: x * scalar, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree_util.tree_map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree_util.tree_map(jnp.subtract, a, b)


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def weighted_mean(updates: Sequence[Tuple[float, Pytree]]) -> Pytree:
    """Sample-weighted average: sum_i (n_i / N) * params_i."""
    total = float(sum(n for n, _ in updates))
    if total <= 0:
        raise ValueError("total sample count must be positive")
    scaled = [tree_scale(p, n / total) for n, p in updates]
    return tree_sum(scaled)


def unweighted_sum(updates: Sequence[Tuple[float, Pytree]]) -> Pytree:
    """`FedAvg_seq` mode (reference agg_operator.py:32-39): plain sum."""
    return tree_sum([p for _, p in updates])


def tree_stack(trees: Sequence[Pytree]) -> Pytree:
    """Stack a list of identically-shaped pytrees on a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_unstack(tree: Pytree, n: int) -> List[Pytree]:
    return [jax.tree_util.tree_map(lambda x: x[i], tree) for i in range(n)]


# ---------------------------------------------------------------------------
# stacked form (in-mesh path)
# ---------------------------------------------------------------------------
def stacked_weighted_sum(stacked: Pytree, weights: jnp.ndarray) -> Pytree:
    """``sum_i w_i * stacked[i]`` where every leaf has leading axis = clients.

    Pure and jit/shard_map-friendly; runs on the MXU via a tensordot-like
    broadcast-multiply + reduce XLA fuses into a single pass over HBM.
    """

    def _leaf(x):
        w = weights.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        return jnp.sum(x * w, axis=0)

    return jax.tree_util.tree_map(_leaf, stacked)


def stacked_weighted_mean(stacked: Pytree, sample_nums: jnp.ndarray) -> Pytree:
    total = jnp.maximum(jnp.sum(sample_nums), 1e-12)
    return stacked_weighted_sum(stacked, sample_nums / total)


# ---------------------------------------------------------------------------
# FedMLAggOperator parity facade (reference agg_operator.py:6-16, dispatch
# :130-141 — here one pytree implementation covers all engines)
# ---------------------------------------------------------------------------
class FedMLAggOperator:
    _SUM_MODE = {"FedAvg_seq", "FedOpt_seq"}

    @staticmethod
    def agg(args, raw_grad_list: Sequence[Tuple[float, Pytree]]) -> Pytree:
        opt = getattr(args, "federated_optimizer", "FedAvg")
        if opt in FedMLAggOperator._SUM_MODE:
            return unweighted_sum(raw_grad_list)
        return weighted_mean(raw_grad_list)
