"""The server-side update buffer — the heart of buffered-async FL.

Instead of closing a round on quorum, the async server parks every
accepted client delta here, tagged with the global-model *version* the
client trained against, and flushes the whole buffer through the
aggregation plane once ``capacity`` deltas accrue (or the flush deadline
fires).  Two properties matter for correctness:

* **one delta per sender per cycle** — ``add`` raises on a duplicate
  sender; the server's journal dedup (``_uploads_this_round``) enforces
  the same invariant on the accept path, so a crash-replay can never
  double-fill a slot;
* **canonical drain order** — ``drain`` returns entries sorted by
  ``(version, sender)``, so the flush aggregate is a left-to-right fold
  over a deterministic list regardless of upload-thread interleaving.
  This is what makes flushes bit-reproducible given an arrival schedule,
  and what lines async up with the sync participant order for the
  FedAvg-equivalence guarantee (``docs/ASYNC.md``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Tuple

from .staleness import _check_policy, staleness_weight


def _approx_nbytes(obj: Any) -> int:
    """Array-leaf byte count of a params pytree, dependency-free (anything
    exposing ``nbytes`` counts; scalars and exotic leaves count as 0)."""
    nb = getattr(obj, "nbytes", None)
    if nb is not None:
        try:
            return int(nb)
        except (TypeError, ValueError):
            return 0
    if isinstance(obj, dict):
        return sum(_approx_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_approx_nbytes(v) for v in obj)
    return 0


@dataclasses.dataclass(frozen=True)
class BufferedDelta:
    """One accepted client update awaiting a flush."""
    sender: int
    params: Any
    n_samples: float
    version: int    # global-model version the client trained against
    staleness: int  # flush version minus trained version, fixed at accept


class UpdateBuffer:
    """Fixed-capacity accumulator of :class:`BufferedDelta`."""

    def __init__(self, capacity: int, policy: str = "constant",
                 alpha: float = 0.5, hinge_b: int = 4):
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError(f"async_buffer_size must be >= 1, got {capacity}")
        self.capacity = capacity
        self.policy = _check_policy(policy)
        self.alpha = float(alpha)
        self.hinge_b = int(hinge_b)
        self._entries: Dict[int, BufferedDelta] = {}
        self._bytes = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def occupancy(self) -> int:
        return len(self._entries)

    def ready(self) -> bool:
        return len(self._entries) >= self.capacity

    def senders(self) -> List[int]:
        return sorted(self._entries)

    @property
    def approx_bytes(self) -> int:
        """Approximate bytes of buffered delta payloads (array leaves only —
        the ``async.buffer_bytes`` live-memory gauge)."""
        return self._bytes

    def add(self, sender: int, params: Any, n_samples: float, version: int,
            staleness: int) -> int:
        """Park one delta; returns the new occupancy.  A duplicate sender is
        a caller bug (the journal dedup must have dropped it first)."""
        sender = int(sender)
        if sender in self._entries:
            raise ValueError(
                f"sender {sender} already buffered this cycle — the journal "
                "dedup must drop a same-cycle re-upload before it gets here")
        if int(staleness) < 0:
            raise ValueError(
                f"negative staleness {staleness} for sender {sender} "
                f"(version {version}): version tags may never lead the server")
        self._entries[sender] = BufferedDelta(
            sender=sender, params=params, n_samples=float(n_samples),
            version=int(version), staleness=int(staleness))
        self._bytes += _approx_nbytes(params)
        return len(self._entries)

    def drain(self) -> List[BufferedDelta]:
        """Remove and return every entry in canonical ``(version, sender)``
        order — the deterministic fold order for the flush aggregate."""
        entries = sorted(self._entries.values(),
                         key=lambda e: (e.version, e.sender))
        self._entries.clear()
        self._bytes = 0
        return entries

    def weighted(self, entries: List[BufferedDelta]) -> List[Tuple[float, Any]]:
        """The ``(weight, params)`` list the aggregation plane consumes:
        ``weight = n_samples * staleness_weight(policy, s)``.  Under the
        ``constant`` policy the multiplier is exactly ``1.0``, so the list
        is bit-identical to the sync path's ``(n_samples, params)``."""
        return [
            (e.n_samples * staleness_weight(
                self.policy, e.staleness, alpha=self.alpha,
                hinge_b=self.hinge_b), e.params)
            for e in entries
        ]

    @staticmethod
    def staleness_stats(entries: List[BufferedDelta]) -> Dict[str, float]:
        """Per-flush staleness distribution for the ``buffer.flush`` span."""
        if not entries:
            return {"staleness_min": 0.0, "staleness_mean": 0.0,
                    "staleness_max": 0.0}
        vals = [e.staleness for e in entries]
        return {
            "staleness_min": float(min(vals)),
            "staleness_mean": round(float(sum(vals)) / len(vals), 4),
            "staleness_max": float(max(vals)),
        }
