"""Injectable monotonic clocks for async deadline / latency math.

Deadline bookkeeping and the EMA latency feeds must never read wall-clock
directly: ``time.time()`` jumps under NTP, is unmockable in tier-1, and the
virtual-time simulators have no wall-clock at all.  Every ``async_fl``
component that needs "now" takes a clock object with one method —
``now() -> float`` (seconds since an arbitrary epoch, monotone
non-decreasing) — defaulting to :class:`MonotonicClock`
(``time.monotonic``).  Tests and the simulators inject
:class:`ManualClock` and advance it explicitly, which is what makes the
async schedules seed-reproducible on CPU.

Audit note (the companion small-fix for this subsystem):
``core/population/pacer.py`` was checked for the same hazard and is clean
— it is pure arithmetic over counts; the only deadline it relies on is
``round_timeout_s``, armed as a *relative* ``threading.Timer`` delay, not
wall-clock math.  The async flush deadline reuses that timer seam and
keeps all remaining time arithmetic (dispatch→report seconds, flush-period
EMA) on the injected clock.
"""

from __future__ import annotations

import time


class MonotonicClock:
    """The production clock: ``time.monotonic`` behind the one-method seam."""

    def now(self) -> float:
        return time.monotonic()


class ManualClock:
    """A clock that only moves when told to — virtual time for tests and
    the simulators.  ``advance`` is the only mutation; going backwards is a
    programming error and raises."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def now(self) -> float:
        return self._t

    def advance(self, dt: float) -> float:
        dt = float(dt)
        if dt < 0:
            raise ValueError(f"ManualClock cannot go backwards (dt={dt})")
        self._t += dt
        return self._t
