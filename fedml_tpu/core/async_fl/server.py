"""Buffered-async execution mode for the message-plane server managers.

One mixin holds the transport-independent half of FedBuff-style serving so
the cross-silo and cross-device managers stay a thin message-schema layer:

* **accept** — an upload is matched against the sender's outstanding
  dispatch (``_in_flight[sender]`` holds the global-model version it was
  handed); a version-tag mismatch is a retransmit of an already-acked
  upload and is dropped, giving exactly-once delta accounting without any
  wire change (clients already echo ``MSG_ARG_KEY_ROUND_INDEX``).
  Accepted deltas are journaled *before* the transport ack (PR 4's
  journal-before-ack contract, now with a ``version`` field) and parked in
  the :class:`~.buffer.UpdateBuffer`.
* **flush** — once ``async_buffer_size`` deltas accrue (or the
  ``async_flush_deadline_s`` timer fires), the buffer drains in canonical
  order through the aggregation plane with staleness-discounted weights,
  the model version (``args.round_idx``) bumps, and every idle participant
  is re-dispatched on the fresh global.  ``comm_round`` counts flushes.
* **schedule** — on each accepted report the
  :class:`~.scheduler.StalenessScheduler` may re-dispatch a fast client
  immediately (its report lands next cycle at staleness >= 1); slow
  clients wait for the flush barrier, and clients too slow for the
  staleness bound are held out of a wave entirely.

Version/cycle mapping: ``args.round_idx`` IS the global-model version and
bumps once per flush — so every existing per-round mechanism (round-open
snapshot + journal reset, per-cycle sender dedup, deterministic round span
ids, population cycle accounting) applies to async cycles unchanged.  A
buffered delta may carry an *older* version tag than the cycle it is
journaled in; the tag rides in the journal record so a crash-replay
recomputes the same staleness.

MRO: insert between ``ServerRecoveryMixin`` and ``PopulationPacingMixin``
(``class Manager(RoundObsMixin, ServerRecoveryMixin,
AsyncBufferedServerMixin, PopulationPacingMixin, RoundTimeoutMixin,
FedMLCommManager)``): ``_close_round_if_complete`` branches to the flush
check in async mode and defers to the pacing quorum logic otherwise.

Host hooks: ``_async_send_model(client_id, parent_ctx=None)`` (build and
send the dispatch message carrying the current global + version tag) and
optionally ``_async_eval_round`` / ``_async_replay_params(record)``.
Everything else rides the seams the sync mode already requires.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from .. import obs
from .buffer import UpdateBuffer
from .clock import MonotonicClock
from .scheduler import StalenessScheduler

logger = logging.getLogger(__name__)

FL_MODES = ("sync", "async")


class AsyncBufferedServerMixin:
    # -- init ----------------------------------------------------------------
    def init_async_fl(self, args, clock=None) -> None:
        """Call from the manager's ``__init__`` after ``init_population``
        and before ``init_server_recovery`` (replay fills the buffer)."""
        self.fl_mode = str(getattr(args, "fl_mode", "sync") or "sync").lower()
        if self.fl_mode not in FL_MODES:
            raise ValueError(
                f"fl_mode must be one of {FL_MODES}, got {self.fl_mode!r}")
        self.async_enabled = self.fl_mode == "async"
        if not self.async_enabled:
            return
        cap = int(getattr(args, "async_buffer_size", 0) or 0) or self.per_round
        if cap > self.per_round:
            # a buffer that can never fill from the active cohort would only
            # flush by deadline; clamp instead of deadlocking deadline-less runs
            logger.warning(
                "async_buffer_size=%d exceeds the active cohort (%d): "
                "clamping to the cohort size", cap, self.per_round)
            cap = self.per_round
        self.async_buffer = UpdateBuffer(
            capacity=cap,
            policy=str(getattr(args, "async_staleness_policy", "constant")
                       or "constant"),
            alpha=float(getattr(args, "async_staleness_alpha", 0.5) or 0.5),
            hinge_b=int(getattr(args, "async_hinge_b", 4) or 4),
        )
        self.async_max_staleness = int(
            getattr(args, "async_max_staleness", 0) or 0)
        self.async_flush_deadline_s = float(
            getattr(args, "async_flush_deadline_s", 0) or 0)
        self._async_clock = clock if clock is not None else MonotonicClock()
        self.async_scheduler = StalenessScheduler(
            self.population.registry, self.async_max_staleness,
            clock=self._async_clock)
        self._flush_timer = None
        self._in_flight: Dict[int, int] = {}   # client_id -> dispatched version
        self._dispatch_t: Dict[int, float] = {}
        self._async_active: set = set()        # the run's participant pool

    # -- host hooks ----------------------------------------------------------
    def _async_send_model(self, client_id: int, parent_ctx=None) -> None:
        raise NotImplementedError  # message schema lives in the manager

    def _async_eval_round(self, round_idx: int) -> None:
        self.eval_history.append(
            self.aggregator.test_on_server_for_all_clients(int(round_idx)))

    def _async_replay_params(self, record: Dict[str, Any]):
        """Extract the params tree from a journal record (cross-device
        overrides this to re-read its model file); None = unreplayable."""
        return record.get("model_params")

    def _async_after_flush(self, entries) -> None:
        """Called once the flushed cycle's successor snapshot is durable (or
        the run finished) — the earliest point the flushed deltas' backing
        artifacts may be released (cross-device deletes upload files here)."""

    # -- dispatch ------------------------------------------------------------
    def _async_note_dispatch_wave(self, wave: List[int]) -> None:
        """(lock held) Cycle-0 bookkeeping for a wave the manager already
        sent (and whose invites the population draw already counted)."""
        now = self._async_clock.now()
        v = int(self.args.round_idx)
        self._async_active.update(int(c) for c in wave)
        for cid in wave:
            self._in_flight[int(cid)] = v
            self._dispatch_t[int(cid)] = now
        self._arm_flush_timer()

    def _async_dispatch(self, client_id: int, parent_ctx=None) -> None:
        """(lock held) Hand one idle client the current global + version."""
        cid = int(client_id)
        self._in_flight[cid] = int(self.args.round_idx)
        self._dispatch_t[cid] = self._async_clock.now()
        self._async_active.add(cid)
        self.population.note_dispatch(cid)
        self._async_send_model(cid, parent_ctx=parent_ctx)

    def _async_idle_clients(self) -> List[int]:
        return sorted(c for c in self._async_active if c not in self._in_flight)

    # -- accept --------------------------------------------------------------
    def _async_handle_upload(self, sender: int, model_params, n_samples,
                             version_tag, parent_ctx=None,
                             journal_extra: Optional[Dict[str, Any]] = None,
                             journal_params: bool = True,
                             measured_seconds: Optional[float] = None) -> bool:
        """(lock held) The async accept path: match the dispatch, bound the
        staleness, journal-before-ack, park in the buffer, schedule, and
        flush when full.  ``journal_params=False`` keeps the tensors out of
        the journal record when ``journal_extra`` already carries a durable
        pointer to them (the cross-device file plane).
        ``measured_seconds`` (the telemetry plane's remote ``client.train``
        duration) replaces the dispatch→report wall clock in the EMA when
        available — the wall clock conflates network and queueing time
        with compute.  Returns True when the delta was buffered (the
        manager may need to release a dropped upload's backing
        artifact)."""
        sender = int(sender)
        v = int(self.args.round_idx)
        if version_tag is None:
            logger.warning(
                "dropping UNTAGGED upload from client %d: async mode cannot "
                "compute staleness without MSG_ARG_KEY_ROUND_INDEX", sender)
            obs.counter_inc("async.dropped_untagged")
            self._note_rejected_late(sender)
            return False
        tag = int(version_tag)
        expected = self._in_flight.get(sender)
        if expected is None or tag != expected:
            # not this sender's outstanding dispatch: a retransmit of an
            # already-acked upload (exactly-once) or a ghost
            logger.info(
                "dropping upload from client %d tagged v%d (outstanding "
                "dispatch: %s) — duplicate or stray", sender, tag, expected)
            obs.counter_inc("async.dropped_dup")
            return False
        staleness = v - tag
        if staleness > self.async_max_staleness:
            # too stale to aggregate — but the client is now idle and fresh
            # work beats idling, so it gets the current global immediately
            logger.warning(
                "dropping stale delta from client %d (staleness %d > bound "
                "%d); re-dispatching on v%d", sender, staleness,
                self.async_max_staleness, v)
            obs.counter_inc("async.dropped_stale")
            self._note_rejected_late(sender)
            self._in_flight.pop(sender, None)
            self._dispatch_t.pop(sender, None)
            self._async_dispatch(sender)
            return False
        payload: Dict[str, Any] = {"n_samples": n_samples, "version": tag}
        if journal_params:
            payload["model_params"] = model_params
        payload.update(journal_extra or {})
        with self._obs_phase("journal.append", parent=parent_ctx, seq=sender,
                             sender=sender, version=tag) as jsp:
            ok = self._journal_upload(sender, **payload)
            if not ok:
                jsp.event("dup", side="journal", sender=sender)
        if not ok:
            # this sender already filled its slot this cycle (a second
            # same-cycle contribution after an immediate re-dispatch, or a
            # replayed duplicate): one delta per sender per cycle
            obs.counter_inc("async.dropped_dup")
            self._in_flight.pop(sender, None)
            self._dispatch_t.pop(sender, None)
            return False
        self._in_flight.pop(sender, None)
        zero_copy = getattr(self, "_zero_copy", None)
        if zero_copy is not None and model_params is not None:
            # accepted (every drop path already returned): land the delta in
            # this sender's arena — one accepted delta per sender per cycle
            # (journal dedup above), and the flush drains the buffer before
            # the sender can be re-dispatched, so arena reuse never clobbers
            # a buffered delta
            model_params = zero_copy.intern(sender, model_params)
        occ = self.async_buffer.add(sender, model_params, n_samples,
                                    version=tag, staleness=staleness)
        obs.histogram_observe("async.staleness", float(staleness))
        obs.gauge_set("async.buffer_occupancy", float(occ))
        obs.gauge_set("async.buffer_bytes",
                      float(self.async_buffer.approx_bytes))
        t0 = self._dispatch_t.pop(sender, None)
        secs = None if t0 is None else max(self._async_clock.now() - t0, 0.0)
        if measured_seconds is not None:
            secs = max(float(measured_seconds), 0.0)
        self.population.note_report(
            sender, round_idx=v,
            n_samples=None if n_samples is None else int(n_samples),
            seconds=secs)
        if (not self.async_buffer.ready()
                and self.async_scheduler.redispatch_now(sender)):
            self._async_dispatch(sender)
        self._close_round_if_complete()
        return True

    # -- close check (PopulationPacingMixin override point) ------------------
    def _close_round_if_complete(self) -> bool:
        if not getattr(self, "async_enabled", False):
            return super()._close_round_if_complete()
        if not self.async_buffer.ready():
            return False
        self._async_flush_safely("full")
        return True

    # -- flush ---------------------------------------------------------------
    def _async_flush_safely(self, reason: str) -> None:
        """(lock held) Flush with the shared error policy (see
        ``straggler._finalize_safely``): with any tolerance knob on, a
        flush failure shuts the run down cleanly instead of wedging it."""
        if self.round_timeout_s <= 0 and self.async_flush_deadline_s <= 0:
            self._async_flush(reason)
            return
        try:
            self._async_flush(reason)
        except Exception:
            logger.exception("async flush failed; shutting down")
            self._finished = True
            self.send_finish_msg()
            self.finish()

    def _async_flush(self, reason: str) -> None:
        """(lock held) Drain → weight → aggregate → bump version → re-open."""
        self._gen += 1  # this cycle's deadline timer goes stale
        self._cancel_flush_timer()
        entries = self.async_buffer.drain()
        closing_idx = int(self.args.round_idx)
        closing_ctx = self._obs_round_ctx()
        closing_root = self._obs_round
        stats = UpdateBuffer.staleness_stats(entries)
        with self._obs_phase("buffer.flush", n_deltas=len(entries),
                             reason=reason, capacity=self.async_buffer.capacity,
                             **stats):
            weighted = self.async_buffer.weighted(entries)
            self.aggregator.aggregate_buffered(weighted)
            freq = int(getattr(self.args, "frequency_of_the_test", 1) or 0)
            if freq and (closing_idx % freq == 0
                         or closing_idx == self.round_num - 1):
                self._async_eval_round(closing_idx)
        obs.counter_inc("async.flushes", labels={"reason": reason})
        obs.gauge_set("async.buffer_occupancy", 0.0)
        obs.gauge_set("async.buffer_bytes", 0.0)
        obs.maybe_export_metrics()
        self.async_scheduler.note_flush()
        self.population.close_round(reason="flush", fail_missing=False)

        self.args.round_idx += 1
        if self.args.round_idx >= self.round_num:
            self._finished = True
            with self._obs_phase("broadcast", parent=closing_ctx,
                                 round_idx=closing_idx, final=True):
                self.send_finish_msg()
            self._obs_close_round(reason="run_complete")
            self._async_after_flush(entries)
            self.finish()
            return

        # open the next cycle: fresh root span, fresh journal + snapshot,
        # and a re-dispatch wave over every idle participant (in-flight
        # clients keep training — their reports land here at staleness >= 1)
        self._obs_round = None
        self._obs_open_round(mode="async")
        self.population.begin_cycle(self.args.round_idx, self.per_round)
        wave = self._async_idle_clients()
        self.client_id_list_in_this_round = sorted(
            set(wave) | set(self._in_flight))
        self._save_round_start()
        # the new cycle's snapshot is durable: a crash from here on restores
        # *after* the flush, so the flushed deltas' artifacts can be released
        self._async_after_flush(entries)
        chosen = [c for c in wave
                  if not self.async_scheduler.defer_at_flush(c)]
        if not chosen and not self._in_flight:
            chosen = wave  # never stall: an all-deferred wave dispatches
        deferred = len(wave) - len(chosen)
        if deferred:
            obs.counter_inc("async.deferred_dispatch", deferred)
        bcast = self._obs_phase("broadcast", parent=closing_ctx,
                                round_idx=closing_idx)
        with self._obs_phase("invite", fanout=len(chosen),
                             mode="async") as inv:
            for cid in chosen:
                self._async_dispatch(cid, parent_ctx=inv.ctx)
        bcast.end()
        if closing_root is not None:
            closing_root.end(reason="flush")
        self._arm_flush_timer()

    # -- deadline timer ------------------------------------------------------
    def _arm_flush_timer(self) -> None:
        if self.async_flush_deadline_s <= 0 or self._finished:
            return
        # the scheduler's liveness contract: armed while a deadline timer
        # is outstanding, beaten when it fires — a timer thread that dies
        # (or never fires) expires the watchdog instead of parking the
        # buffer forever.  Deadline scales with the flush deadline so a
        # slow-but-legal cycle never false-positives.
        wd = getattr(self, "_flush_watchdog", None)
        if wd is None:
            wd = obs.health_watchdog(
                "async.flush",
                deadline_s=max(5.0, 2.0 * self.async_flush_deadline_s + 1.0))
            self._flush_watchdog = wd
        wd.beat()
        self._start_phase_timer("_flush_timer", self._on_flush_deadline,
                                delay=self.async_flush_deadline_s)

    def _cancel_flush_timer(self) -> None:
        t = getattr(self, "_flush_timer", None)
        if t is not None:
            t.cancel()
            self._flush_timer = None
        wd = getattr(self, "_flush_watchdog", None)
        if wd is not None:
            wd.idle()

    def _on_flush_deadline(self, gen: int) -> None:
        wd = getattr(self, "_flush_watchdog", None)
        if wd is not None:
            wd.beat()
        with self._round_lock:
            if self._finished or gen != self._gen:
                return
            if len(self.async_buffer) == 0:
                self._arm_flush_timer()  # nothing to flush; keep waiting
                return
            logger.info("flush deadline: draining %d/%d buffered deltas",
                        len(self.async_buffer), self.async_buffer.capacity)
            self._async_flush_safely("deadline")

    # -- crash recovery ------------------------------------------------------
    def _async_replay_upload(self, record: Dict[str, Any]) -> bool:
        """(recovery) Re-park one journaled delta.  The record's ``version``
        field recomputes the same staleness the dead incarnation accepted
        it at (the cycle index has not moved since the snapshot)."""
        sender = int(record["sender"])
        params = self._async_replay_params(record)
        if params is None:
            return False
        v = int(record.get("version", record.get("round_idx", 0)))
        staleness = int(self.args.round_idx) - v
        if staleness < 0 or staleness > self.async_max_staleness:
            return False
        occ = self.async_buffer.add(sender, params, record["n_samples"],
                                    version=v, staleness=staleness)
        obs.gauge_set("async.buffer_occupancy", float(occ))
        obs.gauge_set("async.buffer_bytes",
                      float(self.async_buffer.approx_bytes))
        n = record.get("n_samples")
        self.population.note_report(
            sender, round_idx=int(self.args.round_idx),
            n_samples=None if n is None else int(n))
        return True

    def _async_resync(self, client_id: int) -> None:
        """(lock held) A client rejoined (or the server restarted and its
        ONLINE reads as a rejoin): if its delta for this cycle is already
        journaled it waits for the flush broadcast; otherwise it gets the
        current global now."""
        cid = int(client_id)
        if cid in self._uploads_this_round:
            return
        if self._async_active and cid not in self._async_active:
            return  # not part of this run's pool
        self._async_dispatch(cid)
