"""Heterogeneity-aware dispatch scheduling for buffered-async FL.

The synchronous modes pay the straggler tax once per round; async pays it
per *dispatch decision*.  :class:`StalenessScheduler` reads the population
registry's ``ema_seconds`` column (fed by every accepted report's
dispatch→report latency) and answers the two questions the server asks:

* :meth:`redispatch_now` — a client just reported mid-cycle: hand it the
  current global immediately (keeping the buffer fed; its next report
  lands at staleness >= 1) or hold it for the flush barrier?  Fast clients
  (strictly below the fleet's median observed latency) go immediately;
  slow clients wait — the Parrot-style pacing rule: dispatch frequency
  adapts to client speed instead of one global cadence.
* :meth:`defer_at_flush` — at a flush's re-dispatch wave, is this client
  so slow that its report would exceed ``async_max_staleness`` flushes
  anyway?  If its latency EMA is beyond ``(max_staleness + 1)`` expected
  flush periods, training it now is wasted work; it is held back and
  reconsidered at the next flush (the flush-period EMA moves, so the
  decision is re-evaluated, never frozen).

All time arithmetic runs on the injected clock (:mod:`.clock`), so the
virtual-time simulators and tier-1 tests drive the same decision code
deterministically.

:class:`VirtualArrivalQueue` is the simulators' deterministic arrival
schedule: a heapq of ``(finish_time, push_seq, client_id)`` whose tie-break
is insertion order — two clients finishing at the same virtual instant pop
in dispatch order, never hash order.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np

from .clock import MonotonicClock


class StalenessScheduler:
    def __init__(self, registry, max_staleness: int, clock=None,
                 flush_ema_alpha: float = 0.3):
        self.registry = registry
        self.max_staleness = int(max_staleness)
        self.clock = clock if clock is not None else MonotonicClock()
        self._alpha = float(flush_ema_alpha)
        self._last_flush_t: Optional[float] = None
        self.flush_period_ema: Optional[float] = None

    # -- latency context -----------------------------------------------------
    def _ema_of(self, client_id: int) -> float:
        pos = int(self.registry.positions([int(client_id)])[0])
        return float(self.registry.ema_seconds[pos])

    def _fleet_median(self) -> Optional[float]:
        ema = self.registry.ema_seconds
        observed = ema[ema > 0]
        if observed.size == 0:
            return None
        return float(np.median(observed))

    # -- flush bookkeeping ---------------------------------------------------
    def note_flush(self) -> None:
        """Fold the just-completed inter-flush interval into the period EMA
        (the denominator of the defer rule)."""
        now = self.clock.now()
        if self._last_flush_t is not None:
            period = max(now - self._last_flush_t, 0.0)
            if self.flush_period_ema is None:
                self.flush_period_ema = period
            else:
                self.flush_period_ema = (
                    (1 - self._alpha) * self.flush_period_ema
                    + self._alpha * period)
        self._last_flush_t = now

    # -- dispatch decisions --------------------------------------------------
    def redispatch_now(self, client_id: int) -> bool:
        """Mid-cycle, on an accepted report: re-dispatch immediately?  Needs
        a staleness budget (>= 1 — an immediate re-dispatch cannot report
        before the next flush) and a strictly-faster-than-median latency
        EMA.  With no observations yet everyone waits for the barrier."""
        if self.max_staleness < 1:
            return False
        mine = self._ema_of(client_id)
        median = self._fleet_median()
        if mine <= 0 or median is None:
            return False
        return mine < median

    def defer_at_flush(self, client_id: int) -> bool:
        """At a flush's re-dispatch wave: hold this client out because its
        expected report would be dropped as too stale anyway."""
        if self.max_staleness < 1 or self.flush_period_ema is None \
                or self.flush_period_ema <= 0:
            return False
        mine = self._ema_of(client_id)
        if mine <= 0:
            return False
        return mine > (self.max_staleness + 1) * self.flush_period_ema


class VirtualArrivalQueue:
    """Deterministic virtual-time report schedule (simulator surface)."""

    def __init__(self):
        self._heap: List[Tuple[float, int, int]] = []
        self._seq = 0

    def __len__(self) -> int:
        return len(self._heap)

    def __bool__(self) -> bool:
        return bool(self._heap)

    def push(self, client_id: int, finish_time: float) -> None:
        heapq.heappush(self._heap,
                       (float(finish_time), self._seq, int(client_id)))
        self._seq += 1

    def peek_time(self) -> float:
        return self._heap[0][0]

    def pop(self) -> Tuple[float, int]:
        """``(finish_time, client_id)`` of the next virtual report."""
        t, _, cid = heapq.heappop(self._heap)
        return t, cid

    def clients(self) -> List[int]:
        """The client ids currently in flight (sorted, for set checks)."""
        return sorted(cid for _, _, cid in self._heap)
