"""``fedml_tpu.core.async_fl`` — buffered asynchronous FL (FedBuff-style).

The subsystem that closes the ROADMAP's "non-synchronous production FL"
gap: instead of gating each round on a quorum, the server accumulates
client deltas in an :class:`UpdateBuffer` (each tagged with the
global-model version it trained against) and flushes through the
aggregation plane once ``async_buffer_size`` deltas accrue or the flush
deadline fires, down-weighting stale deltas by a configurable policy
(:mod:`.staleness`).  Dispatch is heterogeneity-aware
(:class:`StalenessScheduler`): fast clients are re-invited the moment
they report, slow ones are paced against the staleness bound.

Selected via ``args.fl_mode = "async"`` (knob reference in
``arguments.py``; execution model and crash-safety contract in
``docs/ASYNC.md``).  The message-plane half lives in
:class:`AsyncBufferedServerMixin`; the simulators reuse the same buffer /
policy / scheduler pieces with a :class:`VirtualArrivalQueue` and a
:class:`ManualClock` for seed-reproducible virtual time.
"""

from .buffer import BufferedDelta, UpdateBuffer
from .clock import ManualClock, MonotonicClock
from .scheduler import StalenessScheduler, VirtualArrivalQueue
from .server import FL_MODES, AsyncBufferedServerMixin
from .staleness import (
    ASYNC_STALENESS_POLICIES,
    staleness_weight,
    staleness_weights,
)

__all__ = [
    "ASYNC_STALENESS_POLICIES", "FL_MODES",
    "AsyncBufferedServerMixin", "BufferedDelta", "ManualClock",
    "MonotonicClock", "StalenessScheduler", "UpdateBuffer",
    "VirtualArrivalQueue", "staleness_weight", "staleness_weights",
]
