"""Staleness-weighting policies for buffered-async aggregation.

A buffered delta trained against global-model version ``v`` and flushed at
version ``v + s`` has *staleness* ``s`` (the number of flushes it missed).
Its aggregation weight is ``n_samples * weight(policy, s)`` where
``weight`` is one of three closed-form down-weighting schedules (FedBuff,
arXiv:2106.06639 §3.2 — the polynomial family is the paper's ``s(t) =
1/(1+t)^a``; ``hinge`` tolerates a grace window before decaying):

* ``constant``:    ``1.0`` — staleness ignored.  With
  ``async_buffer_size == cohort`` this reproduces synchronous FedAvg
  bit-exactly (the equivalence test in ``tests/test_async_fl.py``).
* ``polynomial``:  ``(1 + s) ** -alpha``.
* ``hinge``:       ``1.0`` for ``s <= b``, else ``1 / (1 + alpha*(s-b))``.

Two callables cover both execution surfaces: :func:`staleness_weight` is
the host-side scalar form (message-plane servers, sp simulator) and
:func:`staleness_weights` is the jit-traceable array form the XLA in-mesh
strategy folds into its one-program flush.
"""

from __future__ import annotations

ASYNC_STALENESS_POLICIES = ("constant", "polynomial", "hinge")


def _check_policy(policy: str) -> str:
    p = str(policy).lower()
    if p not in ASYNC_STALENESS_POLICIES:
        raise ValueError(
            f"async_staleness_policy must be one of {ASYNC_STALENESS_POLICIES}, "
            f"got {policy!r}")
    return p


def staleness_weight(policy: str, staleness: float, alpha: float = 0.5,
                     hinge_b: int = 4) -> float:
    """Scalar weight multiplier for one delta of the given staleness."""
    p = _check_policy(policy)
    s = float(staleness)
    if s < 0:
        raise ValueError(f"staleness must be >= 0, got {s}")
    if p == "constant":
        return 1.0
    if p == "polynomial":
        return float((1.0 + s) ** -float(alpha))
    b = float(hinge_b)
    if s <= b:
        return 1.0
    return float(1.0 / (1.0 + float(alpha) * (s - b)))


def staleness_weights(policy: str, staleness, alpha: float = 0.5,
                      hinge_b: int = 4):
    """Array form of :func:`staleness_weight` — pure ``jnp`` ops on an
    f32 staleness vector, safe inside jit (the policy is a static Python
    branch, the staleness values are traced)."""
    import jax.numpy as jnp

    p = _check_policy(policy)
    s = jnp.asarray(staleness, jnp.float32)
    if p == "constant":
        return jnp.ones_like(s)
    if p == "polynomial":
        return (1.0 + s) ** jnp.float32(-float(alpha))
    b = jnp.float32(float(hinge_b))
    return jnp.where(s <= b, jnp.float32(1.0),
                     1.0 / (1.0 + jnp.float32(float(alpha)) * (s - b)))
