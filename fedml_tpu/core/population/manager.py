"""Population manager: the facade tying registry + policy + pacer together.

Two usage surfaces share one accounting core:

* **message-plane servers** (cross_silo / cross_device) drive the
  incremental API — ``invite`` at round open, ``note_report`` per upload,
  ``note_rejected_late`` for post-close stragglers, ``close_round`` when
  the round finalizes;
* **simulators** (sp / XLA), where a round is synchronous, call
  ``observe_round`` once with the whole cohort (fully vectorized — no
  per-client Python loop, so it holds up at Parrot fleet sizes).

Every close emits one ``cohort_stats`` record through ``core/mlops``
(no-op until ``mlops.init``), mirroring how PR 1's ``comm_stats`` flow.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .pacer import RoundPacer
from .policies import SelectionPolicy, make_policy
from .registry import ClientRegistry


class PopulationManager:
    def __init__(self, registry: ClientRegistry, policy: SelectionPolicy,
                 pacer: Optional[RoundPacer] = None, emit=None):
        self.registry = registry
        self.policy = policy
        self.pacer = pacer if pacer is not None else RoundPacer()
        self._emit = emit  # test override; default is the mlops facade
        self._round_idx: Optional[int] = None
        self._target_k = 0
        self._invited: List[int] = []
        self._reported: set = set()
        self._rejected_late = 0
        self.history: List[Dict[str, Any]] = []

    @classmethod
    def from_args(cls, args, client_ids: Sequence[int],
                  num_samples: Optional[Sequence[int]] = None,
                  rng_style: str = "mt19937", emit=None) -> "PopulationManager":
        """Build the whole stack from validated config knobs (the knob
        schema lives in ``arguments.py``; ``docs/POPULATION.md`` documents
        semantics)."""
        registry = ClientRegistry(client_ids, num_samples=num_samples)
        blocklist = getattr(args, "population_blocklist", None)
        if blocklist:
            registry.blocklist(list(blocklist))
        policy = make_policy(
            getattr(args, "selection_policy", "uniform"),
            registry,
            rng_style=rng_style,
            num_strata=int(getattr(args, "population_strata", 4) or 4),
            importance_alpha=float(getattr(args, "importance_alpha", 1.0) or 1.0),
            importance_staleness=float(
                getattr(args, "importance_staleness", 0.0) or 0.0
            ),
        )
        return cls(registry, policy, pacer=RoundPacer.from_args(args), emit=emit)

    # -- message-plane surface ----------------------------------------------
    def select(self, round_idx: int, k: int) -> np.ndarray:
        """Policy draw only — no accounting (the simulator sampling seam)."""
        return self.policy.select(int(round_idx), int(k))

    def invite(self, round_idx: int, k: int) -> List[int]:
        """Open a round: select ``invite_count(k)`` clients (over-commit)
        and mark them invited.  Returns the invite list in policy order."""
        invited = [int(c) for c in
                   self.policy.select(int(round_idx), self.pacer.invite_count(int(k)))]
        self.registry.note_invited(invited, int(round_idx))
        self._round_idx = int(round_idx)
        self._target_k = int(k)
        self._invited = invited
        self._reported = set()
        self._rejected_late = 0
        return invited

    @property
    def quorum(self) -> int:
        """Reports needed to close the open round."""
        return self.pacer.quorum_for(self._target_k, len(self._invited))

    def note_report(self, client_id: int, round_idx: Optional[int] = None,
                    n_samples: Optional[int] = None,
                    seconds: Optional[float] = None) -> bool:
        """One upload landed; idempotent per round (re-deliveries don't
        double-count).  Returns True when this was a fresh report."""
        cid = int(client_id)
        if cid in self._reported:
            return False
        self._reported.add(cid)
        r = self._round_idx if round_idx is None else int(round_idx)
        self.registry.note_report(cid, 0 if r is None else r,
                                  n_samples=n_samples, seconds=seconds)
        return True

    def quorum_reached(self) -> bool:
        return len(self._reported) >= self.quorum

    # -- async surface (core/async_fl) ---------------------------------------
    def begin_cycle(self, round_idx: int, k: int) -> None:
        """Open accounting for a buffered-async cycle WITHOUT a policy draw:
        async dispatches arrive incrementally (:meth:`note_dispatch`) — the
        flush wave, mid-cycle fast-client re-invites, rejoin resyncs — so
        the invite list grows as the cycle runs instead of being fixed at
        open."""
        self._round_idx = int(round_idx)
        self._target_k = int(k)
        self._invited = []
        self._reported = set()
        self._rejected_late = 0

    def note_dispatch(self, client_id: int) -> None:
        """One async dispatch: count the invite and grow the cycle's
        invite list (reports from clients dispatched in *earlier* cycles
        still land through :meth:`note_report` — membership is not
        required there)."""
        cid = int(client_id)
        self.registry.note_invited([cid], 0 if self._round_idx is None
                                   else self._round_idx)
        self._invited.append(cid)

    # -- crash-recovery surface (core/checkpoint.ServerRecoveryMixin) --------
    def export_registry(self) -> Dict[str, Any]:
        return self.registry.state_columns()

    def restore_registry(self, cols: Dict[str, Any]) -> None:
        self.registry.load_state_columns(cols)

    def resume_round(self, round_idx: int, k: int,
                     invited: Sequence[int]) -> None:
        """Re-open a round from a restored snapshot WITHOUT re-drawing the
        policy or re-counting invites: the snapshot was taken at round open,
        *after* :meth:`invite` ran, so the restored registry columns already
        carry this round's invite marks but none of its reports.  Journal
        replay then re-fills ``_reported`` through the normal
        :meth:`note_report` path, which re-counts each report exactly once
        (the pre-crash counts died with the old incarnation's memory)."""
        self._round_idx = int(round_idx)
        self._target_k = int(k)
        self._invited = [int(c) for c in invited]
        self._reported = set()
        self._rejected_late = 0

    def note_rejected_late(self, client_id: int) -> None:
        self._rejected_late += 1
        self.registry.note_rejected_late(int(client_id))

    def note_rejoin(self, client_id: int) -> None:
        self.registry.note_rejoin(int(client_id))

    def close_round(self, reason: str = "complete",
                    seconds: Optional[float] = None,
                    fail_missing: bool = True) -> Dict[str, Any]:
        """Close the open round: invited-but-missing become failures, and
        one ``cohort_stats`` record is emitted.  Async flush cycles pass
        ``fail_missing=False`` — an invitee that has not reported is still
        *in flight* (its delta lands in a later cycle), not failed."""
        r = self._round_idx if self._round_idx is not None else 0
        missing = [c for c in self._invited if c not in self._reported]
        if missing and fail_missing:
            self.registry.note_failures(missing, r)
        stats = self._stats(r, len(self._invited), len(self._reported),
                            len(missing) if fail_missing else 0,
                            self._rejected_late, reason, seconds)
        self._round_idx = None
        return stats

    # -- simulator surface (fully vectorized) -------------------------------
    def observe_round(self, round_idx: int, invited_ids,
                      reported_ids=None, seconds: Optional[float] = None,
                      reason: str = "complete") -> Dict[str, Any]:
        """Record a whole synchronous round in one shot: everyone in
        ``invited_ids`` was invited; ``reported_ids`` (default: all of them)
        reported.  One vectorized registry update per counter."""
        inv = np.asarray(invited_ids, np.int64).reshape(-1)
        rep = inv if reported_ids is None else np.asarray(reported_ids, np.int64).reshape(-1)
        r = int(round_idx)
        self.registry.note_invited(inv, r)
        self.registry.note_reports(rep, r, seconds=seconds)
        missing = np.setdiff1d(inv, rep)
        if missing.size:
            self.registry.note_failures(missing, r)
        self._target_k = int(rep.size)
        return self._stats(r, int(inv.size), int(rep.size), int(missing.size),
                           0, reason, seconds)

    # -- stats ---------------------------------------------------------------
    def _stats(self, round_idx: int, invited: int, reported: int, failed: int,
               rejected_late: int, reason: str,
               seconds: Optional[float]) -> Dict[str, Any]:
        stats: Dict[str, Any] = {
            "round_idx": int(round_idx),
            "policy": self.policy.name,
            "target_k": int(self._target_k),
            "invited": invited,
            "reported": reported,
            "failed": failed,
            "rejected_late": rejected_late,
            "quorum": self.pacer.quorum_for(self._target_k, invited or self._target_k),
            "overcommit": self.pacer.overcommit,
            "close_reason": str(reason),
        }
        if seconds is not None:
            stats["round_seconds"] = round(float(seconds), 4)
        if self.policy.last_strata_sizes is not None:
            stats["strata_sizes"] = list(self.policy.last_strata_sizes)
        stats.update(self.registry.snapshot())
        self.history.append(stats)
        # registry mirror: the same counters, joinable with comm.* and the
        # span layer (legacy cohort_stats topic keeps emitting below)
        from .. import obs

        labels = {"policy": self.policy.name}
        obs.counter_inc("population.invited", invited, labels)
        obs.counter_inc("population.reported", reported, labels)
        obs.counter_inc("population.failed", failed, labels)
        obs.counter_inc("population.rejected_late", rejected_late, labels)
        obs.counter_inc(f"population.close.{reason}", 1, labels)
        if seconds is not None:
            obs.histogram_observe("population.round_seconds", float(seconds))
        if self._emit is not None:
            self._emit(stats)
        else:
            from ..mlops import log_cohort_stats

            log_cohort_stats(stats)
        return stats
