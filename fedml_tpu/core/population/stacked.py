"""Stacked cohort selection: every round's cohort in ONE vectorized draw.

The XLA Parrot simulator's fleet is 10^5-10^6 VIRTUAL clients; a per-round
Python-level ``choice`` over that pool is host work on the round's critical
path.  This path draws the whole run's schedule up front as one
``(rounds, n_total)`` key matrix and one ``argpartition`` per axis —
no Python loop over clients or rounds.

Uniform cohorts take the k SMALLEST of iid uniform keys per row (an
unordered uniform k-subset); weighted cohorts use Gumbel-top-k
(``log w + Gumbel`` noise, the exponential-race trick), which samples
without replacement proportional to ``w``.  Blocklisted clients get a
``+inf`` key and can never be drawn.

Determinism: one ``RandomState(seed)`` generates the whole matrix, so the
schedule is a pure function of ``(seed, n_total, k, rounds, weights)``.
This is a DIFFERENT schedule from the per-round legacy draw (which reseeds
per round) — it is the scale surface, opt-in via ``population_stacked``,
not the parity surface.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def stacked_cohorts(n_total: int, k: int, rounds: int, seed: int = 0,
                    weights: Optional[Sequence[float]] = None,
                    blocked: Optional[Sequence[int]] = None) -> np.ndarray:
    """Return a ``(rounds, k)`` int64 matrix; row r is round r's cohort,
    sorted by draw priority (stable, deterministic)."""
    n_total, k, rounds = int(n_total), int(k), int(rounds)
    if not (0 < k <= n_total):
        raise ValueError(f"need 0 < k <= n_total (k={k}, n_total={n_total})")
    if rounds <= 0:
        raise ValueError(f"rounds must be > 0 (got {rounds})")
    blocked_arr = None
    if blocked is not None:
        blocked_arr = np.asarray(list(blocked), np.int64)
        if blocked_arr.size and k > n_total - np.unique(blocked_arr).size:
            raise ValueError("blocklist leaves fewer than k eligible clients")
    rs = np.random.RandomState(int(seed))
    if weights is None:
        keys = rs.random_sample((rounds, n_total))
    else:
        w = np.asarray(list(weights), np.float64)
        if w.shape != (n_total,):
            raise ValueError("weights must have length n_total")
        if (w < 0).any() or not (w > 0).any():
            raise ValueError("weights must be >= 0 with at least one > 0")
        logw = np.where(w > 0, np.log(np.maximum(w, 1e-300)), -np.inf)
        # take the k smallest of -(log w + Gumbel) == the k largest Gumbel keys
        keys = -(logw[None, :] + rs.gumbel(size=(rounds, n_total)))
    if blocked_arr is not None and blocked_arr.size:
        keys[:, blocked_arr] = np.inf
    idx = np.argpartition(keys, k - 1, axis=1)[:, :k]
    # canonical within-row order: by key, tie-broken by client id
    part_keys = np.take_along_axis(keys, idx, axis=1)
    order = np.lexsort((idx, part_keys), axis=1)
    return np.take_along_axis(idx, order, axis=1).astype(np.int64)
