"""Pacing mixin for the message-plane server managers.

Layers over-commit + quorum-close semantics ON TOP of
``core/distributed/straggler.RoundTimeoutMixin`` — the deadline, the
generation counter, the stale-upload policy, and the lock discipline all
stay in that one copy; this mixin only (a) swaps the round's participant
list for a policy-selected invite list and (b) replaces the wait-for-all
close check with a quorum check when pacing is enabled.

MRO: ``class Manager(PopulationPacingMixin, RoundTimeoutMixin,
FedMLCommManager)`` — the pacing mixin overrides the no-op hooks
(``_note_rejected_late``, ``_note_population_rejoin``) the timeout mixin
calls.

Host manager requirements (on top of the timeout mixin's): call
``init_population`` from ``__init__`` (after ``init_straggler_tolerance``),
open each round's list via ``_population_round_list``, record each accepted
upload via ``_note_population_report``, and replace the
``check_whether_all_receive`` close dance with ``_close_round_if_complete``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from .manager import PopulationManager


class PopulationPacingMixin:
    def init_population(self, args, client_ids: Sequence[int],
                        num_samples: Optional[Sequence[int]] = None,
                        rng_style: str = "pcg64") -> None:
        self.population = PopulationManager.from_args(
            args, client_ids, num_samples=num_samples, rng_style=rng_style
        )

    # -- round open ----------------------------------------------------------
    def _population_round_list(self, round_idx: int, k: int) -> List[int]:
        """The round's participant list: ``ceil(k * overcommit)`` invitees
        drawn by the selection policy (== the legacy list when the policy is
        uniform and pacing is off)."""
        return self.population.invite(int(round_idx), int(k))

    # -- per-upload ----------------------------------------------------------
    def _note_population_report(self, sender: int,
                                n_samples: Optional[float] = None,
                                seconds: Optional[float] = None) -> None:
        """(lock held) An accepted upload for the CURRENT round.

        ``seconds`` is an optional MEASURED duration for the client's work
        (the telemetry plane's remote ``client.train`` span) — when
        present it feeds the registry's ``ema_seconds`` directly, so
        pacing and the async staleness scheduler consume real phase
        breakdowns instead of server-side wall-clock guesses."""
        self.population.note_report(
            int(sender), round_idx=int(self.args.round_idx),
            n_samples=None if n_samples is None else int(n_samples),
            seconds=None if seconds is None else float(seconds),
        )

    # -- RoundTimeoutMixin hook overrides ------------------------------------
    def _note_rejected_late(self, sender) -> None:
        """A stale/late upload was dropped by the round-tag policy."""
        self.population.note_rejected_late(int(sender))

    def _note_population_rejoin(self, sender) -> None:
        """A crashed client rejoined mid-run (epoch change)."""
        self.population.note_rejoin(int(sender))

    def _note_round_closing(self, reason: str, got) -> None:
        """The round is about to finalize: settle population accounting and
        emit the round's ``cohort_stats`` record."""
        self.population.close_round(reason=reason)

    # -- round close ---------------------------------------------------------
    def _close_round_if_complete(self) -> bool:
        """(lock held, upload already recorded) Close the round if its
        completion condition holds; returns True when it closed.

        Pacing off: the reference wait-for-all condition, bit-identical
        round flow.  Pacing on: close at quorum — outstanding invitees
        become stragglers, and because a straggler's late upload is now
        possible, untagged arrivals flip to droppable exactly as after a
        timeout close (``_had_timeout_close``)."""
        if not self.population.pacer.enabled:
            if not self.aggregator.check_whether_all_receive():
                return False
            self._cancel_round_timer()
            self._note_round_closing("complete", None)
            self._finalize_safely(None)
            return True
        got = self.aggregator.received_indices()
        if len(got) < self.population.quorum:
            return False
        if len(got) < len(self.client_id_list_in_this_round):
            self._had_timeout_close = True
        self._cancel_round_timer()
        reason = "quorum" if len(got) < len(self.client_id_list_in_this_round) else "complete"
        self._note_round_closing(reason, got)
        self._finalize_safely(self.aggregator.consume_received(got))
        return True
