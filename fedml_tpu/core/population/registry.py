"""Client registry: the server-side view of the fleet.

Array-backed (NOT a dict of objects) so the same registry that tracks three
cross-silo silos scales to the XLA Parrot simulator's 10^5-10^6 virtual
clients: every counter is a NumPy column indexed by registry position, and
the bulk update paths (:meth:`note_reports`, :meth:`note_failures`) are one
vectorized op per round.  Per-client runtime prediction reuses
:class:`~fedml_tpu.core.schedule.runtime_estimate.RuntimeEstimator`
(``uniform_devices=False`` — one linear model per client) fed from observed
report latencies; fleet-level reliability context comes from PR 1's
``comm_stats`` snapshot via :meth:`absorb_comm_stats`.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..schedule.runtime_estimate import RuntimeEstimator


class ClientRegistry:
    """Per-client metadata columns, keyed by client id.

    ``client_ids`` is the fleet's id space (``arange(N)`` for simulators,
    ``[1..N]`` for the message-plane servers).  Ids need not be contiguous;
    lookups go through a position map with an identity fast path.
    """

    def __init__(self, client_ids: Sequence[int],
                 num_samples: Optional[Sequence[int]] = None):
        self.ids = np.asarray(list(client_ids), dtype=np.int64)
        if self.ids.ndim != 1 or self.ids.size == 0:
            raise ValueError("client_ids must be a non-empty 1-D sequence")
        if np.unique(self.ids).size != self.ids.size:
            raise ValueError("client_ids must be unique")
        n = self.ids.size
        self._identity = bool(np.array_equal(self.ids, np.arange(n)))
        self._pos: Optional[Dict[int, int]] = (
            None if self._identity else {int(c): i for i, c in enumerate(self.ids)}
        )
        self.num_samples = (
            np.zeros(n, np.int64) if num_samples is None
            else np.asarray(list(num_samples), np.int64)
        )
        if self.num_samples.shape != (n,):
            raise ValueError("num_samples must align with client_ids")
        self.invites = np.zeros(n, np.int64)
        self.reports = np.zeros(n, np.int64)
        self.failures = np.zeros(n, np.int64)       # invited, never reported
        self.rejected_late = np.zeros(n, np.int64)  # reported after round close
        self.rejoins = np.zeros(n, np.int64)        # mid-run crash-and-rejoin
        self.last_seen_round = np.full(n, -1, np.int64)
        # EMA of observed round-trip seconds (0 until first report)
        self.ema_seconds = np.zeros(n, np.float64)
        self._has_obs = np.zeros(n, bool)
        self._blocked = np.zeros(n, bool)
        self._ema_alpha = 0.3
        # per-client linear runtime model t ~ a*n_samples + b (the
        # core/schedule machinery, one model per client instead of per device)
        self.estimator = RuntimeEstimator(num_devices=n, uniform_devices=False)
        self.comm_stats: Dict[str, int] = {}

    def __len__(self) -> int:
        return int(self.ids.size)

    # -- id <-> position ----------------------------------------------------
    def positions(self, client_ids) -> np.ndarray:
        arr = np.asarray(client_ids, np.int64).reshape(-1)
        if self._identity:
            return arr
        assert self._pos is not None
        return np.fromiter((self._pos[int(c)] for c in arr), np.int64, arr.size)

    # -- eligibility --------------------------------------------------------
    def blocklist(self, client_ids) -> None:
        self._blocked[self.positions(client_ids)] = True

    def unblocklist(self, client_ids) -> None:
        self._blocked[self.positions(client_ids)] = False

    def is_blocklisted(self, client_id: int) -> bool:
        return bool(self._blocked[self.positions([client_id])[0]])

    def eligible_ids(self) -> np.ndarray:
        """Ids a policy may draw from (registry order, blocklist excluded)."""
        return self.ids[~self._blocked]

    def eligible_count(self) -> int:
        return int((~self._blocked).sum())

    # -- per-round accounting (vectorized) ----------------------------------
    def note_invited(self, client_ids, round_idx: int) -> None:
        pos = self.positions(client_ids)
        self.invites[pos] += 1

    def note_reports(self, client_ids, round_idx: int,
                     seconds: Optional[float] = None) -> None:
        """Bulk report mark for a whole cohort (the simulator path)."""
        pos = self.positions(client_ids)
        self.reports[pos] += 1
        self.last_seen_round[pos] = int(round_idx)
        if seconds is not None:
            self._observe_seconds(pos, float(seconds))

    def note_report(self, client_id: int, round_idx: int,
                    n_samples: Optional[int] = None,
                    seconds: Optional[float] = None) -> None:
        """Single-client report (the message-plane server path): updates the
        counters, the latency EMA, and the per-client runtime model."""
        pos = int(self.positions([client_id])[0])
        self.reports[pos] += 1
        self.last_seen_round[pos] = int(round_idx)
        if n_samples is not None:
            self.num_samples[pos] = int(n_samples)
        if seconds is not None:
            self._observe_seconds(np.asarray([pos]), float(seconds))
            if n_samples:
                self.estimator.record(pos, int(n_samples), float(seconds))

    def _observe_seconds(self, pos: np.ndarray, seconds: float) -> None:
        a = self._ema_alpha
        fresh = ~self._has_obs[pos]
        ema = self.ema_seconds[pos]
        self.ema_seconds[pos] = np.where(fresh, seconds, (1 - a) * ema + a * seconds)
        self._has_obs[pos] = True

    def note_failures(self, client_ids, round_idx: int) -> None:
        """Invited-but-missing at round close (vectorized)."""
        pos = self.positions(client_ids)
        self.failures[pos] += 1

    def note_rejected_late(self, client_id: int) -> None:
        self.rejected_late[self.positions([client_id])[0]] += 1

    def note_rejoin(self, client_id: int) -> None:
        """A crashed client came back (PR 1's epoch-change rejoin): it stays
        in / re-enters the eligible pool via its registry entry."""
        self.rejoins[self.positions([client_id])[0]] += 1

    def absorb_comm_stats(self, snapshot: Dict[str, int]) -> None:
        """Fold a transport-layer ``comm_stats`` snapshot (PR 1) into the
        registry's fleet-level reliability context."""
        for k, v in dict(snapshot).items():
            self.comm_stats[k] = self.comm_stats.get(k, 0) + int(v)

    # -- derived signals -----------------------------------------------------
    def speed_scores(self) -> np.ndarray:
        """Per-client observed seconds (lower = faster); clients never seen
        get the fleet median so they sort into the middle stratum instead of
        an artificial extreme."""
        scores = self.ema_seconds.copy()
        if self._has_obs.any():
            scores[~self._has_obs] = float(np.median(scores[self._has_obs]))
        return scores

    def predicted_seconds(self, client_id: int, n_samples: int) -> Optional[float]:
        pos = int(self.positions([client_id])[0])
        return self.estimator.predict(pos, int(n_samples))

    def record(self, client_id: int) -> Dict[str, Any]:
        """One client's row as a plain dict (debug / test surface)."""
        pos = int(self.positions([client_id])[0])
        return {
            "client_id": int(self.ids[pos]),
            "num_samples": int(self.num_samples[pos]),
            "invites": int(self.invites[pos]),
            "reports": int(self.reports[pos]),
            "failures": int(self.failures[pos]),
            "rejected_late": int(self.rejected_late[pos]),
            "rejoins": int(self.rejoins[pos]),
            "last_seen_round": int(self.last_seen_round[pos]),
            "ema_seconds": float(self.ema_seconds[pos]),
            "blocklisted": bool(self._blocked[pos]),
        }

    # -- crash-recovery persistence (core/checkpoint.ServerRecoveryMixin) ----
    # The runtime-prediction model (``estimator``) is deliberately NOT part of
    # the persisted state: its per-client observation lists are advisory (they
    # only shape stratified/importance selection) and refit within a few
    # rounds of fresh observations after a server restart.
    _STATE_COLUMNS = ("num_samples", "invites", "reports", "failures",
                      "rejected_late", "rejoins", "last_seen_round",
                      "ema_seconds")

    def state_columns(self) -> Dict[str, np.ndarray]:
        """The registry's durable columns as a flat dict of arrays — msgpack-
        serializable as-is, so it rides inside the server state snapshot."""
        cols = {k: np.asarray(getattr(self, k)).copy() for k in self._STATE_COLUMNS}
        cols["ids"] = self.ids.copy()
        cols["has_obs"] = self._has_obs.copy()
        cols["blocked"] = self._blocked.copy()
        return cols

    def load_state_columns(self, cols: Dict[str, Any]) -> None:
        """Inverse of :meth:`state_columns`; the id space must be unchanged
        (a restarted server serves the same fleet it crashed in)."""
        ids = np.asarray(cols["ids"], np.int64).reshape(-1)
        if not np.array_equal(ids, self.ids):
            raise ValueError(
                "registry snapshot id space does not match this fleet "
                f"(snapshot has {ids.size} ids, registry has {self.ids.size})")
        # np.array (not asarray): deserialized columns may be read-only
        # frombuffer views and the registry mutates these in place
        for k in self._STATE_COLUMNS:
            current = getattr(self, k)
            setattr(self, k, np.array(cols[k], current.dtype).reshape(current.shape))
        self._has_obs = np.array(cols["has_obs"], bool).reshape(self._has_obs.shape)
        self._blocked = np.array(cols["blocked"], bool).reshape(self._blocked.shape)

    def snapshot(self) -> Dict[str, int]:
        """Fleet-level totals for the ``cohort_stats`` sink record."""
        return {
            "fleet": int(self.ids.size),
            "eligible": self.eligible_count(),
            "invited_total": int(self.invites.sum()),
            "reported_total": int(self.reports.sum()),
            "failures_total": int(self.failures.sum()),
            "rejected_late_total": int(self.rejected_late.sum()),
            "rejoins_total": int(self.rejoins.sum()),
        }
