"""Population subsystem: the server-side fleet view and the per-round
participation decision.

Parts (see ``docs/POPULATION.md``):

* :mod:`.registry` — array-backed per-client metadata (sample counts,
  observed latencies via ``core/schedule``, reliability counters via PR 1's
  ``comm_stats``, last-seen round, blocklist);
* :mod:`.policies` — seed-deterministic selection policies behind one
  ``SelectionPolicy`` interface (uniform with bit-exact legacy parity,
  stratified-by-speed, importance);
* :mod:`.pacer` — over-commit + deadline-quorum arithmetic;
* :mod:`.pacing` — the mixin wiring the pacer into the message-plane
  server managers on top of ``RoundTimeoutMixin``;
* :mod:`.manager` — the facade (``PopulationManager``) that owns the
  accounting and emits per-round ``cohort_stats`` through ``core/mlops``;
* :mod:`.stacked` — the vectorized whole-run selection path for
  10^5-10^6 virtual clients.
"""

from .manager import PopulationManager
from .pacer import RoundPacer
from .pacing import PopulationPacingMixin
from .policies import (
    ImportancePolicy,
    SelectionPolicy,
    StratifiedBySpeedPolicy,
    UniformPolicy,
    make_policy,
    uniform_id_choice,
)
from .registry import ClientRegistry
from .stacked import stacked_cohorts

__all__ = [
    "ClientRegistry",
    "SelectionPolicy",
    "UniformPolicy",
    "StratifiedBySpeedPolicy",
    "ImportancePolicy",
    "make_policy",
    "uniform_id_choice",
    "RoundPacer",
    "PopulationManager",
    "PopulationPacingMixin",
    "stacked_cohorts",
]
