"""Seed-deterministic cohort selection policies behind one interface.

Every policy draws from a round-seeded LOCAL generator — never the global
NumPy RNG — so the schedule is a pure function of ``(round_idx, registry
state)`` and identical across backends and reruns.  Two legacy uniform
schedules exist in the tree and both are preserved bit-identically:

* ``mt19937`` — the simulator schedule (``core/sampling.py``'s historical
  ``np.random.seed(round_idx)`` + ``np.random.choice``), now a
  ``RandomState(round_idx)`` draw;
* ``pcg64`` — the cross-silo schedule
  (``np.random.default_rng(round_idx).choice(ids, k)``).

Non-uniform policies (stratified-by-speed, importance — the FedML Parrot
heterogeneity-aware direction, arxiv 2303.01778) consume registry signals
and return a sorted cohort; they are new surfaces with no parity constraint.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .registry import ClientRegistry


def uniform_id_choice(round_idx: int, client_ids: Sequence[int], k: int) -> List[int]:
    """The cross-silo legacy uniform schedule (``pcg64`` style), kept as a
    free function so ``cross_silo.server.FedMLAggregator.client_selection``
    and the policy object share one implementation."""
    ids = list(client_ids)
    if k >= len(ids):
        return ids
    rng = np.random.default_rng(round_idx)
    return rng.choice(ids, k, replace=False).tolist()


def _largest_remainder(sizes: Sequence[int], k: int) -> List[int]:
    """Apportion ``k`` picks across strata proportionally to ``sizes``
    (largest-remainder method, deterministic tie-break by stratum index)."""
    sizes = np.asarray(sizes, np.float64)
    total = float(sizes.sum())
    exact = sizes * (k / total)
    quotas = np.floor(exact).astype(np.int64)
    short = int(k - quotas.sum())
    if short > 0:
        frac = exact - quotas
        order = np.lexsort((np.arange(frac.size), -frac))
        quotas[order[:short]] += 1
    # a stratum cannot owe more picks than it has members; push overflow to
    # the next stratum with headroom (deterministic left-to-right sweep)
    sizes_i = sizes.astype(np.int64)
    for i in range(quotas.size):
        over = int(quotas[i] - sizes_i[i])
        if over > 0:
            quotas[i] = sizes_i[i]
            for j in range(quotas.size):
                if j == i:
                    continue
                room = int(sizes_i[j] - quotas[j])
                if room <= 0:
                    continue
                take = min(room, over)
                quotas[j] += take
                over -= take
                if over == 0:
                    break
    return [int(q) for q in quotas]


class SelectionPolicy:
    """One cohort decision per round: ``select(round_idx, k)`` returns the
    client IDS (not registry positions) of the round's cohort, drawn only
    from the registry's eligible (non-blocklisted) pool, deterministically
    in ``round_idx``.  ``last_strata_sizes`` is set by policies that
    stratify, for the ``cohort_stats`` record."""

    name = "base"

    def __init__(self, registry: ClientRegistry):
        self.registry = registry
        self.last_strata_sizes: Optional[List[int]] = None

    def select(self, round_idx: int, k: int) -> np.ndarray:
        raise NotImplementedError


class UniformPolicy(SelectionPolicy):
    """Uniform without replacement, reproducing the exact legacy schedule of
    its backend family (``rng_style``): with no blocklist, output is
    bit-identical to pre-population behavior — the parity tests rely on it."""

    name = "uniform"

    def __init__(self, registry: ClientRegistry, rng_style: str = "mt19937"):
        super().__init__(registry)
        if rng_style not in ("mt19937", "pcg64"):
            raise ValueError(f"unknown rng_style {rng_style!r}")
        self.rng_style = rng_style

    def select(self, round_idx: int, k: int) -> np.ndarray:
        eligible = self.registry.eligible_ids()
        if k >= eligible.size:
            return eligible.copy()
        if self.rng_style == "pcg64":
            picked = uniform_id_choice(round_idx, eligible.tolist(), k)
            return np.asarray(picked, np.int64)
        rs = np.random.RandomState(round_idx)
        return eligible[rs.choice(eligible.size, k, replace=False)]


class StratifiedBySpeedPolicy(SelectionPolicy):
    """Sort the eligible pool by observed speed (registry latency EMA,
    unseen clients at the fleet median), cut into ``num_strata`` contiguous
    strata, and draw a proportional quota from each — so one cohort spans
    the speed spectrum instead of over-drawing whichever tail uniform
    sampling happens to hit (the Parrot heterogeneity argument)."""

    name = "stratified"

    def __init__(self, registry: ClientRegistry, num_strata: int = 4):
        super().__init__(registry)
        self.num_strata = max(1, int(num_strata))

    def select(self, round_idx: int, k: int) -> np.ndarray:
        eligible = self.registry.eligible_ids()
        if k >= eligible.size:
            self.last_strata_sizes = [int(eligible.size)]
            return eligible.copy()
        scores = self.registry.speed_scores()[self.registry.positions(eligible)]
        order = np.argsort(scores, kind="stable")  # fastest first
        strata = [s for s in np.array_split(eligible[order], self.num_strata)
                  if s.size]
        quotas = _largest_remainder([s.size for s in strata], k)
        rs = np.random.RandomState(round_idx)
        picks = []
        for stratum, q in zip(strata, quotas):
            if q >= stratum.size:
                picks.append(stratum)
            elif q > 0:
                picks.append(stratum[rs.choice(stratum.size, q, replace=False)])
        self.last_strata_sizes = [int(s.size) for s in strata]
        return np.sort(np.concatenate(picks))


class ImportancePolicy(SelectionPolicy):
    """Weighted sampling without replacement via Gumbel-top-k: weight
    ``(num_samples + 1)^alpha`` (data-proportional, Parrot-style importance)
    times an optional staleness boost that nudges long-unseen clients back
    into rotation.  One ``argpartition`` — no per-client Python loop."""

    name = "importance"

    def __init__(self, registry: ClientRegistry, alpha: float = 1.0,
                 staleness_weight: float = 0.0):
        super().__init__(registry)
        self.alpha = float(alpha)
        self.staleness_weight = float(staleness_weight)

    def select(self, round_idx: int, k: int) -> np.ndarray:
        eligible = self.registry.eligible_ids()
        if k >= eligible.size:
            return eligible.copy()
        pos = self.registry.positions(eligible)
        w = (self.registry.num_samples[pos].astype(np.float64) + 1.0) ** self.alpha
        if self.staleness_weight > 0.0:
            last = self.registry.last_seen_round[pos]
            stale = np.where(last < 0, round_idx + 1, round_idx - last)
            w = w * (1.0 + self.staleness_weight * stale / (round_idx + 1.0))
        rs = np.random.RandomState(round_idx)
        keys = np.log(w) + rs.gumbel(size=eligible.size)
        sel = np.argpartition(-keys, k - 1)[:k]
        return np.sort(eligible[sel])


def make_policy(name: str, registry: ClientRegistry, *,
                rng_style: str = "mt19937", num_strata: int = 4,
                importance_alpha: float = 1.0,
                importance_staleness: float = 0.0) -> SelectionPolicy:
    name = str(name or "uniform").lower()
    if name == "uniform":
        return UniformPolicy(registry, rng_style=rng_style)
    if name == "stratified":
        return StratifiedBySpeedPolicy(registry, num_strata=num_strata)
    if name == "importance":
        return ImportancePolicy(registry, alpha=importance_alpha,
                                staleness_weight=importance_staleness)
    raise ValueError(
        f"unknown selection_policy {name!r} (expected uniform|stratified|importance)"
    )
