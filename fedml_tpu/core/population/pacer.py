"""Round pacing: over-commit + deadline-quorum arithmetic.

The Smart-NIC FL-server study (arxiv 2307.06561) observation: at fleet
scale the server cannot afford to wait for the slowest invitee, so it
invites MORE clients than it needs (``ceil(K * overcommit)``) and closes
the round as soon as the target ``K`` (the quorum) have reported — the
rest become stragglers whose late uploads are rejected and counted.

This module is pure arithmetic; the deadline itself is the existing
``RoundTimeoutMixin`` round timer (``round_timeout_s``), NOT a second
timer — the pacer only decides how many to invite and when "enough"
reports have arrived.  Knobs (validated in ``arguments.py``):

* ``pacing_overcommit`` (float >= 1.0, default 1.0) — invite multiplier.
* ``pacing_quorum`` (int >= 0, default 0) — explicit quorum; 0 means the
  target cohort size ``K`` (``client_num_per_round``).

Both at their defaults means pacing is OFF and every round keeps the
reference wait-for-all semantics (bounded only by ``round_timeout_s``
when that is set).
"""

from __future__ import annotations

import math


class RoundPacer:
    def __init__(self, overcommit: float = 1.0, quorum: int = 0):
        self.overcommit = float(overcommit or 1.0)
        self.quorum = int(quorum or 0)
        if self.overcommit < 1.0:
            raise ValueError(
                f"pacing_overcommit must be >= 1.0 (got {self.overcommit})"
            )
        if self.quorum < 0:
            raise ValueError(f"pacing_quorum must be >= 0 (got {self.quorum})")

    @classmethod
    def from_args(cls, args) -> "RoundPacer":
        return cls(
            overcommit=float(getattr(args, "pacing_overcommit", 1.0) or 1.0),
            quorum=int(getattr(args, "pacing_quorum", 0) or 0),
        )

    @property
    def enabled(self) -> bool:
        return self.overcommit > 1.0 or self.quorum > 0

    def invite_count(self, k: int) -> int:
        """``ceil(K * overcommit)`` with a float-noise guard so 1.1 * 10
        does not ceil to 12."""
        return int(math.ceil(int(k) * self.overcommit - 1e-9))

    def quorum_for(self, k: int, invited: int) -> int:
        """Reports needed to close the round: the explicit quorum (or the
        target ``K``), never more than were actually invited, never < 1."""
        q = self.quorum if self.quorum > 0 else int(k)
        return max(1, min(q, int(invited)))

    def __repr__(self) -> str:  # pragma: no cover
        return f"RoundPacer(overcommit={self.overcommit}, quorum={self.quorum})"
