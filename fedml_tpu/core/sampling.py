"""Per-round client sampling (reference ``fedavg_api.py:125-133`` parity).

Seeded by round index so every simulator backend (sp / XLA / distributed)
draws the SAME client schedule for a given round — the property the reference
relies on for reproducibility, kept in one place here.
"""

from __future__ import annotations

import numpy as np


def client_sampling(round_idx: int, client_num_in_total: int, client_num_per_round: int) -> np.ndarray:
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total)
    np.random.seed(round_idx)
    return np.random.choice(range(client_num_in_total), client_num_per_round, replace=False)
