"""Per-round client sampling (reference ``fedavg_api.py:125-133`` parity).

Seeded by round index so every simulator backend (sp / XLA / distributed)
draws the SAME client schedule for a given round — the property the reference
relies on for reproducibility, kept in one place here.

The draw comes from a LOCAL ``np.random.RandomState(round_idx)``, never by
seeding the process-global NumPy RNG: the historical
``np.random.seed(round_idx)`` here silently reset every other consumer of
the global stream each round.  ``RandomState(s).choice(n, k, replace=False)``
is bit-identical to the legacy ``np.random.seed(s)`` +
``np.random.choice(range(n), k, replace=False)`` (same MT19937 seeding, same
permutation-based draw), so existing schedules are unchanged — the parity
tests in ``tests/test_population.py`` pin this.  ``tools/lint_rng.py``
machine-enforces the no-global-RNG rule tree-wide.

This remains the ``uniform`` selection policy's implementation
(``core/population/policies.py``); richer policies live there.
"""

from __future__ import annotations

import numpy as np


def client_sampling(round_idx: int, client_num_in_total: int, client_num_per_round: int) -> np.ndarray:
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total)
    rs = np.random.RandomState(round_idx)
    return rs.choice(client_num_in_total, client_num_per_round, replace=False)
