"""Per-device linear runtime model fitted from observed training times.

Role of reference ``core/schedule/runtime_estimate.py`` (``t_sample_fit``):
model the time a device takes to train a client as ``t ≈ a·n_samples + b``
and report the relative fit error so callers can fall back to sample-count
scheduling when the model is unreliable.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np


def linear_fit(x, y) -> Tuple[float, float, float]:
    """Least-squares ``y ≈ a·x + b``. Returns (a, b, mean relative error)."""
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    if len(x) < 2 or np.ptp(x) == 0:
        a = 0.0
        b = float(y.mean()) if len(y) else 0.0
    else:
        a, b = np.polyfit(x, y, 1)
    pred = a * x + b
    err = float(np.mean(np.abs(pred - y) / np.maximum(y, 1e-12))) if len(y) else 1.0
    return float(a), float(b), err


class RuntimeEstimator:
    """Accumulates (device, n_samples, seconds) observations and predicts
    per-client runtimes per device.

    ``uniform_devices=True`` pools all devices into one model — the right
    default on TPU where mesh slots are identical chips (unlike the
    reference's heterogeneous-GPU fleet)."""

    def __init__(self, num_devices: int, uniform_devices: bool = True):
        self.num_devices = num_devices
        self.uniform_devices = uniform_devices
        self._obs: Dict[int, List[Tuple[float, float]]] = defaultdict(list)
        self._fits: Dict[int, Tuple[float, float, float]] = {}
        self._dirty = True

    def record(self, device_id: int, n_samples: int, seconds: float) -> None:
        key = 0 if self.uniform_devices else int(device_id)
        self._obs[key].append((float(n_samples), float(seconds)))
        self._dirty = True

    def _fit(self) -> None:
        self._fits = {}
        for key, obs in self._obs.items():
            xs, ys = zip(*obs)
            self._fits[key] = linear_fit(xs, ys)
        self._dirty = False

    def fit_error(self, device_id: int = 0) -> float:
        if self._dirty:
            self._fit()
        key = 0 if self.uniform_devices else int(device_id)
        return self._fits.get(key, (0.0, 0.0, 1.0))[2]

    def predict(self, device_id: int, n_samples: int) -> Optional[float]:
        """Predicted seconds for a client of ``n_samples`` on ``device_id``;
        None until at least one observation exists for that device."""
        if self._dirty:
            self._fit()
        key = 0 if self.uniform_devices else int(device_id)
        if key not in self._fits:
            return None
        a, b, _ = self._fits[key]
        return max(a * n_samples + b, 0.0)

    def predict_marginal(self, device_id: int, n_samples: int) -> Optional[float]:
        """Marginal (size-dependent) seconds ``a·n`` WITHOUT the intercept.

        The intercept absorbs per-observation fixed overhead (dispatch, eval,
        collectives) that is paid once per round, not once per client — so
        relative per-client costs for scheduling must exclude it, or every
        client costs ~b and load balancing degenerates to count-balancing.
        Returns None when no model exists or the fitted slope is non-positive
        (degenerate fit — caller should fall back to sample counts)."""
        if self._dirty:
            self._fit()
        key = 0 if self.uniform_devices else int(device_id)
        if key not in self._fits:
            return None
        a, _, _ = self._fits[key]
        if a <= 0.0:
            return None
        return a * n_samples

    def has_model(self) -> bool:
        return bool(self._obs)
