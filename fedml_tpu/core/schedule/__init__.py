"""Heterogeneity-aware client→device scheduling.

Parity with reference ``core/schedule/`` (``seq_train_scheduler.py:9-50``
``SeqTrainScheduler.DP_schedule``; ``runtime_estimate.py:16`` ``t_sample_fit``):
assign per-client workloads to compute slots so the slowest slot (makespan)
is minimized, using a fitted linear per-sample runtime model.

TPU-first differences: the schedule is *static per round* — it decides the
layout of the ``lax.scan``-over-clients inside the compiled round program
(simulation/xla/fed_sim.py), so the output is a dense [n_dev, per_dev]
client-id matrix with a validity mask rather than ragged Python lists.
"""

from .runtime_estimate import RuntimeEstimator, linear_fit
from .seq_train_scheduler import SeqTrainScheduler

__all__ = ["RuntimeEstimator", "linear_fit", "SeqTrainScheduler"]
