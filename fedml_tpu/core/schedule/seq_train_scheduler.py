"""Makespan-minimizing assignment of sampled clients to mesh slots.

Role of reference ``core/schedule/seq_train_scheduler.py`` (``DP_schedule``):
each device trains its assigned clients *sequentially*, so the round takes as
long as the heaviest device; pick the assignment minimizing that makespan.

Implementation: LPT (longest-processing-time-first) greedy — 4/3-optimal for
identical machines — plus an exchange-refinement pass that moves/swaps
clients between the heaviest and lightest slots while it improves makespan.
Costs come from a ``RuntimeEstimator`` when one has observations, else raw
sample counts (equivalent up to the fitted constants).

Output shape is TPU-static: a dense [n_dev, per_dev] id matrix + mask, the
layout consumed by the scan-over-clients in ``simulation/xla/fed_sim.py``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .runtime_estimate import RuntimeEstimator


class SeqTrainScheduler:
    def __init__(
        self,
        num_devices: int,
        estimator: Optional[RuntimeEstimator] = None,
        refine_iters: int = 64,
    ):
        self.num_devices = int(num_devices)
        self.estimator = estimator
        self.refine_iters = int(refine_iters)

    # -- cost model ---------------------------------------------------
    def _costs(self, client_ids: Sequence[int], sizes: Sequence[int]) -> np.ndarray:
        """Cost of each client in arbitrary-but-consistent units.

        Uses the pooled runtime model when one exists; TPU mesh slots are
        identical chips, so a single model covers all devices.  Per-device
        (heterogeneous) estimators would need a full [n_dev, n_clients] cost
        matrix and a different assignment algorithm — fall back to sample
        counts for those rather than mispredicting with device 0's fit."""
        est = self.estimator
        if est is not None and est.has_model() and est.uniform_devices:
            # Marginal cost only: the fitted intercept is whole-round fixed
            # overhead (observations are round wall times), identical across
            # assignments — charging it per client would swamp a·n and reduce
            # LPT to count-balancing.
            costs = [est.predict_marginal(0, int(s)) for s in sizes]
            if all(c is not None for c in costs):
                return np.asarray(costs, np.float64)
        return np.asarray(sizes, np.float64)

    # -- assignment ---------------------------------------------------
    def schedule(
        self, client_ids: Sequence[int], sizes: Sequence[int]
    ) -> Tuple[np.ndarray, np.ndarray, float]:
        """Returns (ids [n_dev, per_dev], mask [n_dev, per_dev], makespan).

        ``per_dev = ceil(len(clients)/n_dev)`` — every slot gets the same
        static scan length; mask==0 rows are weight-0 padding clients."""
        client_ids = np.asarray(client_ids, np.int64)
        n = len(client_ids)
        n_dev = self.num_devices
        per_dev = max(1, -(-n // n_dev))
        costs = self._costs(client_ids, sizes)

        buckets: List[List[int]] = [[] for _ in range(n_dev)]
        loads = np.zeros(n_dev)
        # LPT: heaviest client first onto the lightest non-full slot
        for k in np.argsort(-costs):
            cap_penalty = np.where([len(b) >= per_dev for b in buckets], np.inf, 0.0)
            d = int(np.argmin(loads + cap_penalty))
            buckets[d].append(int(k))
            loads[d] += costs[k]

        self._refine(buckets, loads, costs, per_dev)

        ids = np.zeros((n_dev, per_dev), np.int32)
        mask = np.zeros((n_dev, per_dev), np.int32)
        for d, b in enumerate(buckets):
            for j, k in enumerate(b):
                ids[d, j] = client_ids[k]
                mask[d, j] = 1
        return ids, mask, float(loads.max())

    def _refine(self, buckets, loads, costs, per_dev) -> None:
        """Move/swap between argmax and argmin slots while makespan drops."""
        for _ in range(self.refine_iters):
            hi = int(np.argmax(loads))
            lo = int(np.argmin(loads))
            if hi == lo or not buckets[hi]:
                return
            gap = loads[hi] - loads[lo]
            improved = False
            # best single move hi -> lo (if lo has a free slot)
            if len(buckets[lo]) < per_dev:
                k = min(buckets[hi], key=lambda k: abs(costs[k] - gap / 2))
                if costs[k] < gap:
                    buckets[hi].remove(k)
                    buckets[lo].append(k)
                    loads[hi] -= costs[k]
                    loads[lo] += costs[k]
                    improved = True
            if not improved and buckets[lo]:
                # best swap: transfer delta = c_hi - c_lo in (0, gap)
                best = None
                for a in buckets[hi]:
                    for b in buckets[lo]:
                        delta = costs[a] - costs[b]
                        if 0 < delta < gap and (best is None or abs(delta - gap / 2) < abs(best[2] - gap / 2)):
                            best = (a, b, delta)
                if best is not None:
                    a, b, delta = best
                    buckets[hi].remove(a)
                    buckets[lo].remove(b)
                    buckets[hi].append(b)
                    buckets[lo].append(a)
                    loads[hi] -= delta
                    loads[lo] += delta
                    improved = True
            if not improved:
                return
