"""Hierarchy wire vocabulary: message types, param keys, fused deltas.

The tier speaks four message types below the cross-silo application
vocabulary (like ``comm_ack``, they are invisible to flat deployments):

* ``hier_upload`` — leaf -> edge: one client update (the same payload a
  flat client would send the server, addressed to its edge instead).
* ``hier_counts`` — edge -> parent: the count-then-reduce flush's phase
  A.  Carries the block's ``(total_weight, n_clients)`` plus the edge's
  codec offer with honest byte estimates; ``mean`` folds cannot start
  until the GLOBAL total is known, so counts flow up before any float
  math happens.
* ``hier_total`` — parent -> edge: phase B release.  Carries the global
  total weight and the negotiated per-link codec; mids relay it down.
* ``hier_partial`` — edge -> parent: ONE fused
  ``(partial_sum, total_weight, n_clients, leaf_epoch)`` delta for the
  whole block, stamped with a deterministic ``forward_id`` that a
  replayed incarnation reuses — the parent dedups on it, which is what
  makes edge-kill replay exactly-once.

Transport-level reliability (msg-id ack/dedup/retransmit) rides the
ordinary :class:`~fedml_tpu.core.distributed.comm_manager._ReliableLink`
stamping; the ``forward_id`` here is one layer up — application identity
that survives process death, where a fresh incarnation's msg-id nonce
deliberately does not.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

Pytree = Any

# message types (below the MyMessage application vocabulary)
HIER_UPLOAD = "hier_upload"
HIER_COUNTS = "hier_counts"
HIER_TOTAL = "hier_total"
HIER_PARTIAL = "hier_partial"

# param keys
KEY_ROUND = "hier_round"
KEY_LEAF = "hier_leaf"
KEY_N_SAMPLES = "hier_n_samples"
KEY_EPOCH = "hier_epoch"
KEY_EDGE = "hier_edge"
KEY_FORWARD_ID = "hier_forward_id"
KEY_PAYLOAD = "hier_payload"
KEY_TOTAL_WEIGHT = "hier_total_weight"
KEY_N_CLIENTS = "hier_n_clients"
KEY_CODEC = "hier_codec"
KEY_OFFERS = "hier_offers"

# fused-delta wire marker (a self-describing dict, like the compression
# payloads, so every comm backend can carry it opaquely)
PARTIAL_MARKER = "__fedml_partial_delta__"


def forward_id(edge_id: int, round_idx: int) -> str:
    """The deterministic application-level identity of an edge's fused
    forward for one round: a function of (edge, round) alone, so a
    replayed incarnation re-forwards under the SAME id and the parent's
    dedup makes the replay exactly-once."""
    return f"e{int(edge_id)}:r{int(round_idx)}"


@dataclass
class PartialDelta:
    """One block's fused contribution: the partial fold plus the
    accounting the parent needs to close its own books."""

    partial_sum: Pytree
    total_weight: float
    n_clients: int
    leaf_epoch: int = 0

    def to_wire(self) -> Dict[str, Any]:
        return {
            PARTIAL_MARKER: 1,
            "partial_sum": self.partial_sum,
            "total_weight": float(self.total_weight),
            "n_clients": int(self.n_clients),
            "leaf_epoch": int(self.leaf_epoch),
        }

    @staticmethod
    def is_wire(obj: Any) -> bool:
        return isinstance(obj, dict) and PARTIAL_MARKER in obj

    @staticmethod
    def from_wire(payload: Dict[str, Any]) -> "PartialDelta":
        if not PartialDelta.is_wire(payload):
            raise ValueError("not a partial-delta payload")
        return PartialDelta(
            partial_sum=payload["partial_sum"],
            total_weight=float(payload["total_weight"]),
            n_clients=int(payload["n_clients"]),
            leaf_epoch=int(payload.get("leaf_epoch", 0)),
        )
