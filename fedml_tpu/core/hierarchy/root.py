"""Root-side fan-in: totals, codec negotiation, exactly-once combine.

:class:`HierarchyRoot` is deliberately NOT a comm manager — it attaches
to any existing :class:`~fedml_tpu.core.distributed.comm_manager
.FedMLCommManager` (the cross-silo server, a bare test manager) by
registering the two upward hierarchy handlers, so the flat server keeps
its whole vocabulary and grows the tree's on the side:

* ``hier_counts`` — stage each top-level child's ``(weight, clients,
  codec offer)``.  When the cohort is complete, total the weights in
  child-id order (one deterministic float sum — the same total every
  deployment of the plan computes), negotiate a per-link codec from each
  child's offer, and send ``hier_total`` down.  A child's counts arriving
  AFTER the total exists (a replayed edge incarnation) get an idempotent
  ``hier_total`` re-reply — that re-reply is what drives the replayed
  edge to re-forward.
* ``hier_partial`` — dedup on the deterministic forward id (a replayed
  edge re-forwards under the same id; the duplicate is counted and
  dropped — exactly-once accounting), absorb the grafted leaf telemetry
  into the merger, stage the delta, and when every child has landed,
  combine in child-id order and close the round through ``on_round``.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import obs
from ..aggregate import FedMLAggOperator
from ..compression import maybe_decompress_update
from ..distributed.communication.message import Message
from . import protocol
from .plan import HierarchyPlan
from .protocol import PartialDelta

logger = logging.getLogger(__name__)

Pytree = Any


class HierarchyRoot:
    """The tree's apex: counts -> total -> combine, attached to a manager."""

    def __init__(self, manager, plan: HierarchyPlan,
                 child_ranks: Dict[int, int], mode: Optional[str] = None,
                 plane: Any = None, merger: Any = None,
                 on_round: Optional[Callable[[int, Pytree, float, int],
                                             None]] = None):
        self.manager = manager
        self.plan = plan
        self.child_ranks = dict(child_ranks)
        self.mode = mode or FedMLAggOperator.agg_mode(manager.args)
        self._plane = plane
        self._plane_checked = plane is not None
        self.merger = merger
        self.on_round = on_round
        self._lock = threading.RLock()
        self._counts: Dict[int, Dict[int, Tuple[float, int, Any]]] = {}
        self._codecs: Dict[int, Dict[int, str]] = {}
        self._totals: Dict[int, float] = {}
        self._seen_fwd: Dict[int, set] = {}
        self._deltas: Dict[int, Dict[int, PartialDelta]] = {}
        self._results: Dict[int, Tuple[Pytree, float, int]] = {}
        self._closed: Dict[int, threading.Event] = {}
        self.dup_forwards = 0
        self.rounds_closed = 0
        # armed from the first edge message of an open round until every
        # open round closes: an edge that counted in but never forwards
        # (killed, wedged, partitioned) surfaces as a health.anomaly
        # instead of an indefinitely-parked wait_round
        self._edge_silence = obs.health_silence("hierarchy.edge_silence")
        manager.register_message_receive_handler(
            protocol.HIER_COUNTS, self._handle_counts)
        manager.register_message_receive_handler(
            protocol.HIER_PARTIAL, self._handle_partial)

    @property
    def plane(self):
        if not self._plane_checked:
            self._plane_checked = True
            if str(getattr(self.manager.args, "agg_plane", "host")
                   or "host") == "compiled":
                from ...parallel.agg_plane import plane_for

                self._plane = plane_for(self.manager.args)
        return self._plane

    def _accepted(self) -> List[str]:
        return [s.strip().lower() for s in str(
            getattr(self.manager.args, "edge_codec_accept", "none") or "none"
        ).split(",") if s.strip()]

    # -- phase A: counts up, total down --------------------------------------
    def _handle_counts(self, msg: Message) -> None:
        r = int(msg.get(protocol.KEY_ROUND))
        child = int(msg.get(protocol.KEY_EDGE))
        self._edge_silence.note()
        with self._lock:
            counts = self._counts.setdefault(r, {})
            counts[child] = (float(msg.get(protocol.KEY_TOTAL_WEIGHT, 0.0)),
                             int(msg.get(protocol.KEY_N_CLIENTS, 0)),
                             msg.get(protocol.KEY_OFFERS))
            total_known = r in self._totals
            complete = len(counts) >= len(self.child_ranks)
        if total_known:
            # a replayed child re-sent counts: idempotent hier_total
            # re-reply so the replacement incarnation can re-forward
            self._send_total(r, only_child=child)
            obs.counter_inc("hierarchy.total_rereplies")
            return
        if complete:
            self._close_counts(r)

    def _close_counts(self, r: int) -> None:
        from .router import negotiate_codec

        accepted = self._accepted()
        with self._lock:
            if r in self._totals:
                return
            counts = self._counts[r]
            # one deterministic float sum in child-id order — every
            # deployment of the plan totals the same operands the same way
            total = float(sum(counts[c][0] for c in sorted(counts)))
            self._totals[r] = total
            self._codecs[r] = {c: negotiate_codec(counts[c][2], accepted)
                               for c in counts}
        self._send_total(r)

    def _send_total(self, r: int, only_child: Optional[int] = None) -> None:
        with self._lock:
            total = self._totals[r]
            codecs = dict(self._codecs.get(r, {}))
        for child, rank in sorted(self.child_ranks.items()):
            if only_child is not None and child != only_child:
                continue
            m = Message(protocol.HIER_TOTAL, self.manager.get_sender_id(),
                        rank)
            m.add_params(protocol.KEY_ROUND, r)
            m.add_params(protocol.KEY_TOTAL_WEIGHT, total)
            m.add_params(protocol.KEY_CODEC, codecs.get(child, "none"))
            self.manager.send_message(m)

    # -- phase B: fused deltas up, combine, close ----------------------------
    def _handle_partial(self, msg: Message) -> None:
        r = int(msg.get(protocol.KEY_ROUND))
        child = int(msg.get(protocol.KEY_EDGE))
        fwd = str(msg.get(protocol.KEY_FORWARD_ID))
        self._edge_silence.note()
        with self._lock:
            seen = self._seen_fwd.setdefault(r, set())
            if fwd in seen:
                # a replayed edge's re-forward: the SAME forward id, so this
                # is the same contribution — drop it.  Exactly-once is this
                # line plus the deterministic id.
                self.dup_forwards += 1
                obs.counter_inc("hierarchy.root_dup_forwards")
                return
            seen.add(fwd)
        wire = dict(msg.get(protocol.KEY_PAYLOAD))
        wire["partial_sum"] = maybe_decompress_update(wire["partial_sum"])
        delta = PartialDelta.from_wire(wire)
        if self.merger is not None:
            try:
                self.merger.absorb(msg)
            except Exception:  # telemetry never raises into the round path
                pass
        with self._lock:
            deltas = self._deltas.setdefault(r, {})
            deltas[child] = delta
            if len(deltas) < len(self.child_ranks):
                return
        self._close_round(r)

    def _close_round(self, r: int) -> None:
        with self._lock:
            if r in self._results:
                return
            deltas = self._deltas[r]
            order = sorted(deltas)
            tree = self.plan.combine([deltas[c].partial_sum for c in order],
                                     self.mode, self.plane)
            weight = float(sum(deltas[c].total_weight for c in order))
            n_clients = int(sum(deltas[c].n_clients for c in order))
            self._results[r] = (tree, weight, n_clients)
            self.rounds_closed += 1
            ev = self._closed.setdefault(r, threading.Event())
            open_rounds = any(rr not in self._results for rr in self._counts)
        obs.counter_inc("hierarchy.rounds_closed")
        if not open_rounds:
            self._edge_silence.idle()
        if self.on_round is not None:
            try:
                self.on_round(r, tree, weight, n_clients)
            except Exception:
                logger.exception("hierarchy on_round callback failed for "
                                 "round %d", r)
        ev.set()

    # -- results -------------------------------------------------------------
    def result(self, r: int) -> Optional[Tuple[Pytree, float, int]]:
        with self._lock:
            return self._results.get(r)

    def wait_round(self, r: int, timeout: Optional[float] = None) -> bool:
        with self._lock:
            ev = self._closed.setdefault(r, threading.Event())
        return ev.wait(timeout)

    def prune_round(self, r: int) -> None:
        with self._lock:
            for d in (self._counts, self._codecs, self._totals,
                      self._seen_fwd, self._deltas, self._results,
                      self._closed):
                d.pop(r, None)
