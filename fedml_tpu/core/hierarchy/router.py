"""Tree wiring: rank layout, node construction, per-link codec choice.

:class:`HierarchyRouter` turns a validated
:class:`~fedml_tpu.core.hierarchy.plan.HierarchyPlan` into a deployed
tree over one comm backend.  The rank layout is deterministic in the
plan alone::

    rank 0                      the root (any existing server manager)
    1 .. E                      leaf-edge aggregators, block order
    E+1 .. E+M                  mids (3-level only), group order
    E+M+1 .. E+M+L              leaf senders, leaf-index order

Mid node ids live in the same namespace as edge ids, offset by the edge
count, so every node's deterministic forward id is globally unique.

Codec negotiation is per parent<->child link: the child OFFERS the
schemes it can encode plus honest byte estimates
(:func:`estimate_scheme_bytes` — measured shapes, the real top-k ``k``,
the real index dtype); the parent picks the cheapest offered scheme it
accepts, preferring its own accept-list order on ties, and always falls
back to ``none``.  Lossy codecs trade the bit-identity contract for
bytes — bit-exact deployments negotiate ``none`` (the default).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..compression import _INT32_MAX, topk_k, wire_bytes
from ..distributed.communication.message import Message
from . import protocol
from .edge import EdgeAggregator
from .plan import HierarchyPlan
from .root import HierarchyRoot

Pytree = Any

#: schemes a hierarchy link may negotiate, in default preference order
LINK_SCHEMES = ("none", "topk", "eftopk", "quantize", "qsgd")


def estimate_scheme_bytes(tree: Pytree, method: str,
                          ratio: float = 0.05) -> int:
    """Honest wire-byte estimate for encoding ``tree`` under ``method``,
    WITHOUT running the codec: dense leaf bytes for ``none`` and the
    quantizers (they ship dense float arrays), per-leaf
    ``k * (value + index)`` bytes for top-k — the same ``k`` rule and
    index dtype the real :func:`~fedml_tpu.core.compression.topk_leaf`
    uses, so the estimate and :func:`~fedml_tpu.core.compression
    .wire_bytes` of the actual payload agree."""
    import jax

    method = str(method).lower()
    if method not in LINK_SCHEMES:
        raise ValueError(f"unknown compression method {method!r}")
    if method in ("none", "quantize", "qsgd"):
        return wire_bytes(tree)
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = np.asarray(leaf)
        n = int(arr.size)
        if n == 0:
            continue
        k = topk_k(ratio, n)
        idx_itemsize = 8 if n > _INT32_MAX else 4
        total += k * int(arr.dtype.itemsize) + k * idx_itemsize
    return int(total)


def negotiate_codec(offers: Any, accepted: List[str]) -> str:
    """Pick the link codec from a child's offer and a parent's accept
    list: cheapest offered-and-accepted scheme by the child's byte
    estimates; schemes with no estimate lose to estimated ones; ties (and
    the no-estimates case) resolve by the PARENT's accept-list order.
    Anything malformed degrades to ``none`` — the link always works."""
    accepted = [str(s).lower() for s in (accepted or []) if s]
    if not isinstance(offers, dict):
        return "none"
    schemes = [str(s).lower() for s in offers.get("schemes", []) or []]
    estimates = offers.get("bytes") or {}
    candidates = [s for s in schemes if s in accepted and s in LINK_SCHEMES]
    if not candidates:
        return "none"
    def _key(s: str):
        est = estimates.get(s)
        has = isinstance(est, (int, float))
        return (0 if has else 1, est if has else 0, accepted.index(s))
    return sorted(candidates, key=_key)[0]


class HierarchyRouter:
    """Deterministic rank layout + node construction for one plan."""

    def __init__(self, args, plan: Optional[HierarchyPlan] = None,
                 n_leaves: Optional[int] = None, backend: str = "LOOPBACK",
                 mode: Optional[str] = None):
        if plan is None:
            if n_leaves is None:
                raise ValueError("router needs a plan or a leaf count")
            plan = HierarchyPlan.from_args(args, n_leaves)
        if plan.levels < 2:
            raise ValueError(
                "a hierarchy router needs fan_in_tree >= 2 "
                f"(got {plan.levels}); flat deployments evaluate the plan "
                "at the root directly")
        self.args = args
        self.plan = plan
        self.backend = backend
        self.mode = mode

    # -- rank layout ---------------------------------------------------------
    @property
    def size(self) -> int:
        return 1 + self.plan.n_edges + self.plan.n_mids + self.plan.n_leaves

    def edge_rank(self, edge_idx: int) -> int:
        return 1 + int(edge_idx)

    def mid_rank(self, mid_idx: int) -> int:
        return 1 + self.plan.n_edges + int(mid_idx)

    def leaf_rank(self, leaf_idx: int) -> int:
        return 1 + self.plan.n_edges + self.plan.n_mids + int(leaf_idx)

    def leaf_target_rank(self, leaf_idx: int) -> int:
        """The rank a leaf addresses its upload to: its block's edge."""
        return self.edge_rank(self.plan.edge_of(leaf_idx))

    def mid_id(self, mid_idx: int) -> int:
        """Mid node id in the shared edge-id namespace (forward ids stay
        globally unique)."""
        return self.plan.n_edges + int(mid_idx)

    def root_child_ranks(self) -> Dict[int, int]:
        """The root's direct children: mids when 3-level, else the edges."""
        if self.plan.levels == 3:
            return {self.mid_id(m): self.mid_rank(m)
                    for m in range(self.plan.n_mids)}
        return {e: self.edge_rank(e) for e in range(self.plan.n_edges)}

    # -- node construction ---------------------------------------------------
    def build_edges(self, comm=None, plane: Any = None
                    ) -> List[EdgeAggregator]:
        """Construct every edge (and mid) manager for this plan, leaf-edge
        blocks first, mids after — callers start each with ``run_async()``."""
        nodes: List[EdgeAggregator] = []
        for e, block in enumerate(self.plan.blocks):
            mid = self.plan.mid_of(e)
            parent = 0 if mid is None else self.mid_rank(mid)
            nodes.append(EdgeAggregator(
                self.args, self.plan, edge_id=e, parent_rank=parent,
                children=block, comm=comm, rank=self.edge_rank(e),
                size=self.size, backend=self.backend, mode=self.mode,
                plane=plane))
        for m, group in enumerate(self.plan.mid_groups):
            nodes.append(EdgeAggregator(
                self.args, self.plan, edge_id=self.mid_id(m), parent_rank=0,
                children=list(group),
                child_ranks={e: self.edge_rank(e) for e in group},
                is_mid=True, comm=comm, rank=self.mid_rank(m),
                size=self.size, backend=self.backend, mode=self.mode,
                plane=plane))
        return nodes

    def attach_root(self, manager, merger: Any = None,
                    on_round: Optional[Callable] = None,
                    plane: Any = None) -> HierarchyRoot:
        """Graft the tree's apex onto an existing rank-0 manager."""
        return HierarchyRoot(manager, self.plan,
                             child_ranks=self.root_child_ranks(),
                             mode=self.mode, plane=plane, merger=merger,
                             on_round=on_round)

    # -- leaf-side helper ----------------------------------------------------
    def leaf_upload_message(self, sender_rank: int, leaf_idx: int,
                            round_idx: int, n_samples: float, tree: Pytree,
                            epoch: int = 0,
                            telemetry: Any = None) -> Message:
        """Build one leaf upload addressed to its edge; ``telemetry`` is an
        optional :class:`~fedml_tpu.core.obs.telemetry.ClientTelemetry`
        whose pending ring rides along (and through the edge's graft)."""
        msg = Message(protocol.HIER_UPLOAD, sender_rank,
                      self.leaf_target_rank(leaf_idx))
        msg.add_params(protocol.KEY_ROUND, int(round_idx))
        msg.add_params(protocol.KEY_LEAF, int(leaf_idx))
        msg.add_params(protocol.KEY_N_SAMPLES, float(n_samples))
        msg.add_params(protocol.KEY_EPOCH, int(epoch))
        msg.add_params(protocol.KEY_PAYLOAD, tree)
        if telemetry is not None:
            telemetry.attach(msg)
        return msg
