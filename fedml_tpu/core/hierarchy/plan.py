"""The logical fan-in tree and its canonical blocked fold.

Floating-point addition does not reassociate, so a tree of partial
reductions can never be bit-identical to a *differently grouped* flat
fold — the only way a hierarchy can be "provably bit-identical to the
flat topology" is for the grouping itself to be part of the round's
arithmetic contract.  That is what a :class:`HierarchyPlan` is: the
blocks (contiguous leaf-index ranges), their fold order, and the tree
shape above them, derived purely from config
(``fan_in_tree`` / ``edge_fanout``) and the leaf count.  A flat
deployment evaluates the whole plan at the root
(:meth:`HierarchyPlan.aggregate`); a tree deployment evaluates each
block on its edge aggregator and combines up the tree — same operands,
same order, same bits.  Topology decides WHERE each block folds, never
WHAT is computed (the same move the compiled agg plane made to match the
host fold bit-for-bit).

``mean`` blocks scale every update by ``n_i / total`` with the GLOBAL
total (see :func:`~fedml_tpu.core.aggregate.partial_fold`), which is why
the wire protocol's flush is two-phase (counts up, total down) — no
float math happens at an edge until the global total is known.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

from ..aggregate import combine_partials, partial_fold

Pytree = Any

#: accepted ``fan_in_tree`` depths: 1 = flat, 2 = leaf->edge->root,
#: 3 = leaf->edge->mid->root
FAN_IN_TREE_LEVELS = (1, 2, 3)


def _blocks(n_items: int, fanout: int) -> List[List[int]]:
    """Contiguous index blocks of at most ``fanout`` items (one block of
    everything when ``fanout`` is 0)."""
    if fanout <= 0 or fanout >= n_items:
        return [list(range(n_items))]
    return [list(range(lo, min(lo + fanout, n_items)))
            for lo in range(0, n_items, fanout)]


@dataclass
class HierarchyPlan:
    """The logical tree: blocks of leaves, groups of blocks, fold order."""

    n_leaves: int
    levels: int = 1
    edge_fanout: int = 0
    edge_flush: Any = "all"
    #: leaf-edge blocks: leaf indices folded by each edge, in fold order
    blocks: List[List[int]] = field(init=False)
    #: mid groups (3-level only): edge indices combined by each mid
    mid_groups: List[List[int]] = field(init=False)

    def __post_init__(self):
        if int(self.levels) not in FAN_IN_TREE_LEVELS:
            raise ValueError(
                f"fan_in_tree must be one of {FAN_IN_TREE_LEVELS} "
                f"(got {self.levels!r})")
        if int(self.n_leaves) < 1:
            raise ValueError(f"n_leaves must be >= 1 (got {self.n_leaves})")
        self.n_leaves = int(self.n_leaves)
        self.levels = int(self.levels)
        self.edge_fanout = int(self.edge_fanout)
        fanout = self.edge_fanout if self.levels > 1 else 0
        self.blocks = _blocks(self.n_leaves, fanout)
        self.mid_groups = (_blocks(len(self.blocks), fanout)
                           if self.levels == 3 else [])

    @classmethod
    def from_args(cls, args: Any, n_leaves: int) -> "HierarchyPlan":
        return cls(
            n_leaves=n_leaves,
            levels=int(getattr(args, "fan_in_tree", 1) or 1),
            edge_fanout=int(getattr(args, "edge_fanout", 0) or 0),
            edge_flush=getattr(args, "edge_flush", "all") or "all",
        )

    # -- topology queries ----------------------------------------------------
    @property
    def n_edges(self) -> int:
        """Leaf-edge count (0 when the plan is flat)."""
        return len(self.blocks) if self.levels > 1 else 0

    @property
    def n_mids(self) -> int:
        return len(self.mid_groups)

    def edge_of(self, leaf_idx: int) -> int:
        """The leaf edge folding ``leaf_idx``'s block."""
        for e, block in enumerate(self.blocks):
            if leaf_idx in block:
                return e
        raise ValueError(f"leaf {leaf_idx} not in any block")

    def mid_of(self, edge_idx: int) -> Optional[int]:
        """The mid combining leaf-edge ``edge_idx`` (None for 2-level)."""
        if self.levels != 3:
            return None
        for m, group in enumerate(self.mid_groups):
            if edge_idx in group:
                return m
        raise ValueError(f"edge {edge_idx} not in any mid group")

    def flush_timeout(self) -> Optional[float]:
        """Seconds after which an edge flushes a partial block, or None
        for the default all-children barrier (``edge_flush="all"`` — the
        bit-exactness mode; a timeout flush trades bit-identity against
        the full-cohort plan for liveness under lost leaves)."""
        if isinstance(self.edge_flush, str) \
                and self.edge_flush.strip().lower() == "all":
            return None
        return float(self.edge_flush)

    # -- the canonical blocked fold ------------------------------------------
    def aggregate(self, updates: Sequence[Tuple[float, Pytree]],
                  mode: str = "mean", plane: Any = None) -> Pytree:
        """Evaluate the WHOLE plan at one node (the flat deployment).

        ``updates`` is indexed by leaf (0..n_leaves-1).  With ``plane``
        set (a :class:`~fedml_tpu.parallel.agg_plane.CompiledAggPlane`),
        block folds run ``plane.partial_reduce`` and combines run the
        plane's ``sum`` fold; otherwise both legs are the host fold.
        A tree deployment of the same plan computes the identical value
        bit-for-bit — each edge evaluates one block term, each mid/root
        one combine term.
        """
        if len(updates) != self.n_leaves:
            raise ValueError(
                f"plan expects {self.n_leaves} leaf updates "
                f"(got {len(updates)})")
        total = float(sum(float(n) for n, _ in updates))
        partials = [self.block_partial([updates[i] for i in block],
                                       total, mode, plane)
                    for block in self.blocks]
        if self.levels == 3:
            partials = [self.combine([partials[e] for e in group], mode,
                                     plane)
                        for group in self.mid_groups]
        return self.combine(partials, mode, plane)

    def block_partial(self, block_updates: Sequence[Tuple[float, Pytree]],
                      total_weight: float, mode: str = "mean",
                      plane: Any = None) -> Pytree:
        """One block's partial fold (the edge-aggregator term)."""
        if plane is not None:
            return plane.partial_reduce(list(block_updates),
                                        total_weight=total_weight, mode=mode)
        return partial_fold(block_updates, total_weight, mode=mode)

    def combine(self, partials: Sequence[Pytree], mode: str = "mean",
                plane: Any = None) -> Pytree:
        """Fold child partials (the mid/root term): the plain ``sum``
        fold in child order — partials are already scaled (``mean``) or
        raw sums (``sum``), so no tail math remains."""
        del mode  # same combine either way; kept for call-site symmetry
        if plane is not None:
            return plane.aggregate([(1.0, p) for p in partials], mode="sum")
        return combine_partials(partials)
