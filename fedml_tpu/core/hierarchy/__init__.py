"""Hierarchical fan-in: the streaming edge-aggregator tier.

One flat server cannot absorb million-client rounds even with the staged
ingest pipeline and the fused round tail — this package stands an
intermediate aggregation tier between leaf clients and the root server:

* :mod:`plan` — the *logical* tree: which leaves fold in which block, in
  which order, independent of where each block runs.  The canonical
  arithmetic is the blocked fold; deployment topology decides WHERE each
  block folds, never WHAT is computed, which is what makes a tree
  deployment provably bit-identical to the flat deployment of the same
  plan.
* :mod:`protocol` — the four-message wire vocabulary
  (upload / counts / total / partial) and the fused
  ``(partial_sum, total_weight, n_clients, leaf_epoch)`` delta format.
* :mod:`edge` — :class:`~fedml_tpu.core.hierarchy.edge.EdgeAggregator`,
  a comm-manager node that accepts leaf uploads through the existing
  ingest-pipeline + update-journal machinery (journal-before-ack, msg-id
  dedup), partial-reduces K-at-a-time via the agg plane's fold, and
  forwards ONE fused delta to its parent; a killed edge replays its
  journal and re-forwards under the same forward id.
* :mod:`root` — :class:`~fedml_tpu.core.hierarchy.root.HierarchyRoot`,
  the root-side fan-in that attaches to ANY existing manager
  (cross-silo / cross-device), dedups re-forwards for exactly-once
  accounting, and closes the round with the combined aggregate.
* :mod:`router` — :class:`~fedml_tpu.core.hierarchy.router.HierarchyRouter`,
  rank layout + node construction for 2- and 3-level trees from the
  validated ``fan_in_tree`` / ``edge_fanout`` / ``edge_flush`` knobs,
  plus per-link codec negotiation over honest
  :func:`~fedml_tpu.core.compression.wire_bytes` estimates.

Contract details and the runbook live in ``docs/HIERARCHY.md``.
"""

from __future__ import annotations

from .plan import HierarchyPlan
from .protocol import (
    HIER_COUNTS,
    HIER_PARTIAL,
    HIER_TOTAL,
    HIER_UPLOAD,
    PartialDelta,
)
from .edge import EdgeAggregator
from .root import HierarchyRoot
from .router import HierarchyRouter, estimate_scheme_bytes, negotiate_codec

__all__ = [
    "EdgeAggregator", "HierarchyPlan", "HierarchyRoot", "HierarchyRouter",
    "HIER_COUNTS", "HIER_PARTIAL", "HIER_TOTAL", "HIER_UPLOAD",
    "PartialDelta", "estimate_scheme_bytes", "negotiate_codec",
]
