"""The edge aggregator: one tree node's streaming partial fold.

An :class:`EdgeAggregator` is a :class:`~fedml_tpu.core.distributed
.comm_manager.FedMLCommManager` node that absorbs its block's leaf
uploads through the SAME machinery the root server uses — the reliable
link's msg-id dedup, the staged ingest pipeline
(``wants_ingest_pipeline``), and an :class:`~fedml_tpu.core.checkpoint
.UpdateJournal` with the journal-before-ack contract — so the PR 4/10
exactly-once guarantees hold one tier up, unchanged.

Round lifecycle (the two-phase count-then-reduce flush):

1. leaves send ``hier_upload``; each is journaled before its ack, its
   telemetry blob relayed, and (``sum`` mode, host leg, all-children
   barrier) stream-folded in leaf-index order through the ingest
   :class:`~fedml_tpu.core.ingest.ReorderWindow` so edge memory stays
   O(model) plus the out-of-order tail, not O(block).
2. when the block is complete (or ``edge_flush`` seconds elapsed), the
   edge sends ``hier_counts`` up: its block weight, client count, and
   codec offer.  No ``mean`` float math has happened yet — those scales
   need the GLOBAL total.
3. ``hier_total`` comes down with the global total and the negotiated
   codec; the edge folds its block (host ``partial_fold`` or the agg
   plane's ``partial_reduce``) and forwards ONE fused
   :class:`~fedml_tpu.core.hierarchy.protocol.PartialDelta` under a
   deterministic forward id, leaf telemetry grafted on.

A killed edge's replacement replays the journal, restages the same
uploads, re-offers the same telemetry bytes, re-sends counts, and — on
the parent's idempotent ``hier_total`` re-reply — re-forwards the same
delta under the SAME forward id; the parent's dedup makes the replay
exactly-once.

A *mid* edge (3-level trees) runs the same lifecycle over child EDGES
instead of leaves: child ``hier_counts`` roll up into one, ``hier_total``
relays down with a per-child negotiated codec, child ``hier_partial``
deltas combine (the plain sum fold — children arrive pre-scaled) into
one fused forward.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from .. import ingest, obs
from ..aggregate import FedMLAggOperator
from ..checkpoint import make_edge_journal
from ..compression import compress_update, maybe_decompress_update, wire_bytes
from ..distributed.comm_manager import FedMLCommManager
from ..distributed.communication.message import Message
from ..obs.telemetry import TelemetryRelay
from . import protocol
from .plan import HierarchyPlan
from .protocol import PartialDelta

logger = logging.getLogger(__name__)

Pytree = Any


def _zero_plus(tree: Pytree) -> Pytree:
    """``0 + x`` per leaf — the exact first term of the host ``tree_sum``
    left fold, so a streamed accumulator starts on the same operand."""
    return jax.tree_util.tree_map(lambda x: 0 + x, tree)


class EdgeAggregator(FedMLCommManager):
    """One tree node: leaf-edge (folds a block of leaf uploads) or mid
    (combines child edges' fused deltas)."""

    wants_ingest_pipeline = True

    def __init__(self, args, plan: HierarchyPlan, edge_id: int,
                 parent_rank: int, children: Sequence[int],
                 child_ranks: Optional[Dict[int, int]] = None,
                 is_mid: bool = False, comm=None, rank: int = 0,
                 size: int = 0, backend: str = "LOOPBACK",
                 mode: Optional[str] = None, plane: Any = None):
        self.plan = plan
        self.edge_id = int(edge_id)
        self.parent_rank = int(parent_rank)
        self.children = list(children)       # leaf indices, or child edge ids
        self.child_ranks = dict(child_ranks or {})
        self.is_mid = bool(is_mid)
        self.mode = mode or FedMLAggOperator.agg_mode(args)
        self._plane = plane
        self._plane_checked = plane is not None
        self._lock = threading.RLock()
        # per-round state, keyed by round index
        self._seen: Dict[int, set] = {}               # child keys landed
        self._seen_fwd: Dict[int, set] = {}           # mid: forward ids seen
        self._staged: Dict[int, Dict[int, Tuple[float, Any, int]]] = {}
        self._stream_acc: Dict[int, Pytree] = {}      # sum-mode stream fold
        self._stream_win: Dict[int, ingest.ReorderWindow] = {}
        self._counts_sent: Dict[int, Tuple[float, int]] = {}
        self._members: Dict[int, List[int]] = {}      # frozen at counts time
        self._child_counts: Dict[int, Dict[int, Tuple[float, int, Any]]] = {}
        self._totals: Dict[int, Tuple[float, str]] = {}
        self._forwarded: Dict[int, Message] = {}
        self._flush_timers: Dict[int, threading.Timer] = {}
        # armed while any round is staged: a wedged flush/forward path
        # (dead timer thread, stuck parent send) expires instead of the
        # root waiting forever on a mute edge
        self._watchdog = obs.health_watchdog(f"edge.flush.{edge_id}")
        self.relay = TelemetryRelay()
        self.dup_uploads = 0
        self.dup_forwards = 0
        self._journal = make_edge_journal(args, edge_id)
        super().__init__(args, comm=comm, rank=rank, size=size,
                         backend=backend)
        if self._chunking is not None and self._journal is not None:
            # chunked leaf uploads journal-before-ack at chunk granularity
            # through the same edge journal (sub-message version of the
            # _journal_record contract below)
            self._chunking.bind_journal(self._journal_record)
        self._recover()

    # -- wiring --------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        if self.is_mid:
            self.register_message_receive_handler(
                protocol.HIER_COUNTS, self._handle_child_counts)
            self.register_message_receive_handler(
                protocol.HIER_PARTIAL, self._handle_child_partial)
        else:
            self.register_message_receive_handler(
                protocol.HIER_UPLOAD, self._handle_upload)
        self.register_message_receive_handler(
            protocol.HIER_TOTAL, self._handle_total)

    @property
    def plane(self):
        if not self._plane_checked:
            self._plane_checked = True
            if str(getattr(self.args, "agg_plane", "host")
                   or "host") == "compiled":
                from ...parallel.agg_plane import plane_for

                self._plane = plane_for(self.args)
        return self._plane

    def _streaming(self) -> bool:
        """The stream fold needs the all-children barrier: a timeout flush
        may fold a non-contiguous subset, which only the staged path can
        do in plan order."""
        return (self.mode == "sum" and not self.is_mid
                and self.plane is None and self.flush_deadline() is None)

    def flush_deadline(self) -> Optional[float]:
        return self.plan.flush_timeout()

    # -- journal-before-ack (the _journal_upload idiom, one tier up) ---------
    def _journal_record(self, round_idx: int, record: Dict[str, Any]) -> None:
        journal = self._journal
        if journal is None:
            return
        sink = (ingest.current_sink()
                if journal.group_commit_enabled else None)
        if sink is not None:
            sink.add(journal.append_async(round_idx, record))
        else:
            journal.append(round_idx, record)

    # -- leaf-edge: uploads --------------------------------------------------
    def _handle_upload(self, msg: Message) -> None:
        r = int(msg.get(protocol.KEY_ROUND))
        leaf = int(msg.get(protocol.KEY_LEAF))
        n = float(msg.get(protocol.KEY_N_SAMPLES, 0.0))
        epoch = int(msg.get(protocol.KEY_EPOCH, 0) or 0)
        with self._lock:
            if leaf in self._seen.get(r, ()):
                self.dup_uploads += 1
                obs.counter_inc("hierarchy.dup_uploads")
                return
            if r in self._counts_sent:
                # a straggler past a timeout flush: its weight is not in
                # the counts the parent already totaled — count and drop
                # (journal untouched, so nothing double-folds on replay)
                obs.counter_inc("hierarchy.late_uploads")
                return
        if leaf not in self.children:
            logger.warning("edge %d: leaf %d is not in this block",
                           self.edge_id, leaf)
            return
        # decompress BEFORE journaling: the leaf->edge codec is transport-
        # only, and the journal's msgpack framing can't carry treedefs
        tree = maybe_decompress_update(msg.get(protocol.KEY_PAYLOAD))
        blob = self.relay.collect(msg)
        self._journal_record(r, {
            "round_idx": r, "sender": leaf, "n_samples": n, "epoch": epoch,
            "model_params": tree, "telemetry": blob,
        })
        self._stage_upload(r, leaf, n, tree, epoch)

    def _stage_upload(self, r: int, leaf: int, n: float, tree: Pytree,
                      epoch: int) -> None:
        deadline = self.flush_deadline()
        with self._lock:
            self._seen.setdefault(r, set()).add(leaf)
            staged = self._staged.setdefault(r, {})
            if self._streaming():
                # stream the host sum fold in leaf-index order: each payload
                # is dropped the moment the window releases it into the
                # accumulator (the journal keeps the durable copy)
                win = self._stream_win.get(r)
                if win is None:
                    win = ingest.ReorderWindow(list(self.children))
                    self._stream_win[r] = win
                staged[leaf] = (n, None, epoch)
                for _, item in win.stage(leaf, tree):
                    acc = self._stream_acc.get(r)
                    self._stream_acc[r] = (
                        _zero_plus(item) if acc is None
                        else jax.tree_util.tree_map(lambda a, b: a + b,
                                                    acc, item))
            else:
                staged[leaf] = (n, tree, epoch)
            if (deadline is not None and r not in self._flush_timers
                    and r not in self._counts_sent):
                t = threading.Timer(deadline, self._maybe_send_counts,
                                    args=(r, True))
                t.daemon = True
                self._flush_timers[r] = t
                t.start()
        self._watchdog.beat()
        self._maybe_send_counts(r)

    # -- phase A: counts up --------------------------------------------------
    def _maybe_send_counts(self, r: int, force: bool = False) -> None:
        with self._lock:
            if r in self._counts_sent:
                return
            if not force and len(self._seen.get(r, ())) < len(self.children):
                return
            staged = self._staged.get(r, {})
            if not staged:
                return
            members = sorted(staged)
            if self.is_mid:
                counts = self._child_counts.get(r, {})
                weight = float(sum(counts[c][0] for c in members))
                n_clients = int(sum(counts[c][1] for c in members))
            else:
                weight = float(sum(staged[c][0] for c in members))
                n_clients = len(members)
            self._counts_sent[r] = (weight, n_clients)
            self._members[r] = members
            timer = self._flush_timers.pop(r, None)
        if timer is not None:
            timer.cancel()
        msg = Message(protocol.HIER_COUNTS, self.rank, self.parent_rank)
        msg.add_params(protocol.KEY_ROUND, r)
        msg.add_params(protocol.KEY_EDGE, self.edge_id)
        msg.add_params(protocol.KEY_TOTAL_WEIGHT, weight)
        msg.add_params(protocol.KEY_N_CLIENTS, n_clients)
        msg.add_params(protocol.KEY_OFFERS, self._codec_offers(r))
        self.send_message(msg)
        obs.counter_inc("hierarchy.counts_sent")

    def _codec_offers(self, r: int) -> Dict[str, Any]:
        """This edge's codec offer: the schemes it can speak plus honest
        byte estimates for the fused forward, measured on a staged tree
        (same shapes as the partial)."""
        from .router import estimate_scheme_bytes

        schemes = [s.strip().lower() for s in str(
            getattr(self.args, "edge_codec_offers", "none") or "none"
        ).split(",") if s.strip()]
        sample: Optional[Pytree] = None
        with self._lock:
            if r in self._stream_acc:
                sample = self._stream_acc[r]
            else:
                for _n, t_, _e in self._staged.get(r, {}).values():
                    if t_ is not None and not isinstance(t_, PartialDelta):
                        sample = t_
                        break
        estimates: Dict[str, int] = {}
        if sample is not None:
            ratio = float(getattr(self.args, "edge_codec_ratio", 0.05) or 0.05)
            for s in schemes:
                try:
                    estimates[s] = estimate_scheme_bytes(sample, s, ratio)
                except Exception:
                    pass
        return {"schemes": schemes, "bytes": estimates}

    # -- mid: child counts / partials ---------------------------------------
    def _handle_child_counts(self, msg: Message) -> None:
        r = int(msg.get(protocol.KEY_ROUND))
        child = int(msg.get(protocol.KEY_EDGE))
        with self._lock:
            counts = self._child_counts.setdefault(r, {})
            fresh = child not in counts
            counts[child] = (float(msg.get(protocol.KEY_TOTAL_WEIGHT, 0.0)),
                             int(msg.get(protocol.KEY_N_CLIENTS, 0)),
                             msg.get(protocol.KEY_OFFERS))
            self._seen.setdefault(r, set()).add(child)
            # a mid "stages" a placeholder per counted child so the counts
            # barrier sees progress before any partial arrives
            self._staged.setdefault(r, {}).setdefault(child, (0.0, None, 0))
            already_total = r in self._totals
        if not fresh and already_total:
            # a replayed child re-sent counts after this mid already has
            # the global total: re-relay it down idempotently so the
            # replayed incarnation can re-fold and re-forward
            self._relay_total_down(r, self._totals[r][0], only_child=child)
            return
        self._maybe_send_counts(r)

    def _handle_child_partial(self, msg: Message) -> None:
        r = int(msg.get(protocol.KEY_ROUND))
        child = int(msg.get(protocol.KEY_EDGE))
        fwd = str(msg.get(protocol.KEY_FORWARD_ID))
        with self._lock:
            seen = self._seen_fwd.setdefault(r, set())
            if fwd in seen:
                self.dup_forwards += 1
                obs.counter_inc("hierarchy.dup_forwards")
                return
            seen.add(fwd)
        wire = dict(msg.get(protocol.KEY_PAYLOAD))
        wire["partial_sum"] = maybe_decompress_update(wire["partial_sum"])
        delta = PartialDelta.from_wire(wire)
        collected = self.relay.collect_many(msg)
        self._journal_record(r, {
            "round_idx": r, "sender": child, "forward_id": fwd,
            "delta": delta.to_wire(), "telemetry": collected,
        })
        with self._lock:
            self._staged.setdefault(r, {})[child] = (
                delta.total_weight, delta, delta.leaf_epoch)
        self._maybe_forward(r)

    # -- phase B: total down, fused delta up ---------------------------------
    def _handle_total(self, msg: Message) -> None:
        r = int(msg.get(protocol.KEY_ROUND))
        total = float(msg.get(protocol.KEY_TOTAL_WEIGHT))
        codec = str(msg.get(protocol.KEY_CODEC, "none") or "none")
        with self._lock:
            self._totals[r] = (total, codec)
        if self.is_mid:
            self._relay_total_down(r, total)
        self._maybe_forward(r)

    def _relay_total_down(self, r: int, total: float,
                          only_child: Optional[int] = None) -> None:
        from .router import negotiate_codec

        accepted = [s.strip().lower() for s in str(
            getattr(self.args, "edge_codec_accept", "none") or "none"
        ).split(",") if s.strip()]
        with self._lock:
            counts = dict(self._child_counts.get(r, {}))
        for child in sorted(counts):
            if only_child is not None and child != only_child:
                continue
            child_rank = self.child_ranks.get(child)
            if child_rank is None:
                continue
            m = Message(protocol.HIER_TOTAL, self.rank, child_rank)
            m.add_params(protocol.KEY_ROUND, r)
            m.add_params(protocol.KEY_TOTAL_WEIGHT, total)
            m.add_params(protocol.KEY_CODEC,
                         negotiate_codec(counts[child][2], accepted))
            self.send_message(m)

    def _maybe_forward(self, r: int) -> None:
        with self._lock:
            if r in self._forwarded:
                # duplicate hier_total (the parent's idempotent re-reply to
                # a replayed sibling, or a retransmit): re-forward the SAME
                # message — same forward id, same blobs; the parent dedups
                msg = self._forwarded[r]
                obs.counter_inc("hierarchy.reforwards")
            else:
                if r not in self._totals or r not in self._counts_sent:
                    return
                staged = self._staged.get(r, {})
                members = self._members.get(r, [])
                if self.is_mid:
                    ready = [c for c in members
                             if isinstance(staged.get(c, (0, None, 0))[1],
                                           PartialDelta)]
                else:
                    ready = [c for c in members
                             if c in staged
                             and (self._streaming()
                                  or staged[c][1] is not None)]
                if len(ready) < len(members):
                    return
                msg = self._build_forward(r)
                self._forwarded[r] = msg
        self.send_message(msg)
        obs.counter_inc("hierarchy.forwards")
        self._watchdog.beat()

    def _build_forward(self, r: int) -> Message:
        total, codec = self._totals[r]
        weight, n_clients = self._counts_sent[r]
        staged = self._staged[r]
        order = [c for c in self.children if c in self._members[r]]
        if self.is_mid:
            deltas = [staged[c][1] for c in order]
            partial = self.plan.combine([d.partial_sum for d in deltas],
                                        self.mode, self.plane)
            epoch = min((d.leaf_epoch for d in deltas), default=0)
        elif self._streaming():
            partial = self._stream_acc.pop(r)
            epoch = min((staged[c][2] for c in order), default=0)
        else:
            updates = [(staged[c][0], staged[c][1]) for c in order]
            partial = self.plan.block_partial(updates, total, self.mode,
                                              self.plane)
            epoch = min((staged[c][2] for c in order), default=0)
        delta = PartialDelta(partial_sum=partial, total_weight=weight,
                             n_clients=n_clients, leaf_epoch=epoch)
        wire = delta.to_wire()
        if codec != "none":
            ratio = float(getattr(self.args, "edge_codec_ratio", 0.05) or 0.05)
            bits = int(getattr(self.args, "edge_codec_bits", 8) or 8)
            payload, _ = compress_update(partial, method=codec, ratio=ratio,
                                         bits=bits)
            wire["partial_sum"] = payload
            obs.counter_inc("hierarchy.codec_compressed")
        try:
            obs.histogram_observe("hierarchy.forward_bytes",
                                  float(wire_bytes(wire["partial_sum"])))
        except Exception:
            pass
        msg = Message(protocol.HIER_PARTIAL, self.rank, self.parent_rank)
        msg.add_params(protocol.KEY_ROUND, r)
        msg.add_params(protocol.KEY_EDGE, self.edge_id)
        msg.add_params(protocol.KEY_FORWARD_ID,
                       protocol.forward_id(self.edge_id, r))
        msg.add_params(protocol.KEY_PAYLOAD, wire)
        self.relay.graft(msg)
        if not self.is_mid:
            # free the round's staged payloads; the journal keeps the
            # durable copy a replacement incarnation would replay
            self._staged[r] = {c: (staged[c][0], None, staged[c][2])
                               for c in staged}
        return msg

    # -- crash recovery ------------------------------------------------------
    def _recover(self) -> None:
        """Replay the predecessor incarnation's journal: restage every
        accepted upload (or child delta), re-offer its telemetry, and
        re-send counts — the parent's idempotent ``hier_total`` re-reply
        then drives a re-forward under the SAME forward id."""
        journal = self._journal
        if journal is None:
            return
        for r in journal.rounds():
            records, bad_tail = journal.replay(r)
            if bad_tail:
                obs.counter_inc("hierarchy.replay_bad_tail")
            restaged = 0
            chunk_recs = [x for x in records if x.get("kind") == "chunk"]
            if chunk_recs and self._chunking is not None:
                # partial chunk streams resume in the reassembler; complete
                # ones re-dispatch on the sender's retransmit and are then
                # deduped by _seen like any re-delivered upload
                self._chunking.restore(chunk_recs)
            for rec in records:
                if rec.get("kind") == "chunk":
                    continue
                blob_field = rec.get("telemetry")
                blobs = (blob_field if isinstance(blob_field, (list, tuple))
                         else [blob_field])
                for b in blobs:
                    if isinstance(b, (bytes, bytearray)):
                        self.relay.offer(bytes(b))
                if "delta" in rec:
                    fwd = str(rec.get("forward_id"))
                    child = int(rec["sender"])
                    with self._lock:
                        seen = self._seen_fwd.setdefault(r, set())
                        if fwd in seen:
                            continue
                        seen.add(fwd)
                        self._seen.setdefault(r, set()).add(child)
                        delta = PartialDelta.from_wire(rec["delta"])
                        self._staged.setdefault(r, {})[child] = (
                            delta.total_weight, delta, delta.leaf_epoch)
                        self._child_counts.setdefault(r, {})[child] = (
                            delta.total_weight, delta.n_clients, None)
                else:
                    leaf = int(rec["sender"])
                    with self._lock:
                        if leaf in self._seen.get(r, set()):
                            continue
                    self._stage_upload(r, leaf, float(rec["n_samples"]),
                                       rec["model_params"],
                                       int(rec.get("epoch", 0)))
                restaged += 1
            if restaged:
                obs.counter_inc("hierarchy.replayed_records", restaged)
                logger.info("edge %d: replayed %d journaled records for "
                            "round %d", self.edge_id, restaged, r)
                self._maybe_send_counts(r)

    # -- housekeeping --------------------------------------------------------
    def prune_round(self, r: int) -> None:
        """Drop a finished round's state (the parent has combined it)."""
        with self._lock:
            for d in (self._seen, self._seen_fwd, self._staged,
                      self._stream_acc, self._stream_win, self._counts_sent,
                      self._members, self._child_counts, self._totals,
                      self._forwarded):
                d.pop(r, None)
            timer = self._flush_timers.pop(r, None)
            live = bool(self._staged)
        if timer is not None:
            timer.cancel()
        if live:
            self._watchdog.beat()
        else:
            self._watchdog.idle()
        if self._journal is not None:
            self._journal.prune_before(r + 1)

    def finish(self) -> None:
        with self._lock:
            timers = list(self._flush_timers.values())
            self._flush_timers.clear()
        for t in timers:
            t.cancel()
        self._watchdog.close()
        if self._journal is not None:
            try:
                self._journal.flush(timeout=10.0)
                self._journal.close()
            except Exception:
                pass
        super().finish()
