"""fedlint framework: shared walker, tokenizer stripping, pragmas, baseline.

The four grep-based lint scripts (``tools/lint_{rng,obs,agg,perf}.py``) each
re-implemented comment/string stripping and file walking, and — being raw
regexes — could be dodged by a one-line import alias (``from os import fsync
as f``).  This package replaces all of that with ONE framework:

* :class:`SourceFile` — path + raw lines + tokenize-stripped code lines +
  parsed AST + import-alias map, computed once and shared by every analyzer;
* :class:`Analyzer` / :class:`Rule` — the pass plug-in surface.  Rules carry
  a stable id (``perf-stray-fsync``), may opt into RAW-line scanning
  (``raw=True`` — string literals stay visible, used by the telemetry wire
  key rule), and may demand a justification on their pragmas;
* pragmas — ``# fedlint: allow[rule-id] — why`` suppresses that rule on that
  line.  Rules with ``requires_justification`` (the race and ack-ordering
  analyzers) reject a bare pragma: the finding stands until a non-empty
  justification follows the bracket.  Legacy per-tool pragmas
  (``# lint_rng: allow`` ...) keep working for the ported passes;
* baseline — a JSON suppression file for grandfathering pre-existing
  findings.  Race/ack entries are REJECTED at load (warned and ignored):
  those two contracts may only be silenced by an inline justified pragma;
* engine — :func:`analyze_file` / :func:`analyze_tree` walk, run analyzers,
  and apply suppression, returning an :class:`AnalysisResult`.

Exit-code contract (``tools/fedlint.py``): 0 clean / all suppressed,
1 findings, 2 usage or internal error.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .imports import ImportMap

#: bumped when the JSON reporter's schema changes shape
JSON_SCHEMA_VERSION = 1

#: rule-id prefixes that may never be baselined — only justified pragmas
NO_BASELINE_PREFIXES = ("race-", "ack-")

_PRAGMA_RE = re.compile(r"#\s*fedlint:\s*allow\[([^\]]+)\]\s*(.*)$")
# leading separators commonly used between the bracket and the justification
_JUSTIFICATION_STRIP = " \t:—–-"


class Rule:
    """One checkable contract: stable id + human summary + scan options."""

    __slots__ = ("id", "summary", "raw", "requires_justification", "order")

    def __init__(self, id: str, summary: str, *, raw: bool = False,
                 requires_justification: bool = False, order: int = 0):
        self.id = id
        self.summary = summary
        self.raw = raw
        self.requires_justification = requires_justification
        self.order = order


class Finding:
    """One rule violation at one source line."""

    __slots__ = ("analyzer", "rule", "path", "lineno", "message", "source",
                 "note")

    def __init__(self, analyzer: str, rule: str, path: str, lineno: int,
                 message: str, source: str, note: str = ""):
        self.analyzer = analyzer
        self.rule = rule
        self.path = path
        self.lineno = int(lineno)
        self.message = message
        self.source = source
        self.note = note

    def relpath(self, root: str) -> str:
        try:
            rel = os.path.relpath(self.path, root)
        except ValueError:  # pragma: no cover - cross-drive on windows
            rel = self.path
        return rel.replace(os.sep, "/")

    def to_dict(self, root: str) -> Dict[str, Any]:
        d = {
            "analyzer": self.analyzer,
            "rule": self.rule,
            "path": self.relpath(root),
            "line": self.lineno,
            "message": self.message,
            "source": self.source.strip(),
        }
        if self.note:
            d["note"] = self.note
        return d

    def sort_key(self) -> Tuple:
        return (self.path, self.lineno, self.analyzer, self.rule)


def strip_comments_and_strings(source: str) -> List[str]:
    """The file's lines with comments and string literals blanked via
    ``tokenize`` — only actual code can trip a (non-raw) rule.  Unparseable
    files fall back to the raw lines rather than being skipped (the same
    behaviour the four legacy linters shared)."""
    lines = source.splitlines()
    kept = list(lines)
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return kept
    for tok in tokens:
        if tok.type not in (tokenize.COMMENT, tokenize.STRING):
            continue
        (srow, scol), (erow, ecol) = tok.start, tok.end
        for row in range(srow, erow + 1):
            line = kept[row - 1]
            lo = scol if row == srow else 0
            hi = ecol if row == erow else len(line)
            kept[row - 1] = line[:lo] + " " * (hi - lo) + line[hi:]
    return kept


class SourceFile:
    """One parsed file, shared by every analyzer: the tokenizer strip and the
    AST parse happen once per file, not once per pass."""

    __slots__ = ("path", "text", "raw_lines", "_code_lines", "_tree",
                 "_parsed", "_imports")

    def __init__(self, path: str, text: Optional[str] = None):
        self.path = os.path.abspath(path)
        if text is None:
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                text = f.read()
        self.text = text
        self.raw_lines = text.splitlines()
        self._code_lines: Optional[List[str]] = None
        self._tree: Optional[ast.AST] = None
        self._parsed = False
        self._imports: Optional[ImportMap] = None

    @property
    def code_lines(self) -> List[str]:
        if self._code_lines is None:
            self._code_lines = strip_comments_and_strings(self.text)
        return self._code_lines

    @property
    def tree(self) -> Optional[ast.Module]:
        """The parsed module, or None when the file doesn't parse (passes
        then fall back to their regex form or skip)."""
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.text)
            except (SyntaxError, ValueError):
                self._tree = None
        return self._tree

    @property
    def imports(self) -> ImportMap:
        if self._imports is None:
            self._imports = ImportMap(self.tree)
        return self._imports

    def raw_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.raw_lines):
            return self.raw_lines[lineno - 1]
        return ""


class Analyzer:
    """Base class for one pass.

    Subclasses set ``name``, ``rules`` and implement :meth:`check`.
    ``legacy_pragma`` is the old per-tool pragma substring this pass still
    honors; ``exempt_parts`` / ``exempt_files`` are path fragments whose
    files the pass skips entirely (the seam owners)."""

    name: str = ""
    rules: Tuple[Rule, ...] = ()
    legacy_pragma: Optional[str] = None
    exempt_parts: Tuple[str, ...] = ()
    exempt_files: Tuple[str, ...] = ()

    def rule_by_id(self, rule_id: str) -> Rule:
        for r in self.rules:
            if r.id == rule_id:
                return r
        raise KeyError(rule_id)

    def is_exempt(self, path: str) -> bool:
        norm = os.path.normpath(os.path.abspath(path))
        for part in self.exempt_parts:
            p = part.replace("/", os.sep)
            if os.sep + p + os.sep in norm or norm.endswith(os.sep + p):
                return True
        for part in self.exempt_files:
            p = part.replace("/", os.sep)
            if norm.endswith(os.sep + p):
                return True
        return False

    def finding(self, rule: Rule, src: SourceFile, lineno: int,
                message: str) -> Finding:
        return Finding(self.name, rule.id, src.path, lineno, message,
                       src.raw_line(lineno).rstrip())

    def check(self, src: SourceFile) -> List[Finding]:
        raise NotImplementedError


def parse_pragma(raw_line: str) -> Optional[Tuple[Set[str], str]]:
    """``(allowed_rule_ids, justification)`` for a ``# fedlint: allow[...]``
    pragma on ``raw_line``, or None.  ``*`` allows every rule."""
    m = _PRAGMA_RE.search(raw_line)
    if m is None:
        return None
    rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
    justification = m.group(2).strip(_JUSTIFICATION_STRIP).strip()
    return rules, justification


class Baseline:
    """Suppression file: grandfathered findings keyed on
    ``(rule, path, stripped source line)`` — line numbers drift, content
    mostly doesn't.  Race/ack entries are refused at load time."""

    def __init__(self, entries: Optional[Iterable[Dict[str, str]]] = None):
        self.entries: Set[Tuple[str, str, str]] = set()
        self.rejected: List[Dict[str, str]] = []
        for e in entries or ():
            rule = str(e.get("rule", ""))
            if rule.startswith(NO_BASELINE_PREFIXES):
                self.rejected.append(dict(e))
                continue
            self.entries.add((rule, str(e.get("path", "")),
                              str(e.get("source", "")).strip()))

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or "entries" not in doc:
            raise ValueError(f"baseline {path}: expected {{'entries': [...]}}")
        return cls(doc["entries"])

    def matches(self, finding: Finding, root: str) -> bool:
        key = (finding.rule, finding.relpath(root), finding.source.strip())
        return key in self.entries

    @staticmethod
    def render(findings: Sequence[Finding], root: str) -> str:
        entries = []
        for f in sorted(findings, key=Finding.sort_key):
            if f.rule.startswith(NO_BASELINE_PREFIXES):
                continue  # never write race/ack grandfathering
            entries.append({"rule": f.rule, "path": f.relpath(root),
                            "source": f.source.strip()})
        return json.dumps({"version": 1, "entries": entries},
                          indent=2, sort_keys=True) + "\n"


class AnalysisResult:
    """Findings plus the suppression accounting the reporters render."""

    __slots__ = ("root", "findings", "files_scanned", "suppressed_pragma",
                 "suppressed_baseline", "baseline_rejected")

    def __init__(self, root: str):
        self.root = root
        self.findings: List[Finding] = []
        self.files_scanned = 0
        self.suppressed_pragma = 0
        self.suppressed_baseline = 0
        self.baseline_rejected: List[Dict[str, str]] = []

    @property
    def clean(self) -> bool:
        return not self.findings


def iter_python_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def analyze_file(src: SourceFile, analyzers: Sequence[Analyzer],
                 result: Optional[AnalysisResult] = None,
                 baseline: Optional[Baseline] = None,
                 root: Optional[str] = None) -> List[Finding]:
    """Run ``analyzers`` over one file and apply pragma/baseline suppression.
    Returns the surviving findings (also appended to ``result`` if given)."""
    kept: List[Finding] = []
    root = root or os.path.dirname(src.path)
    for analyzer in analyzers:
        if analyzer.is_exempt(src.path) and not any(r.raw for r in analyzer.rules):
            continue
        for f in sorted(analyzer.check(src), key=Finding.sort_key):
            raw = src.raw_line(f.lineno)
            rule = analyzer.rule_by_id(f.rule)
            if analyzer.legacy_pragma and analyzer.legacy_pragma in raw:
                if result is not None:
                    result.suppressed_pragma += 1
                continue
            pragma = parse_pragma(raw)
            if pragma is not None:
                allowed, justification = pragma
                if f.rule in allowed or "*" in allowed:
                    if rule.requires_justification and not justification:
                        f.note = ("pragma present but missing the required "
                                  "justification — add one after the bracket")
                    else:
                        if result is not None:
                            result.suppressed_pragma += 1
                        continue
            if baseline is not None and baseline.matches(f, root):
                if result is not None:
                    result.suppressed_baseline += 1
                continue
            kept.append(f)
    kept.sort(key=Finding.sort_key)
    if result is not None:
        result.findings.extend(kept)
        result.files_scanned += 1
    return kept


def analyze_tree(root: str, analyzers: Sequence[Analyzer],
                 baseline: Optional[Baseline] = None) -> AnalysisResult:
    result = AnalysisResult(os.path.abspath(root))
    if baseline is not None:
        result.baseline_rejected = list(baseline.rejected)
    for path in iter_python_files(root):
        analyze_file(SourceFile(path), analyzers, result=result,
                     baseline=baseline, root=result.root)
    result.findings.sort(key=Finding.sort_key)
    return result
