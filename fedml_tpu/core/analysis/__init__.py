"""fedlint: the unified AST static-analysis plane.

One framework (shared walker, tokenizer stripping, import-alias resolution,
pragmas, baseline, reporters) hosting pluggable analyzers:

* the four ported lint contracts (rng / obs / agg / perf);
* the thread-ownership race detector (``races``);
* the ack-durability ordering checker (``ack``);
* the JAX purity/determinism pass (``purity``).

Entry points: ``tools/fedlint.py`` (CLI), or programmatically::

    from fedml_tpu.core.analysis import analyze_tree, build_analyzers
    result = analyze_tree("fedml_tpu", build_analyzers())

See ``docs/STATIC_ANALYSIS.md`` for the rule catalog, the ownership
annotation convention, and the pragma/baseline policy.
"""

from .framework import (
    AnalysisResult,
    Analyzer,
    Baseline,
    Finding,
    JSON_SCHEMA_VERSION,
    NO_BASELINE_PREFIXES,
    Rule,
    SourceFile,
    analyze_file,
    analyze_tree,
    iter_python_files,
    parse_pragma,
    strip_comments_and_strings,
)
from .imports import ImportMap, receiver_of, terminal_name
from .passes import build_analyzers
from .report import render_json, render_rule_catalog, render_text

__all__ = [
    "AnalysisResult", "Analyzer", "Baseline", "Finding",
    "ImportMap", "JSON_SCHEMA_VERSION", "NO_BASELINE_PREFIXES", "Rule",
    "SourceFile", "analyze_file", "analyze_tree", "build_analyzers",
    "iter_python_files", "parse_pragma", "receiver_of", "render_json",
    "render_rule_catalog", "render_text", "strip_comments_and_strings",
    "terminal_name",
]
