"""Import-alias resolution for fedlint passes.

The legacy grep linters were dodged by a one-line rename::

    from os import fsync as f          # lint_perf never saw "os.fsync("
    import msgpack as mp               # mp.unpackb(...) sailed through

:class:`ImportMap` closes that gap: it records every ``import`` /
``from ... import`` binding in a module and resolves a ``Name`` or
``Attribute`` chain back to its fully qualified dotted name, so rules match
on what a call IS (``os.fsync``) rather than how it is spelled.

Names that were never imported resolve to themselves (``msgpack_restore``
stays ``msgpack_restore``) — rules that ban a bare helper name still work —
with a small fallback table for the conventional scientific aliases
(``np``/``_np`` → ``numpy``, ``jnp`` → ``jax.numpy``) so fixture snippets
and REPL-ish code without imports still resolve.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional

# conventional aliases assumed even without an import statement; a real
# import of the same name takes precedence
_FALLBACK_ALIASES = {
    "np": "numpy",
    "_np": "numpy",
    "jnp": "jax.numpy",
    "lax": "jax.lax",
}


class ImportMap:
    """Maps local names to the dotted module/attribute they were bound to."""

    __slots__ = ("aliases",)

    def __init__(self, tree: Optional[ast.AST] = None):
        self.aliases: Dict[str, str] = {}
        if tree is None:
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        # "import numpy.random as nr" binds nr -> numpy.random
                        self.aliases[alias.asname] = alias.name
                    else:
                        # "import numpy.random" binds only the root "numpy"
                        root = alias.name.split(".", 1)[0]
                        self.aliases[root] = root
            elif isinstance(node, ast.ImportFrom):
                module = node.module or ""
                if node.level:
                    # relative import: keep the dots so resolution is honest
                    module = "." * node.level + module
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    full = f"{module}.{alias.name}" if module else alias.name
                    self.aliases[local] = full

    def resolve_name(self, name: str) -> str:
        if name in self.aliases:
            return self.aliases[name]
        return _FALLBACK_ALIASES.get(name, name)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Fully qualified dotted name for a Name/Attribute chain, or None
        when the chain is rooted in something dynamic (a call result, a
        subscript, ``self.<attr>`` ...)."""
        parts = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        parts.append(self.resolve_name(cur.id))
        return ".".join(reversed(parts))


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last segment of a call target: ``foo`` for ``a.b.foo`` / ``foo``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def receiver_of(node: ast.AST) -> Optional[ast.AST]:
    """The expression a method is called on: ``a.b`` for ``a.b.foo``."""
    if isinstance(node, ast.Attribute):
        return node.value
    return None
