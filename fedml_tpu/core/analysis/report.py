"""Reporters for fedlint results.

Both renderers RETURN strings — printing is the CLI's job (and library code
printing metric-shaped JSON would trip the obs pass's own rule).  The JSON
document is versioned and key-sorted so trace_report-style consumers can
depend on its shape.
"""

from __future__ import annotations

import json
from typing import Sequence

from .framework import Analyzer, AnalysisResult, JSON_SCHEMA_VERSION


def render_text(result: AnalysisResult) -> str:
    lines = []
    for f in result.findings:
        lines.append(f"fedlint: {f.relpath(result.root)}:{f.lineno}: "
                     f"[{f.rule}] {f.message}")
        if f.note:
            lines.append(f"fedlint:     note: {f.note}")
    for entry in result.baseline_rejected:
        lines.append("fedlint: baseline entry for rule "
                     f"'{entry.get('rule', '?')}' IGNORED — race/ack "
                     "contracts may only be suppressed by a justified "
                     "inline pragma")
    suppressed = result.suppressed_pragma + result.suppressed_baseline
    tail = (f"{result.files_scanned} file(s) scanned, "
            f"{len(result.findings)} finding(s)")
    if suppressed:
        tail += (f", {result.suppressed_pragma} pragma-suppressed, "
                 f"{result.suppressed_baseline} baseline-suppressed")
    lines.append(f"fedlint: {tail}")
    if not result.findings:
        lines.append("fedlint: clean")
    return "\n".join(lines)


def render_json(result: AnalysisResult) -> str:
    doc = {
        "version": JSON_SCHEMA_VERSION,
        "root": result.root,
        "findings": [f.to_dict(result.root) for f in result.findings],
        "counts": {
            "findings": len(result.findings),
            "files_scanned": result.files_scanned,
        },
        "suppressed": {
            "pragma": result.suppressed_pragma,
            "baseline": result.suppressed_baseline,
        },
        "baseline_rejected": result.baseline_rejected,
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def render_rule_catalog(analyzers: Sequence[Analyzer]) -> str:
    lines = []
    for analyzer in analyzers:
        lines.append(f"{analyzer.name}:")
        for rule in analyzer.rules:
            flags = []
            if rule.raw:
                flags.append("raw")
            if rule.requires_justification:
                flags.append("justified-pragma-only")
            suffix = f"  [{', '.join(flags)}]" if flags else ""
            lines.append(f"  {rule.id:<26} {rule.summary}{suffix}")
    return "\n".join(lines)
