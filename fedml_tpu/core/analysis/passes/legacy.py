"""The four grep-lint contracts re-implemented as AST passes.

Each pass keeps its legacy pragma (``# lint_rng: allow`` ...) and its seam
exemptions, but matches on RESOLVED call targets instead of raw text — so
``from os import fsync as f`` / ``import msgpack as mp`` no longer dodge the
perf contract, while ``self.msgpack_restore(...)`` (a method that merely
shares the name) no longer needs the brittle ``(?<![\\w.])`` look-behind.

Files that fail to parse fall back to the original regex scan over
tokenizer-stripped lines — the legacy tools linted unparseable files raw
rather than skipping them, and the shims must keep that behaviour.
"""

from __future__ import annotations

import ast
import re
from typing import List

from ..framework import Analyzer, Finding, Rule, SourceFile
from ..imports import receiver_of, terminal_name

# ---------------------------------------------------------------------------
# rng


#: global-RNG entry points — seeding plus every draw that reads the global
#: stream; RandomState / default_rng / Generator are LOCAL and not listed
GLOBAL_RNG_DRAWS = frozenset(
    "seed choice rand randn randint random_integers random_sample random "
    "ranf sample permutation shuffle bytes normal standard_normal uniform "
    "binomial poisson exponential laplace gumbel beta gamma dirichlet "
    "multinomial multivariate_normal get_state set_state".split())

_RNG_FALLBACK = re.compile(
    r"(?<![\w.])(?:np|_np|numpy)\.random\.(?:%s)\s*\(" %
    "|".join(sorted(GLOBAL_RNG_DRAWS)))


class RngAnalyzer(Analyzer):
    """No global-NumPy-RNG use: every schedule-affecting draw must come from
    a local, explicitly-seeded generator (the lint_rng contract)."""

    name = "rng"
    legacy_pragma = "lint_rng: allow"
    rules = (Rule("rng-global-rng", "global NumPy RNG use", order=0),)

    def check(self, src: SourceFile) -> List[Finding]:
        rule = self.rules[0]
        if src.tree is None:
            return [self.finding(rule, src, lineno, "global NumPy RNG use")
                    for lineno, code in enumerate(src.code_lines, 1)
                    if _RNG_FALLBACK.search(code)]
        findings = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            q = src.imports.resolve(node.func)
            if (q and q.startswith("numpy.random.") and q.count(".") == 2
                    and q.rsplit(".", 1)[1] in GLOBAL_RNG_DRAWS):
                findings.append(self.finding(
                    rule, src, node.lineno, f"global NumPy RNG use: {q}"))
        return findings


# ---------------------------------------------------------------------------
# obs

_COUNTER_BAG_FALLBACK = re.compile(r"(?<![\w.])defaultdict\s*\(\s*int\s*\)")
_SINK_EMIT_FALLBACK = re.compile(r"(?i)\w*(?:sink|fan)\w*\s*\.\s*emit\s*\(")
_PRINTED_JSON_FALLBACK = re.compile(
    r"(?<![\w.])print\s*\(\s*json\s*\.\s*dumps\s*\(")
_DIRECT_RENDER_FALLBACK = re.compile(r"(?<![\w.])render_openmetrics\s*\(")
# built by concatenation so these sources never trip their own raw rule
_TELEMETRY_WIRE = re.compile("__obs_" + "telemetry__")
_SINKISH = re.compile(r"(?i)sink|fan")

_TELEMETRY_SEAM = "core/obs/telemetry.py"


class ObsAnalyzer(Analyzer):
    """One metrics surface, one sink fan, one exposition seam, one telemetry
    wire key (the lint_obs contract)."""

    name = "obs"
    legacy_pragma = "lint_obs: allow"
    exempt_parts = ("core/obs", "core/mlops")
    rules = (
        Rule("obs-counter-bag", "bare counter bag", order=0),
        Rule("obs-sink-emit", "direct sink emit", order=1),
        Rule("obs-printed-json", "printed metric json", order=2),
        Rule("obs-direct-render", "direct registry render", order=3),
        Rule("obs-telemetry-key", "telemetry wire key", raw=True, order=4),
    )

    def _is_seam(self, src: SourceFile) -> bool:
        return src.path.replace("\\", "/").endswith("/" + _TELEMETRY_SEAM)

    def check(self, src: SourceFile) -> List[Finding]:
        exempt = self.is_exempt(src.path)
        seam = self._is_seam(src)
        if exempt and seam:
            return []  # the owning module spells the key freely
        findings = []
        if not exempt:
            if src.tree is None:
                findings.extend(self._fallback(src))
            else:
                findings.extend(self._check_ast(src))
        if not seam:
            rule = self.rule_by_id("obs-telemetry-key")
            for lineno, raw in enumerate(src.raw_lines, 1):
                if _TELEMETRY_WIRE.search(raw):
                    findings.append(self.finding(
                        rule, src, lineno,
                        "telemetry wire key spelled outside "
                        "core/obs/telemetry.py"))
        return findings

    def _check_ast(self, src: SourceFile) -> List[Finding]:
        findings = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            q = src.imports.resolve(node.func)
            term = terminal_name(node.func)
            if (q in ("collections.defaultdict", "defaultdict")
                    and len(node.args) == 1 and not node.keywords
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id == "int"):
                findings.append(self.finding(
                    self.rule_by_id("obs-counter-bag"), src, node.lineno,
                    "bare counter bag: defaultdict(int) bypasses the "
                    "metrics registry"))
            elif term == "emit":
                recv = receiver_of(node.func)
                recv_name = terminal_name(recv) if recv is not None else None
                if recv_name and _SINKISH.search(recv_name):
                    findings.append(self.finding(
                        self.rule_by_id("obs-sink-emit"), src, node.lineno,
                        f"direct sink emit: {recv_name}.emit bypasses the "
                        "mlops fan"))
            elif q == "print" and node.args:
                inner = node.args[0]
                if (isinstance(inner, ast.Call)
                        and src.imports.resolve(inner.func) == "json.dumps"):
                    findings.append(self.finding(
                        self.rule_by_id("obs-printed-json"), src, node.lineno,
                        "printed metric json races the bench driver's "
                        "stdout contract"))
            elif (q and q.rsplit(".", 1)[-1] == "render_openmetrics"
                  and q.split(".", 1)[0] not in ("self", "cls")):
                findings.append(self.finding(
                    self.rule_by_id("obs-direct-render"), src, node.lineno,
                    "direct registry render: exposition belongs to the "
                    "core/obs exporter"))
        return findings

    def _fallback(self, src: SourceFile) -> List[Finding]:
        findings = []
        for lineno, code in enumerate(src.code_lines, 1):
            if _COUNTER_BAG_FALLBACK.search(code):
                findings.append(self.finding(
                    self.rule_by_id("obs-counter-bag"), src, lineno,
                    "bare counter bag"))
            if _SINK_EMIT_FALLBACK.search(code):
                findings.append(self.finding(
                    self.rule_by_id("obs-sink-emit"), src, lineno,
                    "direct sink emit"))
            if _PRINTED_JSON_FALLBACK.search(code):
                findings.append(self.finding(
                    self.rule_by_id("obs-printed-json"), src, lineno,
                    "printed metric json"))
            if _DIRECT_RENDER_FALLBACK.search(code):
                findings.append(self.finding(
                    self.rule_by_id("obs-direct-render"), src, lineno,
                    "direct registry render"))
        return findings


# ---------------------------------------------------------------------------
# agg

_TREEMAP_STAR_FALLBACK = re.compile(r"tree_map\s*\(\s*lambda\s*\*")
_PSEUDOGRAD_FALLBACK = re.compile(
    r"tree_map\s*\(\s*lambda\s+\w+\s*,\s*\w+\s*:\s*\w+\s*-\s*\w+")
_APPLY_UPDATES_FALLBACK = re.compile(r"(?<![\w])apply_updates\s*\(")

#: files allowed to spell the host server-optimizer tail: the replicated
#: oracle (core/aggregate is analyzer-exempt), the sp/fedopt reference
#: implementation, the compiled round plane, and the in-mesh strategies
_SERVER_OPT_SEAMS = ("simulation/sp/fedopt", "parallel/agg_plane.py",
                     "simulation/xla/algorithms.py")


class AggAnalyzer(Analyzer):
    """No hand-rolled star-lambda tree_map aggregation loops outside
    core/aggregate and the compiled agg plane, and no host server-optimizer
    round tails (pseudo-gradient fold + optax apply) outside the sanctioned
    seams (the lint_agg contract)."""

    name = "agg"
    legacy_pragma = "lint_agg: allow"
    exempt_files = ("core/aggregate.py",)
    rules = (Rule("agg-host-treemap", "host tree_map aggregation loop",
                  order=0),
             Rule("agg-server-opt-host", "host server-optimizer round loop",
                  order=1))

    def check(self, src: SourceFile) -> List[Finding]:
        findings = self._treemap_findings(src)
        findings.extend(self._server_opt_findings(src))
        return findings

    def _treemap_findings(self, src: SourceFile) -> List[Finding]:
        rule = self.rule_by_id("agg-host-treemap")
        if src.tree is None:
            return [self.finding(rule, src, lineno,
                                 "host tree_map aggregation loop")
                    for lineno, code in enumerate(src.code_lines, 1)
                    if _TREEMAP_STAR_FALLBACK.search(code)]
        findings = []
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "tree_map"
                    and node.args
                    and isinstance(node.args[0], ast.Lambda)
                    and node.args[0].args.vararg is not None):
                findings.append(self.finding(
                    rule, src, node.lineno,
                    "host tree_map aggregation loop: star-lambda fold "
                    "belongs to core/aggregate or the agg plane"))
        return findings

    def _server_opt_findings(self, src: SourceFile) -> List[Finding]:
        """A function that both folds a pseudo-gradient (two-arg lambda
        subtraction under tree_map) AND applies an optax update is a host
        server-optimizer round tail — those belong to
        ``core/aggregate.host_server_round_update`` or the sharded round
        plane, where the op chain is pinned bit-exact against the compiled
        program."""
        rule = self.rule_by_id("agg-server-opt-host")
        norm = src.path.replace("\\", "/")
        if any(seam in norm for seam in _SERVER_OPT_SEAMS):
            return []
        msg = ("host server-optimizer round loop: the pseudo-gradient tail "
               "belongs to core/aggregate.host_server_round_update or the "
               "sharded round plane")
        if src.tree is None:
            if not any(_PSEUDOGRAD_FALLBACK.search(c)
                       for c in src.code_lines):
                return []
            return [self.finding(rule, src, lineno, msg)
                    for lineno, code in enumerate(src.code_lines, 1)
                    if _APPLY_UPDATES_FALLBACK.search(code)]
        by_line = {}
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            pseudograd, steps = [], False
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                term = terminal_name(node.func)
                if (term == "tree_map" and node.args
                        and isinstance(node.args[0], ast.Lambda)):
                    lam = node.args[0]
                    if (len(lam.args.args) == 2 and lam.args.vararg is None
                            and isinstance(lam.body, ast.BinOp)
                            and isinstance(lam.body.op, ast.Sub)):
                        pseudograd.append(node.lineno)
                elif term == "apply_updates":
                    steps = True
            if steps:
                for lineno in pseudograd:
                    by_line[lineno] = self.finding(rule, src, lineno, msg)
        return list(by_line.values())


# ---------------------------------------------------------------------------
# perf

_STRAY_FSYNC_FALLBACK = re.compile(r"(?<![\w.])os\s*\.\s*fsync\s*\(")
_HOT_CODEC_FALLBACK = re.compile(
    r"(?<![\w.])(?:msgpack_restore|msgpack_serialize)\s*\("
    r"|(?<![\w.])msgpack\s*\.\s*(?:packb|unpackb)\s*\(")

_CODEC_BARE = frozenset({"msgpack_restore", "msgpack_serialize"})
_CODEC_QUALIFIED = frozenset({"msgpack.packb", "msgpack.unpackb"})


class PerfAnalyzer(Analyzer):
    """No stray fsyncs outside the durability seam, no hot-path msgpack
    codecs outside the framer/decoder (the lint_perf contract)."""

    name = "perf"
    legacy_pragma = "lint_perf: allow"
    exempt_parts = ("core/obs", "core/checkpoint.py", "core/ingest.py")
    rules = (
        Rule("perf-stray-fsync",
             "per-record fsync outside the durability seam", order=0),
        Rule("perf-hot-codec",
             "hot-path msgpack codec outside the seams", order=1),
    )

    def check(self, src: SourceFile) -> List[Finding]:
        if src.tree is None:
            return self._fallback(src)
        findings = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            q = src.imports.resolve(node.func)
            if q is None:
                continue
            if q == "os.fsync":
                findings.append(self.finding(
                    self.rule_by_id("perf-stray-fsync"), src, node.lineno,
                    "per-record fsync outside the durability seam"))
            elif (q in _CODEC_QUALIFIED or q in _CODEC_BARE
                  or (q.rsplit(".", 1)[-1] in _CODEC_BARE
                      and q.split(".", 1)[0] == "flax")):
                # dotted lookalikes (self.msgpack_restore, a method that
                # merely shares the name) are deliberately not codec calls
                findings.append(self.finding(
                    self.rule_by_id("perf-hot-codec"), src, node.lineno,
                    f"hot-path msgpack codec outside the seams: {q}"))
        return findings

    def _fallback(self, src: SourceFile) -> List[Finding]:
        findings = []
        for lineno, code in enumerate(src.code_lines, 1):
            if _STRAY_FSYNC_FALLBACK.search(code):
                findings.append(self.finding(
                    self.rule_by_id("perf-stray-fsync"), src, lineno,
                    "per-record fsync outside the durability seam"))
            if _HOT_CODEC_FALLBACK.search(code):
                findings.append(self.finding(
                    self.rule_by_id("perf-hot-codec"), src, lineno,
                    "hot-path msgpack codec outside the seams"))
        return findings
