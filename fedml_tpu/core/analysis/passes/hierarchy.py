"""Partial-reduction seam guard for the hierarchical fan-in tier.

The hierarchy's bit-identity contract (``docs/HIERARCHY.md``) holds
because every partial reduction is evaluated by ONE arithmetic seam: the
:class:`~fedml_tpu.core.hierarchy.plan.HierarchyPlan` routing into the
host fold (``core/aggregate.py``) or the compiled plane
(``parallel/agg_plane.py``).  A ``partial_fold`` / ``partial_reduce`` /
``combine_partials`` call ANYWHERE else is how the contract rots: a
second call site picks its own block order or its own total, and the
tree deployment silently stops matching the flat one.

* ``hierarchy-reduce-seam`` — a partial-reduction entry point invoked
  outside ``core/hierarchy/``, ``core/aggregate.py`` and
  ``parallel/agg_plane.py``.  Pragmas require a justification
  (``# fedlint: allow[hierarchy-reduce-seam] — ...``).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from ..framework import Analyzer, Finding, Rule, SourceFile

# the seam: the only modules that may invoke a partial reduction
_SEAM_PARTS = ("core/hierarchy",)
_SEAM_FILES = ("core/aggregate.py", "parallel/agg_plane.py")

# the partial-reduction entry points the seam owns
_SEAM_CALLS = frozenset(
    {"partial_fold", "combine_partials", "partial_reduce", "block_partial"})


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class HierarchyReduceSeamAnalyzer(Analyzer):
    """Flags partial-reduction calls outside the hierarchy seam."""

    name = "hierarchy"
    rules = (
        Rule("hierarchy-reduce-seam",
             "partial reduction invoked outside the hierarchy seam",
             requires_justification=True, order=0),
    )

    def _exempt(self, path: str) -> bool:
        # fixtures opt IN by basename, overriding the path exemption
        if os.path.basename(path).startswith("hier_"):
            return False
        norm = os.path.normpath(os.path.abspath(path)).replace(os.sep, "/")
        if any(f"/{part}/" in norm or norm.endswith(f"/{part}")
               for part in _SEAM_PARTS):
            return True
        return any(norm.endswith(f"/{f}") for f in _SEAM_FILES)

    def check(self, src: SourceFile) -> List[Finding]:
        if src.tree is None or self._exempt(src.path):
            return []
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal_name(node.func)
            if name not in _SEAM_CALLS:
                continue
            findings.append(self.finding(
                self.rules[0], src, node.lineno,
                f"'{name}' called outside the hierarchy seam "
                "(core/hierarchy, core/aggregate.py, parallel/agg_plane.py) "
                "— a second partial-reduction site can pick its own block "
                "order or total and break the tree/flat bit-identity "
                "contract; route through HierarchyPlan or justify"))
        findings.sort(key=Finding.sort_key)
        return findings
