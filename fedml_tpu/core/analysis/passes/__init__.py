"""Built-in fedlint passes: the four ported lint contracts plus the race,
ack-ordering, purity, and mesh-staleness analyzers."""

from __future__ import annotations

from typing import List

from ..framework import Analyzer
from .ack_order import AckDurabilityAnalyzer
from .chunking import ChunkReassemblySeamAnalyzer
from .health import HealthSeamAnalyzer
from .hierarchy import HierarchyReduceSeamAnalyzer
from .legacy import AggAnalyzer, ObsAnalyzer, PerfAnalyzer, RngAnalyzer
from .meshguard import MeshStaleProgramAnalyzer
from .purity import PurityAnalyzer
from .races import ThreadOwnershipAnalyzer
from .security import SecHostFallbackAnalyzer

__all__ = [
    "AckDurabilityAnalyzer", "AggAnalyzer", "ChunkReassemblySeamAnalyzer",
    "HealthSeamAnalyzer", "HierarchyReduceSeamAnalyzer",
    "MeshStaleProgramAnalyzer", "ObsAnalyzer", "PerfAnalyzer",
    "PurityAnalyzer", "RngAnalyzer", "SecHostFallbackAnalyzer",
    "ThreadOwnershipAnalyzer", "build_analyzers",
]


def build_analyzers() -> List[Analyzer]:
    """Fresh instances of every built-in pass, in reporting order."""
    return [
        RngAnalyzer(),
        ObsAnalyzer(),
        AggAnalyzer(),
        PerfAnalyzer(),
        ThreadOwnershipAnalyzer(),
        AckDurabilityAnalyzer(),
        PurityAnalyzer(),
        MeshStaleProgramAnalyzer(),
        SecHostFallbackAnalyzer(),
        HierarchyReduceSeamAnalyzer(),
        ChunkReassemblySeamAnalyzer(),
        HealthSeamAnalyzer(),
    ]
