"""Ack-durability ordering checker: "ack implies journaled", statically.

PR 4 established the durability contract (an acked upload is already in the
journal) and PR 10 stretched it across the staged ingest pipeline
(``deferred_ack_scope`` tickets + the group-commit journal).  Chaos tests
exercise the contract dynamically; this pass pins it statically so the
hierarchical aggregator tier can't silently break the ordering.

Scope is self-selecting: any function whose body calls an ack primitive
(``_send_ack`` / ``send_ack``).  Within such a function the pass walks
calls in source order — an optimistic linearization: branches are read
top-to-bottom and assumed reachable — and requires every ack call to be
preceded by a durability marker:

* ``deferred_ack_scope(...)`` — the ticketed deferral seam (acks inside the
  scope are withheld until the journal tickets resolve);
* a journal append (``<...journal...>.append/append_async/append_blob*``)
  or a ``_journal_upload(...)`` helper — the write is durable (or ticketed)
  before the ack;
* a ``dispatch(...)`` hand-off — ordering responsibility moved to the
  handler seam, which itself journals before acking (and is checked where
  it is defined).

Nested functions are separate scopes: a callback that acks must justify its
own ordering (typically with a pragma explaining which completion event
implies durability).  Lambdas are not analyzed — keep ack logic out of
lambdas.  The ``ack-before-journal`` pragma requires a justification.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List

from ..framework import Analyzer, Finding, Rule, SourceFile
from ..imports import receiver_of, terminal_name

_ACK_NAMES = frozenset({"_send_ack", "send_ack"})
_SCOPE_MARKERS = frozenset({"deferred_ack_scope"})
_HANDOFF_MARKERS = frozenset({"dispatch", "_dispatch",
                              "_journal_upload", "journal_upload"})
_JOURNAL_APPENDS = frozenset({"append", "append_async", "append_blob",
                              "append_blob_async"})
_JOURNALISH = re.compile(r"(?i)journal")


def _calls_in_order(stmts, *, skip_nested: bool = True) -> Iterator[ast.Call]:
    """Calls in source order; nested def/lambda bodies excluded (they run
    later, on someone else's schedule)."""

    def visit(node: ast.AST) -> Iterator[ast.Call]:
        if skip_nested and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            yield node
        for child in ast.iter_child_nodes(node):
            yield from visit(child)

    for stmt in stmts:
        yield from visit(stmt)


def _receiver_is_journalish(src: SourceFile, call: ast.Call) -> bool:
    recv = receiver_of(call.func)
    while recv is not None:
        name = terminal_name(recv)
        if name is not None and _JOURNALISH.search(name):
            return True
        recv = recv.value if isinstance(recv, ast.Attribute) else None
    return False


def _is_durability_marker(src: SourceFile, call: ast.Call) -> bool:
    term = terminal_name(call.func)
    if term is None:
        return False
    if term in _SCOPE_MARKERS or term in _HANDOFF_MARKERS:
        return True
    if term in _JOURNAL_APPENDS and _receiver_is_journalish(src, call):
        return True
    return False


class AckDurabilityAnalyzer(Analyzer):
    """Any path reaching an ack before a journal append / deferral ticket /
    dispatch hand-off is a finding."""

    name = "ack"
    rules = (Rule("ack-before-journal",
                  "ack reachable before a durability marker",
                  requires_justification=True, order=0),)

    def check(self, src: SourceFile) -> List[Finding]:
        if src.tree is None:
            return []
        findings: List[Finding] = []
        rule = self.rules[0]
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            calls = list(_calls_in_order(node.body))
            if not any(terminal_name(c.func) in _ACK_NAMES for c in calls):
                continue
            marker_seen = False
            for call in calls:
                if _is_durability_marker(src, call):
                    marker_seen = True
                    continue
                if terminal_name(call.func) in _ACK_NAMES and not marker_seen:
                    findings.append(self.finding(
                        rule, src, call.lineno,
                        f"{node.name}() acks before any journal append, "
                        "deferred_ack_scope ticket, or dispatch hand-off — "
                        "an acked upload must already be durable"))
        findings.sort(key=Finding.sort_key)
        return findings
