"""Thread-ownership race detector.

Scope is self-selecting: only classes that spawn a thread onto one of their
own methods (``threading.Thread(target=self._worker)``) are analyzed — a
class with no threads has no cross-thread state to get wrong.

For each such class the pass:

1. collects the thread ENTRY methods (the ``target=`` of every Thread the
   class creates) and assigns each a role — the method name by default,
   overridable with ``# thread-role: <name>`` on the ``def`` line;
2. walks the intra-class call graph (``self.method()`` edges) from each
   entry: a method reachable from an entry runs in that entry's thread
   context; every other method is assumed to run on the caller's ("main")
   thread;
3. records every ``self.<attr>`` access with its context set, whether it is
   a write (assign / augassign / ``del`` / subscript store), whether it
   happens inside ``with self.<lock>:``, and whether it is ``__init__``
   publication (writes in ``__init__`` happen-before ``Thread.start`` and
   are not shared-state writes);
4. reads ownership annotations: ``# owned-by: <role>`` on any line that
   touches ``self.<attr>`` declares the attribute's owning context
   (``main`` for caller-thread state, or a thread role such as
   ``transport`` / ``dispatch`` / ``committer`` / ``exporter``).

Two rules, both requiring a justification on their pragmas:

* ``race-unannotated-shared`` — an attribute is written outside
  ``__init__``, is touched from two or more thread contexts, has no
  ownership annotation, and at least one write holds no lock;
* ``race-cross-thread-write`` — an annotated attribute is written, without
  a lock, from a context that is not its owner.

Lock detection: a ``with self.<attr>:`` block where the attribute name
looks lock-ish (lock/cond/mutex/sem) or was assigned a
``threading.Lock/RLock/Condition/Semaphore``.  Known limitations, by
design: mutator METHOD calls (``self.buf.append(x)``) are not writes (too
many false positives on queues that are themselves thread-safe), and
reads are not checked for lock discipline — the annotation plus write-side
checking is the contract this pass pins.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..framework import Analyzer, Finding, Rule, SourceFile

_ROLE_COMMENT = re.compile(r"#\s*owned-by:\s*([A-Za-z_][\w.-]*)")
_THREAD_ROLE_COMMENT = re.compile(r"#\s*thread-role:\s*([A-Za-z_][\w.-]*)")
_LOCKISH = re.compile(r"(?i)lock|cond|mutex|sem")
_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
})

MAIN_CONTEXT = "main"


class _Access:
    __slots__ = ("attr", "method", "lineno", "write", "locked", "init")

    def __init__(self, attr: str, method: str, lineno: int, write: bool,
                 locked: bool, init: bool):
        self.attr = attr
        self.method = method
        self.lineno = lineno
        self.write = write
        self.locked = locked
        self.init = init


def _method_defs(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    defs: Dict[str, ast.AST] = {}
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    return defs


def _self_attr(node: ast.AST) -> Optional[str]:
    """``attr`` when ``node`` is ``self.<attr>``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _AccessCollector(ast.NodeVisitor):
    """Walks one method body recording self.<attr> accesses with the
    enclosing-lock state.  Nested defs are included: they execute in the
    enclosing method's context unless handed to another thread, and the
    conservative attribution keeps callbacks visible."""

    def __init__(self, method: str, init: bool, lock_attrs: Set[str]):
        self.method = method
        self.init = init
        self.lock_attrs = lock_attrs
        self.depth = 0  # >0 while inside `with self.<lock>:`
        self.accesses: List[_Access] = []
        self._write_targets: Set[int] = set()

    def _record(self, attr: str, lineno: int, write: bool):
        self.accesses.append(_Access(attr, self.method, lineno, write,
                                     self.depth > 0, self.init))

    def _mark_targets(self, node: ast.AST):
        attr = _self_attr(node)
        if attr is not None:
            self._record(attr, node.lineno, True)
            return
        if isinstance(node, ast.Subscript):
            inner = _self_attr(node.value)
            if inner is not None:
                self._record(inner, node.lineno, True)
                return
            self.visit(node.value)
            self.visit(node.slice)
            return
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                self._mark_targets(elt)
            return
        if isinstance(node, ast.Starred):
            self._mark_targets(node.value)
            return
        self.visit(node)

    def visit_Assign(self, node: ast.Assign):
        self.visit(node.value)
        for target in node.targets:
            self._mark_targets(target)

    def visit_AugAssign(self, node: ast.AugAssign):
        self.visit(node.value)
        self._mark_targets(node.target)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self.visit(node.value)
            self._mark_targets(node.target)

    def visit_Delete(self, node: ast.Delete):
        for target in node.targets:
            self._mark_targets(target)

    def visit_With(self, node: ast.With):
        holds = False
        for item in node.items:
            ctx = item.context_expr
            self.visit(ctx)
            attr = _self_attr(ctx)
            if attr is not None and attr in self.lock_attrs:
                holds = True
            if item.optional_vars is not None:
                self._mark_targets(item.optional_vars)
        if holds:
            self.depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if holds:
            self.depth -= 1

    visit_AsyncWith = visit_With

    def visit_Attribute(self, node: ast.Attribute):
        attr = _self_attr(node)
        if attr is not None:
            self._record(attr, node.lineno, False)
        self.generic_visit(node)


class ThreadOwnershipAnalyzer(Analyzer):
    """Flags unannotated shared mutable attributes and cross-thread writes
    in thread-spawning classes."""

    name = "races"
    rules = (
        Rule("race-unannotated-shared",
             "shared mutable attribute without ownership annotation",
             requires_justification=True, order=0),
        Rule("race-cross-thread-write",
             "write to an owned attribute from a foreign thread context",
             requires_justification=True, order=1),
    )

    def check(self, src: SourceFile) -> List[Finding]:
        if src.tree is None:
            return []
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(src, node))
        return findings

    # -- per-class analysis -------------------------------------------------

    def _check_class(self, src: SourceFile,
                     cls: ast.ClassDef) -> List[Finding]:
        methods = _method_defs(cls)
        if not methods:
            return []
        entries = self._thread_entries(src, cls, methods)
        if not entries:
            return []  # no threads spawned onto own methods: out of scope

        lock_attrs = self._lock_attrs(src, methods)
        contexts = self._method_contexts(methods, entries)

        accesses: List[_Access] = []
        ownership: Dict[str, Tuple[str, int]] = {}
        for mname, mdef in methods.items():
            collector = _AccessCollector(mname, mname == "__init__",
                                         lock_attrs)
            for stmt in mdef.body:
                collector.visit(stmt)
            accesses.extend(collector.accesses)
        for acc in accesses:
            m = _ROLE_COMMENT.search(src.raw_line(acc.lineno))
            if m and acc.attr not in ownership:
                ownership[acc.attr] = (m.group(1), acc.lineno)

        by_attr: Dict[str, List[_Access]] = {}
        for acc in accesses:
            by_attr.setdefault(acc.attr, []).append(acc)

        findings: List[Finding] = []
        for attr, accs in sorted(by_attr.items()):
            if attr in lock_attrs:
                continue  # the locks themselves are safely shared
            shared = [a for a in accs if not a.init]
            ctxs: Set[str] = set()
            for a in shared:
                ctxs.update(contexts.get(a.method, {MAIN_CONTEXT}))
            writes = [a for a in shared if a.write]
            owner = ownership.get(attr)
            if owner is None:
                if len(ctxs) < 2 or not writes:
                    continue
                unlocked = [w for w in writes if not w.locked]
                if unlocked:
                    w = min(unlocked, key=lambda a: a.lineno)
                    findings.append(self.finding(
                        self.rules[0], src, w.lineno,
                        f"{cls.name}.{attr} is written in {w.method}() and "
                        f"touched from contexts {sorted(ctxs)} with no lock "
                        "held and no '# owned-by:' annotation"))
            else:
                role = owner[0]
                for w in writes:
                    wctx = contexts.get(w.method, {MAIN_CONTEXT})
                    if role not in wctx and not w.locked:
                        findings.append(self.finding(
                            self.rules[1], src, w.lineno,
                            f"{cls.name}.{attr} is owned by '{role}' but "
                            f"written from {w.method}() (context "
                            f"{sorted(wctx)}) without a lock"))
        findings.sort(key=Finding.sort_key)
        return findings

    # -- scope discovery ----------------------------------------------------

    def _thread_entries(self, src: SourceFile, cls: ast.ClassDef,
                        methods: Dict[str, ast.AST]) -> Dict[str, str]:
        """method name -> role, for every Thread(target=self.<method>)."""
        entries: Dict[str, str] = {}
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            q = src.imports.resolve(node.func)
            if q != "threading.Thread":
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                attr = _self_attr(kw.value)
                if attr is not None and attr in methods:
                    mdef = methods[attr]
                    m = _THREAD_ROLE_COMMENT.search(
                        src.raw_line(mdef.lineno))
                    entries[attr] = m.group(1) if m else attr.lstrip("_")
        return entries

    def _lock_attrs(self, src: SourceFile,
                    methods: Dict[str, ast.AST]) -> Set[str]:
        locks: Set[str] = set()
        for mdef in methods.values():
            for node in ast.walk(mdef):
                if isinstance(node, ast.Assign):
                    for target in node.targets:
                        attr = _self_attr(target)
                        if attr is None:
                            continue
                        if _LOCKISH.search(attr):
                            locks.add(attr)
                        elif (isinstance(node.value, ast.Call)
                              and src.imports.resolve(node.value.func)
                              in _LOCK_CTORS):
                            locks.add(attr)
        return locks

    def _method_contexts(self, methods: Dict[str, ast.AST],
                         entries: Dict[str, str]) -> Dict[str, Set[str]]:
        """Each method's thread-context set: entry roles for methods
        reachable from an entry, MAIN_CONTEXT otherwise."""
        edges: Dict[str, Set[str]] = {m: set() for m in methods}
        for mname, mdef in methods.items():
            for node in ast.walk(mdef):
                if isinstance(node, ast.Call):
                    callee = _self_attr(node.func)
                    if callee is not None and callee in methods:
                        edges[mname].add(callee)
        contexts: Dict[str, Set[str]] = {m: set() for m in methods}
        for entry, role in entries.items():
            stack, seen = [entry], {entry}
            while stack:
                cur = stack.pop()
                contexts[cur].add(role)
                for nxt in edges[cur]:
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
        for m in methods:
            if not contexts[m]:
                contexts[m] = {MAIN_CONTEXT}
        return contexts
