"""Liveness-bookkeeping seam guard for the health & SLO plane.

The health plane stays deterministic and exactly-once because ONE seam
owns liveness state: ``core/obs/health.py`` holds every watchdog's
``last_beat``, decides expiry against the injectable clock, and is the
only place allowed to poll ``Thread.is_alive()``.  A second site that
keeps its own ``last_heartbeat = time.monotonic()`` or polls thread
liveness directly forks the plane: its deadline arithmetic runs on the
wall clock instead of the injected one (the chaos legs stop being
deterministic), its expiry fires zero or twice instead of once, and its
verdicts never reach the status machine, the ``health.*`` events, or the
flight dumps.  Subsystems express liveness ONLY through the facade
handles — ``obs.health_watchdog(...).beat()/idle()`` and
``obs.health_silence(...).note()``.

* ``health-seam`` — outside ``core/obs/health.py``: ``is_alive()``
  polled on a receiver assigned from ``threading.Thread(...)`` in the
  same file, or a timestamp store into a liveness-named attribute /
  subscript (``last_beat`` / ``last_heartbeat`` / ``last_seen_ts`` /
  ``heartbeat_ts``-style names) whose RHS is a clock call
  (``time.time`` / ``monotonic`` / ``perf_counter``).  Scoped tightly on
  purpose: ``multiprocessing.Process.is_alive()`` (a *process* health
  check, e.g. the MPI simulator's), round-number bookkeeping like the
  population registry's ``last_seen_round = int(round_idx)``, and the
  deploy daemon's on-disk heartbeat dict are all legitimate and stay
  clean.  Pragmas require a justification
  (``# fedlint: allow[health-seam] — ...``).
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Set

from ..framework import Analyzer, Finding, Rule, SourceFile

# the seam: the only module that may keep liveness clocks or poll threads
_SEAM_FILES = ("core/obs/health.py",)

# attribute / subscript names that smell like hand-rolled liveness clocks
_LIVENESS_NAME = re.compile(
    r"(last_(beat|heartbeat|seen|alive)|heartbeat)", re.IGNORECASE)

# clock calls whose result makes a store a liveness timestamp
_CLOCK_CALLS = frozenset({"time", "monotonic", "perf_counter"})


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_clock_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _terminal_name(node.func) in _CLOCK_CALLS)


def _store_name(target: ast.AST) -> Optional[str]:
    """The liveness-relevant name of a store target (plain name, attribute,
    or the container of a subscript)."""
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Subscript):
        return _terminal_name(target.value)
    return None


class HealthSeamAnalyzer(Analyzer):
    """Flags liveness bookkeeping outside the health-plane seam."""

    name = "health"
    rules = (
        Rule("health-seam",
             "thread liveness polled or heartbeat clock kept outside the "
             "health plane",
             requires_justification=True, order=0),
    )

    def _exempt(self, path: str) -> bool:
        # fixtures opt IN by basename, overriding the path exemption
        if os.path.basename(path).startswith("health_"):
            return False
        norm = os.path.normpath(os.path.abspath(path)).replace(os.sep, "/")
        return any(norm.endswith(f"/{f}") for f in _SEAM_FILES)

    def _flag(self, findings: List[Finding], src: SourceFile, lineno: int,
              what: str) -> None:
        findings.append(self.finding(
            self.rules[0], src, lineno,
            f"{what} outside the health seam (core/obs/health.py) — a "
            "second liveness site runs on the wall clock instead of the "
            "injected one and its expiry never reaches the status machine "
            "or the flight dumps; use obs.health_watchdog / "
            "obs.health_silence or justify"))

    def _thread_names(self, tree: ast.AST) -> Set[str]:
        """Terminal names assigned from ``threading.Thread(...)`` anywhere
        in the file (plain names and attribute targets alike)."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not (isinstance(node.value, ast.Call)
                    and _terminal_name(node.value.func) == "Thread"):
                continue
            for target in node.targets:
                name = _terminal_name(target)
                if name:
                    names.add(name)
        return names

    def check(self, src: SourceFile) -> List[Finding]:
        if src.tree is None or self._exempt(src.path):
            return []
        findings: List[Finding] = []
        thread_names = self._thread_names(src.tree)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "is_alive":
                receiver = _terminal_name(node.func.value)
                if receiver in thread_names:
                    self._flag(findings, src, node.lineno,
                               f"'{receiver}.is_alive()' polled on a "
                               "threading.Thread")
            elif isinstance(node, ast.Assign) \
                    and _is_clock_call(node.value):
                for target in node.targets:
                    name = _store_name(target)
                    if name and _LIVENESS_NAME.search(name):
                        self._flag(findings, src, node.lineno,
                                   f"heartbeat timestamp stored into "
                                   f"'{name}'")
        findings.sort(key=Finding.sort_key)
        return findings
