"""JAX purity/determinism pass.

Compiled programs (jit / pmap / vmap / scan bodies, including the agg
plane's cached executables) trace once and replay: anything impure inside
the traced body is silently frozen at trace time or breaks bit-exactness
against the host oracle.  This pass finds the compiled functions in a
module and flags the classic impurities inside them:

* ``purity-wall-clock`` — ``time.*`` / ``datetime.now`` inside a traced
  body reads trace-time, not run-time;
* ``purity-host-rng`` — stdlib ``random.*`` or ``numpy.random.*`` draws
  (``jax.random`` with explicit keys is the supported path);
* ``purity-host-numpy`` — host ``numpy`` calls applied to TRACED values
  (arguments data-dependent on the function's parameters).  Host numpy on
  static values (shapes, python scalars) is fine and not flagged —
  ``.shape`` / ``.dtype`` / ``.ndim`` / ``.size`` chains are treated as
  static and do not propagate taint;
* ``purity-unsorted-dict`` — iterating a traced dict's ``.items()`` /
  ``.keys()`` / ``.values()`` without ``sorted(...)`` feeds
  insertion-order-dependent structure into pytree construction;
* ``purity-donated-reuse`` — reading a value after it was passed in a
  donated argument position of a ``jax.jit(..., donate_argnums=...)``
  wrapper call: the buffer was surrendered to XLA and may alias the
  output.  Rebinding in the same statement
  (``x, s = step(x, s)``) un-consumes, matching the canonical pattern.

Compiled-function discovery: ``@jax.jit`` / ``@partial(jax.jit, ...)``
style decorators, local wrapping (``f2 = jax.jit(f)`` / ``jax.vmap(f)``),
and function-argument positions of ``lax.scan`` / ``while_loop`` /
``fori_loop`` / ``cond``.  Nested defs inside a compiled function are part
of its trace and checked with it.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..framework import Analyzer, Finding, Rule, SourceFile
from ..imports import terminal_name

_JIT_WRAPPERS = frozenset({
    "jax.jit", "jit", "jax.pmap", "pmap", "jax.vmap", "vmap",
    "jax.numpy.vectorize",
})
_PARTIAL = frozenset({"functools.partial", "partial"})
#: wrapper -> positional indices whose arguments are traced bodies
_FN_ARG_WRAPPERS = {
    "jax.lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "jax.lax.map": (0,),
}
#: attribute chains that stay static under tracing (no taint propagation)
_STATIC_ATTRS = frozenset({"shape", "dtype", "ndim", "size"})
_WALL_CLOCK_EXACT = frozenset({
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
})
_DICT_ITERS = frozenset({"items", "keys", "values"})


def _iter_statements(body) -> Iterator[ast.stmt]:
    """Statements in source order, recursing into blocks but not into
    nested function bodies (separate scopes)."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for _, value in ast.iter_fields(stmt):
            if isinstance(value, list):
                stmts = [v for v in value if isinstance(v, ast.stmt)]
                if stmts:
                    yield from _iter_statements(stmts)
                for v in value:
                    if isinstance(v, ast.excepthandler):
                        yield from _iter_statements(v.body)


def _calls_skip_nested(node: ast.AST) -> Iterator[ast.Call]:
    def visit(n: ast.AST) -> Iterator[ast.Call]:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(n, ast.Call):
            yield n
        for child in ast.iter_child_nodes(n):
            yield from visit(child)
    for child in ast.iter_child_nodes(node):
        yield from visit(child)
    if isinstance(node, ast.Call):
        yield node


def _donate_positions(call: ast.Call) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            return tuple(e.value for e in v.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, int))
    return ()


class PurityAnalyzer(Analyzer):
    """Flags impure constructs inside compiled (jit/scan/vmap) functions and
    donated-buffer reuse around jit wrapper calls."""

    name = "purity"
    rules = (
        Rule("purity-wall-clock",
             "wall-clock read inside a traced body", order=0),
        Rule("purity-host-rng",
             "host RNG draw inside a traced body", order=1),
        Rule("purity-host-numpy",
             "host numpy call on a traced value", order=2),
        Rule("purity-unsorted-dict",
             "unsorted dict iteration inside a traced body", order=3),
        Rule("purity-donated-reuse",
             "value read after being donated to a jit call", order=4),
    )

    def check(self, src: SourceFile) -> List[Finding]:
        if src.tree is None:
            return []
        findings: List[Finding] = []
        compiled = self._compiled_functions(src)
        # only the outermost compiled defs: nested defs inside a compiled
        # body are checked as part of that body's trace
        outer = [f for f in compiled
                 if not any(p in compiled for p in self._ancestors(src, f))]
        for fdef in outer:
            findings.extend(self._check_compiled(src, fdef))
        donated_attrs = self._donated_attrs(src)
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(
                    self._check_donated(src, node, donated_attrs))
        findings.sort(key=Finding.sort_key)
        return findings

    # -- compiled-function discovery ----------------------------------------

    def _ancestors(self, src: SourceFile, fdef: ast.AST):
        return self._parents.get(fdef, ())

    def _compiled_functions(self, src: SourceFile) -> Set[ast.AST]:
        tree = src.tree
        defs_by_name: Dict[str, ast.AST] = {}
        parents: Dict[ast.AST, Tuple[ast.AST, ...]] = {}

        def index(node: ast.AST, chain: Tuple[ast.AST, ...]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs_by_name[child.name] = child
                    parents[child] = chain
                    index(child, chain + (child,))
                else:
                    index(child, chain)

        index(tree, ())
        self._parents = parents

        compiled: Set[ast.AST] = set()
        for fdef in parents:
            for dec in getattr(fdef, "decorator_list", ()):
                if self._is_jit_expr(src, dec):
                    compiled.add(fdef)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            q = src.imports.resolve(node.func)
            if q in _JIT_WRAPPERS:
                if node.args and isinstance(node.args[0], ast.Name):
                    target = defs_by_name.get(node.args[0].id)
                    if target is not None:
                        compiled.add(target)
            elif q in _FN_ARG_WRAPPERS:
                for pos in _FN_ARG_WRAPPERS[q]:
                    if pos < len(node.args) and isinstance(
                            node.args[pos], ast.Name):
                        target = defs_by_name.get(node.args[pos].id)
                        if target is not None:
                            compiled.add(target)
        return compiled

    def _is_jit_expr(self, src: SourceFile, expr: ast.AST) -> bool:
        q = src.imports.resolve(expr)
        if q in _JIT_WRAPPERS:
            return True
        if isinstance(expr, ast.Call):
            fq = src.imports.resolve(expr.func)
            if fq in _JIT_WRAPPERS:
                return True
            if fq in _PARTIAL and expr.args:
                return src.imports.resolve(expr.args[0]) in _JIT_WRAPPERS
        return False

    # -- traced-body checks -------------------------------------------------

    def _check_compiled(self, src: SourceFile,
                        fdef: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        taint = self._tainted_names(fdef)
        for node in ast.walk(fdef):
            if not isinstance(node, ast.Call):
                continue
            q = src.imports.resolve(node.func)
            if q is not None:
                root = q.split(".", 1)[0]
                if root == "time" and "." in q:
                    findings.append(self.finding(
                        self.rule_by_id("purity-wall-clock"), src,
                        node.lineno,
                        f"{q} inside traced {fdef.name}() reads trace-time, "
                        "not run-time"))
                    continue
                if q in _WALL_CLOCK_EXACT:
                    findings.append(self.finding(
                        self.rule_by_id("purity-wall-clock"), src,
                        node.lineno,
                        f"{q} inside traced {fdef.name}()"))
                    continue
                if ((root == "random" and "." in q)
                        or q.startswith("numpy.random.")):
                    findings.append(self.finding(
                        self.rule_by_id("purity-host-rng"), src, node.lineno,
                        f"{q} inside traced {fdef.name}() — draw from "
                        "jax.random with an explicit key instead"))
                    continue
                if (root == "numpy" and "." in q
                        and not q.startswith("numpy.random.")
                        and self._any_tainted(node, taint)):
                    findings.append(self.finding(
                        self.rule_by_id("purity-host-numpy"), src,
                        node.lineno,
                        f"{q} applied to a traced value inside "
                        f"{fdef.name}() — use jax.numpy"))
                    continue
            term = terminal_name(node.func)
            if (term in _DICT_ITERS
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in taint
                    and not self._directly_sorted(fdef, node)):
                findings.append(self.finding(
                    self.rule_by_id("purity-unsorted-dict"), src,
                    node.lineno,
                    f"iteration over {node.func.value.id}.{term}() inside "
                    f"traced {fdef.name}() is insertion-order dependent — "
                    "wrap in sorted(...)"))
        return findings

    def _tainted_names(self, fdef: ast.AST) -> Set[str]:
        """Names data-dependent on the traced function's parameters,
        propagated through assignments in source order."""
        taint: Set[str] = set()
        for scope in ast.walk(fdef):
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                a = scope.args
                for arg in (list(a.posonlyargs) + list(a.args)
                            + list(a.kwonlyargs)):
                    taint.add(arg.arg)
                if a.vararg:
                    taint.add(a.vararg.arg)
                if a.kwarg:
                    taint.add(a.kwarg.arg)
        for stmt in _iter_statements(fdef.body):
            value = getattr(stmt, "value", None)
            if (isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign))
                    and value is not None
                    and self._expr_tainted(value, taint)):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for target in targets:
                    for name in ast.walk(target):
                        if isinstance(name, ast.Name):
                            taint.add(name.id)
            elif isinstance(stmt, ast.For) and self._expr_tainted(
                    stmt.iter, taint):
                for name in ast.walk(stmt.target):
                    if isinstance(name, ast.Name):
                        taint.add(name.id)
        return taint

    def _expr_tainted(self, expr: ast.AST, taint: Set[str]) -> bool:
        if isinstance(expr, ast.Attribute) and expr.attr in _STATIC_ATTRS:
            return False  # shapes/dtypes are static under trace
        if isinstance(expr, ast.Name):
            return expr.id in taint
        return any(self._expr_tainted(child, taint)
                   for child in ast.iter_child_nodes(expr))

    def _any_tainted(self, call: ast.Call, taint: Set[str]) -> bool:
        for arg in call.args:
            if self._expr_tainted(arg, taint):
                return True
        for kw in call.keywords:
            if self._expr_tainted(kw.value, taint):
                return True
        return False

    def _directly_sorted(self, fdef: ast.AST, call: ast.Call) -> bool:
        """True when the .items()/.keys()/.values() call is the immediate
        argument of sorted(...)."""
        for node in ast.walk(fdef):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "sorted"
                    and any(arg is call for arg in node.args)):
                return True
        return False

    # -- donated-buffer reuse -----------------------------------------------

    def _donated_attrs(self, src: SourceFile) -> Dict[str, Tuple[int, ...]]:
        """self.<attr> -> donated positions, for jit wrappers stored on
        instances (``self._step = jax.jit(step, donate_argnums=(0, 1))``)."""
        out: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not (isinstance(value, ast.Call)
                    and src.imports.resolve(value.func) in _JIT_WRAPPERS):
                continue
            positions = _donate_positions(value)
            if not positions:
                continue
            for target in node.targets:
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    out[target.attr] = positions
        return out

    def _check_donated(self, src: SourceFile, fdef: ast.AST,
                       donated_attrs: Dict[str, Tuple[int, ...]]
                       ) -> List[Finding]:
        findings: List[Finding] = []
        donated_locals: Dict[str, Tuple[int, ...]] = {}
        consumed: Dict[Tuple[str, str], int] = {}

        def key_for(expr: ast.AST) -> Optional[Tuple[str, str]]:
            if isinstance(expr, ast.Name):
                return ("name", expr.id)
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"):
                return ("attr", expr.attr)
            return None

        for stmt in _iter_statements(fdef.body):
            # 1) reads of already-consumed values in this statement
            if consumed:
                for node in ast.walk(stmt):
                    k = key_for(node)
                    if (k in consumed
                            and isinstance(getattr(node, "ctx", None),
                                           ast.Load)):
                        label = (k[1] if k[0] == "name"
                                 else f"self.{k[1]}")
                        findings.append(self.finding(
                            self.rule_by_id("purity-donated-reuse"), src,
                            node.lineno,
                            f"{label} is read after being donated at line "
                            f"{consumed[k]} — the buffer was surrendered "
                            "to XLA and may alias the output"))
                        consumed.pop(k)
            # 2) register local donated wrappers
            if isinstance(stmt, ast.Assign):
                value = stmt.value
                if (isinstance(value, ast.Call)
                        and src.imports.resolve(value.func)
                        in _JIT_WRAPPERS):
                    positions = _donate_positions(value)
                    if positions:
                        for target in stmt.targets:
                            if isinstance(target, ast.Name):
                                donated_locals[target.id] = positions
            # 3) new consumption by donated-wrapper calls in this statement
            for call in _calls_skip_nested(stmt):
                positions: Tuple[int, ...] = ()
                if (isinstance(call.func, ast.Name)
                        and call.func.id in donated_locals):
                    positions = donated_locals[call.func.id]
                else:
                    k = key_for(call.func)
                    if k is not None and k[0] == "attr" \
                            and k[1] in donated_attrs:
                        positions = donated_attrs[k[1]]
                for pos in positions:
                    if pos < len(call.args):
                        ak = key_for(call.args[pos])
                        if ak is not None:
                            consumed[ak] = call.lineno
            # 4) stores un-consume (the canonical x, s = step(x, s))
            for target in self._store_targets(stmt):
                consumed.pop(target, None)
        return findings

    def _store_targets(self, stmt: ast.stmt):
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.For):
            targets = [stmt.target]
        out = []

        def collect(node):
            if isinstance(node, (ast.Tuple, ast.List)):
                for elt in node.elts:
                    collect(elt)
            elif isinstance(node, ast.Starred):
                collect(node.value)
            elif isinstance(node, ast.Name):
                out.append(("name", node.id))
            elif (isinstance(node, ast.Attribute)
                  and isinstance(node.value, ast.Name)
                  and node.value.id == "self"):
                out.append(("attr", node.attr))

        for t in targets:
            collect(t)
        return out
