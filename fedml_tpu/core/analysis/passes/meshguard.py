"""Mesh-staleness detector for compiled-program caches.

An elastic resize (``ShardedRoundPlane.remesh``) re-shards every resident
buffer onto a new device mesh.  A compiled XLA program is specialized to
the shardings it was lowered with — executing a cached program against
re-sharded buffers is at best a silent full re-layout and at worst a
wrong-devices crash mid-round.  The aggregation plane's contract is that
every program-cache key BEGINS with the mesh fingerprint
(``self.mesh_key`` / ``mesh_fingerprint(...)``), so a resize re-keys
every lookup and a stale program can never be fetched.

This pass pins that contract statically:

* ``mesh-stale-program`` — a read from a program/plane cache (an
  ``X.get(...)`` call or an ``X[...]`` subscript load where ``X``'s
  terminal name looks like a compiled-object cache: ``_programs``,
  ``_ROUND_PROGRAMS``, ``_PLANES``, ...) inside a scope whose lexical
  function chain never references ``mesh_key`` or ``mesh_fingerprint``.
  The fetch site itself need not hash the mesh — building the key from
  ``self.mesh_key`` anywhere in the enclosing function is what the rule
  checks for — but a function that fetches compiled state with no mesh
  identity in sight is exactly the bug class a resize turns into a
  crash.

Cache-name scope is deliberately narrow (names ending in ``programs`` /
``planes``, case-insensitive, optional leading underscore): the rule
exists for compiled-executable caches, not every dict in the tree.
Writes (``X[k] = v``) and non-fetch methods (``.clear()``, ``.pop()``)
are not reads and are not flagged.  Pragmas require a justification —
a cache read that is provably mesh-invariant must say why.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set, Tuple

from ..framework import Analyzer, Finding, Rule, SourceFile

# terminal names that denote a compiled-program / plane cache
_CACHE_NAME = re.compile(r"(?i)^_?[a-z0-9_]*(program|plane)s$")

# identifiers that carry mesh identity into a cache key
_MESH_TOKENS = frozenset({"mesh_key", "mesh_fingerprint"})


def _terminal_name(node: ast.AST) -> Optional[str]:
    """The rightmost identifier of a Name / Attribute chain, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _ScopeWalker(ast.NodeVisitor):
    """Collects cache reads with their lexical function chain, and per-scope
    mesh-identity references.  Scope 0 is the module; each nested function
    pushes a new scope id so a read inside a closure is cleared by a mesh
    reference in ANY enclosing function (the key is often built outside the
    closure that performs the fetch)."""

    def __init__(self):
        self._stack: List[int] = [0]
        self._next_id = 1
        self.mesh_scopes: Set[int] = set()
        # (lineno, cache_name, scope chain at the read)
        self.reads: List[Tuple[int, str, Tuple[int, ...]]] = []

    def _enter_function(self, node: ast.AST):
        sid = self._next_id
        self._next_id += 1
        self._stack.append(sid)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _enter_function
    visit_AsyncFunctionDef = _enter_function

    def _note_mesh(self, name: Optional[str]):
        if name in _MESH_TOKENS:
            self.mesh_scopes.add(self._stack[-1])

    def visit_Name(self, node: ast.Name):
        self._note_mesh(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        self._note_mesh(node.attr)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        func = node.func
        if (isinstance(func, ast.Attribute) and func.attr == "get"):
            cache = _terminal_name(func.value)
            if cache is not None and _CACHE_NAME.match(cache):
                self.reads.append(
                    (node.lineno, cache, tuple(self._stack)))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        if isinstance(node.ctx, ast.Load):
            cache = _terminal_name(node.value)
            if cache is not None and _CACHE_NAME.match(cache):
                self.reads.append(
                    (node.lineno, cache, tuple(self._stack)))
        self.generic_visit(node)


class MeshStaleProgramAnalyzer(Analyzer):
    """Flags compiled-program cache reads whose enclosing scope never
    references the mesh fingerprint."""

    name = "meshguard"
    rules = (
        Rule("mesh-stale-program",
             "compiled-program cache read not keyed on the mesh fingerprint",
             requires_justification=True, order=0),
    )

    def check(self, src: SourceFile) -> List[Finding]:
        if src.tree is None:
            return []
        walker = _ScopeWalker()
        walker.visit(src.tree)
        findings: List[Finding] = []
        for lineno, cache, chain in walker.reads:
            if any(sid in walker.mesh_scopes for sid in chain):
                continue
            findings.append(self.finding(
                self.rules[0], src, lineno,
                f"read from compiled cache '{cache}' in a scope with no "
                "mesh_key/mesh_fingerprint reference — a remesh would "
                "serve a stale program here"))
        findings.sort(key=Finding.sort_key)
        return findings
