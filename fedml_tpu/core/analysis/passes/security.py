"""Host-fallback detector for the security/privacy planes.

PR 17 moved Byzantine filtering, DP noise, and the SecAgg finite-field
fold onto the compiled round path (``parallel/sec_plane``,
``core/mpc/inmesh``).  The host implementations are retained as bit-exact
oracles — but NEW host-side aggregation sneaking into ``core/security``,
``core/dp`` or ``core/mpc`` is exactly how the compiled plane rots: the
host copy drifts, the parity tests pin the old behavior, and the mesh
path silently stops being the one that runs.

* ``sec-host-fallback`` — inside the security/privacy modules
  (``core/security``, ``core/dp``, ``core/mpc``), either

  - a Python ``for`` loop that folds client payloads (iteration over an
    updates/grads/payloads/shares-shaped name with an accumulation in
    the body), or
  - a ``tree_map`` call in a lexical function chain that takes a client
    payload collection (an ``updates`` / ``raw_grad_list`` -shaped
    parameter) and carries no JAX-compute marker (``jnp`` / ``lax`` /
    ``jit`` / ``vmap`` / ``shard_map``) — a host pytree fold over
    client payloads, not a compiled one.

  Pragmas require a justification: a retained host oracle must say so
  (``# fedlint: allow[sec-host-fallback] — retained host oracle ...``).

Loops that merely inspect payloads (no accumulation) and ``tree_map``
calls inside jnp-using functions (compiled defense/attack math) are not
flagged — the rule targets the host *fold*, not every traversal.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Set, Tuple

from ..framework import Analyzer, Finding, Rule, SourceFile

# path fragments that put a file in the security/privacy plane; fixture
# files opt in by basename (sec_*.py)
_SCOPE_PARTS = ("core/security", "core/dp", "core/mpc")

# names that look like per-client payload COLLECTIONS (plural / _list /
# _dict forms only: a singular `client_update` is one intercepted update,
# not a fold candidate)
_PAYLOAD_NAME = re.compile(
    r"(?i)^((raw_)?(client_)?(grad|update|upload|payload|delta|share|mask)"
    r"(s|_list|_dict)|stack(ed)?|masked|weighted_updates)$")

# identifiers that mark a function as JAX-compute (its tree_map compiles)
_JAX_MARKERS = frozenset({"jnp", "lax", "jit", "vmap", "pmap", "shard_map"})

# accumulation carriers inside a fold body
_ACC_CALLS = frozenset({"mod", "add", "field_add", "_mod_add"})


def _terminal_name(node: ast.AST) -> Optional[str]:
    """The rightmost identifier of a Name / Attribute chain, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _iter_base_name(node: ast.AST) -> Optional[str]:
    """The payload collection a ``for`` iterates, through the common
    wrappers: ``enumerate(updates)``, ``sorted(payloads)``,
    ``self.masked.values()``/``.items()``."""
    while True:
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Name)
                    and fn.id in ("enumerate", "sorted", "list", "tuple",
                                  "reversed", "zip")
                    and node.args):
                node = node.args[0]
                continue
            if isinstance(fn, ast.Attribute) and fn.attr in ("values", "items"):
                node = fn.value
                continue
        return _terminal_name(node)


def _accumulates(body: List[ast.stmt]) -> bool:
    """True when the loop body carries a running fold: an augmented
    assignment, an additive BinOp, or a modular-add call."""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.AugAssign):
                return True
            if isinstance(node, ast.BinOp) and isinstance(
                    node.op, (ast.Add, ast.Sub)):
                return True
            if isinstance(node, ast.Call) and _terminal_name(
                    node.func) in _ACC_CALLS:
                return True
    return False


class _SecWalker(ast.NodeVisitor):
    """Collects payload-fold loops, tree_map calls with their lexical
    function chain, and per-scope JAX-compute references (same scope
    model as the meshguard pass: a marker in ANY enclosing function
    clears the call)."""

    def __init__(self):
        self._stack: List[int] = [0]
        self._next_id = 1
        self.jax_scopes: Set[int] = set()
        # scopes whose function signature takes a payload collection
        self.payload_scopes: Set[int] = set()
        self.fold_loops: List[Tuple[int, str]] = []
        # (lineno, scope chain at the call)
        self.tree_maps: List[Tuple[int, Tuple[int, ...]]] = []

    def _enter_function(self, node: ast.AST):
        sid = self._next_id
        self._next_id += 1
        a = node.args
        params = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        if any(_PAYLOAD_NAME.match(p) for p in params):
            self.payload_scopes.add(sid)
        self._stack.append(sid)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _enter_function
    visit_AsyncFunctionDef = _enter_function

    def _note_jax(self, name: Optional[str]):
        if name in _JAX_MARKERS:
            self.jax_scopes.add(self._stack[-1])

    def visit_Name(self, node: ast.Name):
        self._note_jax(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        self._note_jax(node.attr)
        self.generic_visit(node)

    def visit_For(self, node: ast.For):
        base = _iter_base_name(node.iter)
        if (base is not None and _PAYLOAD_NAME.match(base)
                and _accumulates(node.body)):
            self.fold_loops.append((node.lineno, base))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if _terminal_name(node.func) == "tree_map":
            self.tree_maps.append((node.lineno, tuple(self._stack)))
        self.generic_visit(node)


class SecHostFallbackAnalyzer(Analyzer):
    """Flags host-side aggregation folds in the security/privacy modules."""

    name = "sec"
    rules = (
        Rule("sec-host-fallback",
             "host-side aggregation fold in a security/privacy module",
             requires_justification=True, order=0),
    )

    def _in_scope(self, path: str) -> bool:
        norm = os.path.normpath(os.path.abspath(path)).replace(os.sep, "/")
        if os.path.basename(path).startswith("sec_"):
            return True
        return any(f"/{part}/" in norm or norm.endswith(f"/{part}")
                   for part in _SCOPE_PARTS)

    def check(self, src: SourceFile) -> List[Finding]:
        if src.tree is None or not self._in_scope(src.path):
            return []
        walker = _SecWalker()
        walker.visit(src.tree)
        findings: List[Finding] = []
        for lineno, base in walker.fold_loops:
            findings.append(self.finding(
                self.rules[0], src, lineno,
                f"host aggregation fold over '{base}' in a security/privacy "
                "module — client folds belong on the compiled plane "
                "(parallel/sec_plane, core/mpc/inmesh); a retained host "
                "oracle needs a justified pragma"))
        for lineno, chain in walker.tree_maps:
            if not any(sid in walker.payload_scopes for sid in chain):
                continue
            if any(sid in walker.jax_scopes for sid in chain):
                continue
            findings.append(self.finding(
                self.rules[0], src, lineno,
                "tree_map over a client payload collection with no "
                "JAX-compute marker in scope — a host pytree fold in a "
                "security/privacy module; move it onto the compiled plane "
                "or justify the host oracle"))
        findings.sort(key=Finding.sort_key)
        return findings
