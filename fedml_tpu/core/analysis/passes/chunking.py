"""Chunk-header / reassembly seam guard for resumable chunked uploads.

Chunked uploads stay resumable and exactly-once because ONE seam owns the
wire vocabulary: ``core/distributed/chunking.py`` builds and parses every
``comm_chunk`` header and mutates every reassembly buffer, and
``core/ingest.py`` re-exports the reassembler as the pipeline-facing
stage.  A second site that reads ``chunk_idx`` out of a message, or that
constructs chunk frames itself, forks the resume protocol: its idea of
stream identity, crc framing, or journal record shape drifts from the
reassembler's and the replay/dedup accounting silently stops being
exactly-once.

* ``chunk-reassembly-seam`` — a chunk wire-vocabulary literal used as a
  call argument / subscript key / comparison operand, or a chunk framing
  entry point (``ChunkReassembler`` / ``build_chunks`` /
  ``split_payload``) invoked, outside ``core/distributed/chunking.py``
  and ``core/ingest.py``.  Pragmas require a justification
  (``# fedlint: allow[chunk-reassembly-seam] — ...``).
  (:func:`~fedml_tpu.core.distributed.chunking.truncate_for_fault` is
  deliberately NOT guarded — it exists so the fault seam can tear frames
  WITHOUT parsing headers itself.)
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from ..framework import Analyzer, Finding, Rule, SourceFile

# the seam: the only modules that may parse chunk headers or touch
# reassembly buffers
_SEAM_FILES = ("core/distributed/chunking.py", "core/ingest.py")

# the chunk wire vocabulary (param keys + message types); literals only —
# every legitimate caller imports the constants from the seam instead
_CHUNK_KEYS = frozenset({
    "chunk_stream", "chunk_idx", "chunk_n", "chunk_data", "chunk_crc",
    "chunk_total", "chunk_inner_type", "comm_chunk", "comm_chunk_reset",
})

# framing/reassembly entry points the seam owns
_SEAM_CALLS = frozenset({"ChunkReassembler", "build_chunks", "split_payload"})


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _chunk_literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value in _CHUNK_KEYS:
        return node.value
    return None


class ChunkReassemblySeamAnalyzer(Analyzer):
    """Flags chunk-header parsing / framing outside the chunking seam."""

    name = "chunking"
    rules = (
        Rule("chunk-reassembly-seam",
             "chunk header parsed or reassembly invoked outside the "
             "chunking seam",
             requires_justification=True, order=0),
    )

    def _exempt(self, path: str) -> bool:
        # fixtures opt IN by basename, overriding the path exemption
        if os.path.basename(path).startswith("chunk_"):
            return False
        norm = os.path.normpath(os.path.abspath(path)).replace(os.sep, "/")
        return any(norm.endswith(f"/{f}") for f in _SEAM_FILES)

    def _flag(self, findings: List[Finding], src: SourceFile, lineno: int,
              what: str) -> None:
        findings.append(self.finding(
            self.rules[0], src, lineno,
            f"{what} outside the chunking seam "
            "(core/distributed/chunking.py, core/ingest.py) — a second "
            "chunk-parsing site forks the resume protocol and breaks the "
            "replay/dedup exactly-once accounting; import the seam's API "
            "or justify"))

    def check(self, src: SourceFile) -> List[Finding]:
        if src.tree is None or self._exempt(src.path):
            return []
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name in _SEAM_CALLS:
                    self._flag(findings, src, node.lineno,
                               f"'{name}' called")
                    continue
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    lit = _chunk_literal(arg)
                    if lit is not None:
                        self._flag(findings, src, node.lineno,
                                   f"chunk wire key '{lit}' passed")
            elif isinstance(node, ast.Subscript):
                lit = _chunk_literal(node.slice)
                if lit is not None:
                    self._flag(findings, src, node.lineno,
                               f"chunk wire key '{lit}' subscripted")
            elif isinstance(node, ast.Compare):
                for operand in [node.left] + list(node.comparators):
                    lit = _chunk_literal(operand)
                    if lit is not None:
                        self._flag(findings, src, node.lineno,
                                   f"chunk wire key '{lit}' compared")
        findings.sort(key=Finding.sort_key)
        return findings
