"""DP noise mechanisms over parameter pytrees.

Math parity with reference ``core/dp/mechanisms/gaussian.py:14-21`` (classic
Gaussian mechanism sigma = sqrt(2 ln(1.25/delta)) * sensitivity / epsilon) and
``laplace.py`` (scale = sensitivity / epsilon); implemented with ``jax.random``
splits per leaf so noising is pure, reproducible and jit-able on TPU.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

Pytree = Any


class Gaussian:
    def __init__(self, epsilon: float, delta: float, sensitivity: float = 1.0):
        if not 0 < float(delta) < 1:
            raise ValueError("delta must be in (0, 1)")
        if float(epsilon) <= 0:
            raise ValueError("epsilon must be positive")
        self.epsilon = float(epsilon)
        self.delta = float(delta)
        self.sensitivity = float(sensitivity)
        self.sigma = self.compute_sigma(self.epsilon, self.delta, self.sensitivity)

    @staticmethod
    def compute_sigma(epsilon: float, delta: float, sensitivity: float) -> float:
        return math.sqrt(2.0 * math.log(1.25 / delta)) * sensitivity / epsilon

    def add_noise(self, tree: Pytree, key: jax.Array) -> Pytree:
        return _add_noise_tree(tree, key, lambda k, shape: self.sigma * jax.random.normal(k, shape))


class Laplace:
    def __init__(self, epsilon: float, sensitivity: float = 1.0):
        if float(epsilon) <= 0:
            raise ValueError("epsilon must be positive")
        self.epsilon = float(epsilon)
        self.sensitivity = float(sensitivity)
        self.scale = self.sensitivity / self.epsilon

    def add_noise(self, tree: Pytree, key: jax.Array) -> Pytree:
        return _add_noise_tree(tree, key, lambda k, shape: self.scale * jax.random.laplace(k, shape))


def _add_noise_tree(tree: Pytree, key: jax.Array, noise_fn) -> Pytree:
    """Add noise leaf-wise, PRESERVING each leaf's dtype (noise is drawn in
    float32 then cast back — a dtype change would force re-jit of every
    downstream compiled step and break donated/sharded buffers).  Non-float
    leaves (ints, bools — e.g. step counters) pass through unchanged."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(key, max(len(leaves), 1))
    out = []
    for leaf, k in zip(leaves, keys):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
            noise = noise_fn(k, jnp.shape(leaf)).astype(jnp.result_type(leaf))
            out.append(leaf + noise)
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def create_mechanism(mechanism_type: str, epsilon: float, delta: float, sensitivity: float):
    mechanism_type = mechanism_type.lower()
    if mechanism_type == "gaussian":
        return Gaussian(epsilon, delta, sensitivity)
    if mechanism_type == "laplace":
        return Laplace(epsilon, sensitivity)
    raise ValueError(f"unknown DP mechanism: {mechanism_type!r}")
