"""Singleton DP engine gating central (cdp) / local (ldp) noise.

Parity with reference ``core/dp/fed_privacy_mechanism.py:21-46``: enabled by
``enable_dp`` + ``dp_type in {cdp, ldp}`` + ``mechanism_type in
{gaussian, laplace}``; central noise is added after aggregation on the server,
local noise after local training on the client.  Noise generation uses a
threaded ``jax.random`` key so runs are reproducible given ``random_seed``.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from .budget_accountant import BudgetAccountant
from .mechanisms import create_mechanism

DP_TYPE_CENTRAL = "cdp"
DP_TYPE_LOCAL = "ldp"


class FedMLDifferentialPrivacy:
    _instance: Optional["FedMLDifferentialPrivacy"] = None

    def __init__(self):
        self.is_dp_enabled = False
        self.dp_type: Optional[str] = None
        self.mechanism = None
        self.accountant: Optional[BudgetAccountant] = None
        self.epsilon = None
        self.delta = None
        self._key = jax.random.PRNGKey(0)

    @classmethod
    def get_instance(cls) -> "FedMLDifferentialPrivacy":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def init(self, args: Any) -> None:
        if not getattr(args, "enable_dp", False):
            self.is_dp_enabled = False
            return
        self.is_dp_enabled = True
        self.dp_type = str(getattr(args, "dp_type", DP_TYPE_CENTRAL)).lower().strip()
        if self.dp_type not in (DP_TYPE_CENTRAL, DP_TYPE_LOCAL):
            raise ValueError(f"dp_type must be 'cdp' or 'ldp', got {self.dp_type!r}")
        self.epsilon = float(getattr(args, "epsilon", 1.0))
        self.delta = float(getattr(args, "delta", 1e-5))
        sensitivity = float(getattr(args, "sensitivity", 1.0))
        mechanism_type = str(getattr(args, "mechanism_type", "gaussian")).lower()
        self.mechanism = create_mechanism(mechanism_type, self.epsilon, self.delta, sensitivity)
        budget = getattr(args, "privacy_budget", None)
        if budget is None:
            self.accountant = BudgetAccountant(float("inf"), 1.0)
        elif isinstance(budget, (int, float)):
            self.accountant = BudgetAccountant(float(budget), 1.0)
        elif isinstance(budget, (list, tuple)) and len(budget) == 2:
            self.accountant = BudgetAccountant(float(budget[0]), float(budget[1]))
        else:
            raise ValueError(
                f"privacy_budget must be a scalar epsilon or (epsilon, delta) pair, got {budget!r}"
            )
        self._key = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)) + 7919)

    def is_local_dp_enabled(self) -> bool:
        return self.is_dp_enabled and self.dp_type == DP_TYPE_LOCAL

    def is_global_dp_enabled(self) -> bool:
        return self.is_dp_enabled and self.dp_type == DP_TYPE_CENTRAL

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def add_noise(self, tree: Any) -> Any:
        if self.mechanism is None:
            raise RuntimeError("DP engine not initialized")
        if self.accountant is not None:
            # Laplace is pure epsilon-DP: never charges delta.
            from .mechanisms import Laplace

            delta = 0.0 if isinstance(self.mechanism, Laplace) else self.delta
            self.accountant.spend(self.epsilon, delta)
        return self.mechanism.add_noise(tree, self._next_key())

    def add_local_noise(self, local_grad: Any) -> Any:
        return self.add_noise(local_grad)

    def noise_scale(self) -> float:
        """The mechanism's calibrated noise scale (Gaussian sigma / Laplace
        b) — what the compiled DP stage feeds as its runtime ``dp_sigma``
        input, so the accountant-driven calibration is the single source of
        truth on both planes."""
        if self.mechanism is None:
            return 0.0
        return float(getattr(self.mechanism, "sigma",
                             getattr(self.mechanism, "scale", 0.0)))

    def spend_budget(self, times: int = 1) -> None:
        """Account ``times`` mechanism applications WITHOUT noising —
        for paths that apply the (jax-pure) mechanism inside a compiled
        region (the in-mesh local-DP round) and account host-side."""
        if self.accountant is None:
            return
        from .mechanisms import Laplace

        delta = 0.0 if isinstance(self.mechanism, Laplace) else self.delta
        for _ in range(int(times)):
            self.accountant.spend(self.epsilon, delta)

    def add_global_noise(self, global_model: Any) -> Any:
        return self.add_noise(global_model)
