"""Privacy budget accountant.

Parity with reference ``core/dp/budget_accountant.py``: tracks per-round
(epsilon, delta) spends under basic and advanced composition and raises when
the configured budget is exhausted.
"""

from __future__ import annotations

import math
from typing import List, Tuple


class BudgetAccountant:
    def __init__(self, epsilon: float = float("inf"), delta: float = 1.0):
        self.epsilon_budget = float(epsilon)
        self.delta_budget = float(delta)
        self._spends: List[Tuple[float, float]] = []

    def spend(self, epsilon: float, delta: float = 0.0) -> None:
        eps_total, delta_total = self.total()
        if eps_total + epsilon > self.epsilon_budget + 1e-12 or delta_total + delta > self.delta_budget + 1e-12:
            raise RuntimeError(
                f"privacy budget exhausted: spent=({eps_total:.4g},{delta_total:.4g}) "
                f"request=({epsilon:.4g},{delta:.4g}) budget=({self.epsilon_budget:.4g},{self.delta_budget:.4g})"
            )
        self._spends.append((float(epsilon), float(delta)))

    def total(self) -> Tuple[float, float]:
        """Basic (sequential) composition."""
        return (sum(e for e, _ in self._spends), sum(d for _, d in self._spends))

    def total_advanced(self, delta_slack: float = 1e-6) -> Tuple[float, float]:
        """Advanced composition (Dwork-Roth Thm 3.20) for k homogeneous spends."""
        if not self._spends:
            return (0.0, 0.0)
        k = len(self._spends)
        eps = max(e for e, _ in self._spends)
        delta = sum(d for _, d in self._spends) + delta_slack
        eps_adv = eps * math.sqrt(2.0 * k * math.log(1.0 / delta_slack)) + k * eps * (math.exp(eps) - 1.0)
        return (min(eps_adv, k * eps), delta)

    @property
    def remaining(self) -> Tuple[float, float]:
        e, d = self.total()
        return (self.epsilon_budget - e, self.delta_budget - d)

    def __len__(self) -> int:
        return len(self._spends)
