"""Dropout-resilient SecAgg round coordination.

The seed's SecAgg server stalls unless EVERY client's masked vector arrives
(``cross_silo/secagg`` waits on ``len(self.masked) < self.client_num``) — a
single client lost to a chaos drop/reset poisons the round, because its
pairwise masks never cancel.  This module implements the classic
Bonawitz-style recovery so a dropout is the common case, not a round-killer:

* **Setup** — every client's DH secret is derived deterministically from the
  round seed; each secret is ALSO Shamir-shared (:func:`..mpc.secagg.
  BGW_encoding`, degree ``threshold-1``) so any ``threshold`` survivors can
  reconstruct a dropped client's key.
* **Masking** — clients quantize into the M31 field and add the pairwise
  masks (:func:`..mpc.secagg.mask_model_update`); submissions are journaled
  exactly-once (duplicate payloads from a chaos retransmit are counted and
  ignored, never double-folded).
* **Unmask** — the survivors' payloads field-sum (host loop or the compiled
  :mod:`.inmesh` scan — exact field math, so bit-identical either way); for
  each dropped client the coordinator reconstructs its secret from the
  survivors' shares (``BGW_decoding`` at the survivor alphas), re-derives
  the agreed keys against each survivor's public key, PRG-expands the
  uncancelled masks, and applies the sign-correct correction.  The result
  is bitwise the plain field sum of the survivors' unmasked residues —
  a mid-round dropout never perturbs a single bit of the aggregate.

The whole round state round-trips through :meth:`SecAggRound.export_state` /
:meth:`SecAggRound.from_state`, so a server kill between submissions resumes
and unmasks bit-identically with exactly-once accounting.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import obs
from ..obs.trace import NULL_SPAN
from .field import FIELD_PRIME
from .secagg import (BGW_decoding, BGW_encoding, mask_model_update,
                     my_key_agreement, my_pk_gen, pairwise_mask,
                     transform_finite_to_tensor, transform_tensor_to_finite)

SECAGG_PLANES = ("host", "compiled")


class SecAggRound:
    """One dropout-resilient SecAgg round over ``n_clients`` participants.

    ``threshold`` survivors (default a strict majority) are enough to
    unmask; fewer raises — below the reconstruction threshold the masks are
    information-theoretically unrecoverable and the round must abort rather
    than emit garbage.  ``plane`` picks the field-sum implementation:
    ``host`` is the per-client numpy loop, ``compiled`` the
    :mod:`.inmesh` scan; both produce identical residues.
    """

    def __init__(self, n_clients: int, threshold: Optional[int] = None,
                 seed: int = 0, q_bits: int = 16, plane: str = "host"):
        if plane not in SECAGG_PLANES:
            raise ValueError(
                f"secagg_plane must be one of {SECAGG_PLANES} (got {plane!r})")
        n = int(n_clients)
        if n < 2:
            raise ValueError(f"SecAgg needs >= 2 clients (got {n})")
        t = int(threshold) if threshold is not None else n // 2 + 1
        if not (2 <= t <= n):
            raise ValueError(
                f"threshold must be in [2, {n}] (got {t})")
        self.n = n
        self.threshold = t
        self.seed = int(seed)
        self.q_bits = int(q_bits)
        self.plane = plane
        # deterministic per-client DH secrets: the same (seed, n) always
        # rebuilds the same key material, so a killed-and-restored server
        # re-derives the setup instead of persisting secrets
        rng = np.random.default_rng(self.seed)
        self.sks: List[int] = [int(rng.integers(2, 2 ** 30))
                               for _ in range(n)]
        self.pks: List[int] = [my_pk_gen(sk) for sk in self.sks]
        # sk_shares[i][j] = client j's Shamir share of client i's secret
        # (degree threshold-1, evaluated at alpha = j + 1)
        self.sk_shares: List[np.ndarray] = [
            BGW_encoding(np.asarray([sk], dtype=np.int64), n, t - 1, rng)
            for sk in self.sks]
        self.payloads: Dict[int, np.ndarray] = {}
        self.dup_submissions = 0

    # -- client side ---------------------------------------------------------
    def quantize(self, vec: np.ndarray) -> np.ndarray:
        return transform_tensor_to_finite(
            np.asarray(vec, np.float64), FIELD_PRIME, q_bits=self.q_bits)

    def client_payload(self, client_id: int, vec: np.ndarray) -> np.ndarray:
        """Quantize ``vec`` into the field and apply client ``client_id``'s
        pairwise masks against every peer."""
        i = int(client_id)
        z = self.quantize(vec)
        peer_keys = {j: my_key_agreement(self.sks[i], self.pks[j])
                     for j in range(self.n) if j != i}
        return mask_model_update(z, i, peer_keys, FIELD_PRIME)

    # -- server side ---------------------------------------------------------
    def submit(self, client_id: int, payload: np.ndarray) -> bool:
        """Journal one masked payload exactly-once.  A duplicate (chaos
        retransmit, replayed upload) is counted and dropped — folding it
        twice would double that client's contribution."""
        i = int(client_id)
        if not (0 <= i < self.n):
            raise ValueError(f"client_id {i} out of range [0, {self.n})")
        if i in self.payloads:
            self.dup_submissions += 1
            obs.counter_inc("secagg.dup_submissions_total")
            return False
        self.payloads[i] = np.asarray(payload, np.int64)
        return True

    @property
    def survivors(self) -> List[int]:
        return sorted(self.payloads)

    @property
    def dropped(self) -> List[int]:
        return [d for d in range(self.n) if d not in self.payloads]

    def _field_sum(self, stack: np.ndarray) -> np.ndarray:
        if self.plane == "compiled":
            from .inmesh import field_sum
            return field_sum(stack)
        # retained host oracle: exact field math, any order — the compiled
        # scan must match this loop bit-for-bit
        total = np.zeros(stack.shape[1:], dtype=np.int64)
        for v in stack:  # fedlint: allow[sec-host-fallback] — retained host oracle for the compiled field fold
            total = np.mod(total + v, FIELD_PRIME)
        return total

    def _correct(self, total: np.ndarray, mask: np.ndarray,
                 add: bool) -> np.ndarray:
        if self.plane == "compiled":
            from .inmesh import field_add, field_sub
            return (field_add if add else field_sub)(total, mask)
        return np.mod(total + mask if add else total - mask, FIELD_PRIME)

    def unmask(self, obs_parent: Any = None) -> np.ndarray:
        """Field-sum the survivors' payloads and strip the uncancelled
        masks of every dropped client.  Returns float64 aggregate (the
        dequantized residues).  Raises when fewer than ``threshold``
        payloads arrived."""
        surv = self.survivors
        if len(surv) < self.threshold:
            raise ValueError(
                f"only {len(surv)} of {self.n} payloads arrived; "
                f"threshold {self.threshold} survivors required to unmask")
        parent = obs_parent if obs_parent is not None else obs.active_ctx()
        sp = (obs.span("round.unmask", parent, n_clients=self.n,
                       survivors=len(surv), dropped=len(self.dropped),
                       plane=self.plane)
              if parent is not None else NULL_SPAN)
        with sp:
            stack = np.stack([self.payloads[s] for s in surv])
            total = self._field_sum(stack)
            reconstructions = 0
            for d in self.dropped:
                # >= threshold survivor shares reconstruct the dropped
                # secret (Lagrange at 0 over the survivor alphas)
                idx = surv[: self.threshold]
                shares = np.stack([self.sk_shares[d][s] for s in idx])
                alphas = np.asarray([s + 1 for s in idx], dtype=np.int64)
                sk_d = int(BGW_decoding(shares, alphas)[0])
                reconstructions += 1
                for s in surv:
                    # the agreed key from the RECONSTRUCTED secret equals
                    # what survivor s derived (DH symmetry), so the PRG
                    # expands the exact mask s folded in
                    key = my_key_agreement(sk_d, self.pks[s])
                    m = pairwise_mask(total.shape, key, FIELD_PRIME)
                    # s included +m when its peer d ranks above it, -m
                    # below — apply the inverse
                    total = self._correct(total, m, add=(d < s))
            obs.counter_inc("secagg.unmask_reconstructions",
                            reconstructions)
            sp.end(reconstructions=reconstructions)
        return transform_finite_to_tensor(
            total, FIELD_PRIME, q_bits=self.q_bits)

    # -- crash recovery ------------------------------------------------------
    def export_state(self) -> Dict[str, Any]:
        """Snapshot for server-kill recovery: the deterministic setup is
        re-derived from (seed, n, threshold), so only the journaled
        payloads and counters persist."""
        return {
            "version": 1,
            "n": self.n,
            "threshold": self.threshold,
            "seed": self.seed,
            "q_bits": self.q_bits,
            "plane": self.plane,
            "payloads": {int(i): np.asarray(v, np.int64)
                         for i, v in self.payloads.items()},
            "dup_submissions": int(self.dup_submissions),
        }

    @classmethod
    def from_state(cls, state: Dict[str, Any]) -> "SecAggRound":
        round_ = cls(state["n"], threshold=state["threshold"],
                     seed=state["seed"], q_bits=state["q_bits"],
                     plane=state.get("plane", "host"))
        for i, v in state["payloads"].items():
            round_.payloads[int(i)] = np.asarray(v, np.int64)
        round_.dup_submissions = int(state["dup_submissions"])
        return round_
