"""Secure-aggregation primitives.

Capability parity with reference ``core/mpc/secagg.py`` (quantization :351,
additive sharing :316, BGW :164/:192, LCC :213/:297, key agreement :329-343)
— rebuilt on the vectorized int64 field ops in :mod:`.field`.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from .field import FIELD_PRIME, _as_field, lagrange_basis_at, mod_inverse, mod_pow

# ---------------------------------------------------------------------------
# fixed-point quantization into the field (reference :345-395)
# ---------------------------------------------------------------------------
def transform_tensor_to_finite(x: np.ndarray, p=FIELD_PRIME, q_bits: int = 16) -> np.ndarray:
    """Float -> field residues: round(x * 2^q) mapped symmetrically into [0, p).

    Negative values land in the upper half of the field (two's-complement
    style), exactly as the reference's ``my_q`` transform.
    """
    scale = np.int64(1) << q_bits
    q = np.round(np.asarray(x, dtype=np.float64) * float(scale)).astype(np.int64)
    return np.mod(q, p)


def transform_finite_to_tensor(z: np.ndarray, p=FIELD_PRIME, q_bits: int = 16) -> np.ndarray:
    """Field residues -> float, undoing :func:`transform_tensor_to_finite`."""
    z = _as_field(z, p)
    half = (int(p) - 1) // 2
    signed = np.where(z > half, z - p, z).astype(np.float64)
    return signed / float(np.int64(1) << q_bits)


# ---------------------------------------------------------------------------
# additive secret sharing (reference Gen_Additive_SS :316)
# ---------------------------------------------------------------------------
def generate_additive_shares(secret: np.ndarray, n_shares: int, rng: np.random.Generator, p=FIELD_PRIME) -> np.ndarray:
    """Split ``secret`` (field residues) into n shares summing to it mod p.
    Returns array [n_shares, *secret.shape]."""
    secret = _as_field(secret, p)
    shares = rng.integers(0, int(p), size=(n_shares - 1,) + secret.shape, dtype=np.int64)
    last = np.mod(secret - shares.sum(axis=0), p)
    return np.concatenate([shares, last[None]], axis=0)


# ---------------------------------------------------------------------------
# BGW (Shamir) threshold sharing (reference :164-212)
# ---------------------------------------------------------------------------
def BGW_encoding(secret: np.ndarray, n: int, t: int, rng: np.random.Generator, p=FIELD_PRIME) -> np.ndarray:
    """Degree-t Shamir shares for n parties; party i evaluates at alpha=i+1.
    secret: [...]; returns [n, ...]."""
    secret = _as_field(secret, p)
    coeffs = rng.integers(0, int(p), size=(t,) + secret.shape, dtype=np.int64)
    alphas = np.arange(1, n + 1, dtype=np.int64)
    shares = np.empty((n,) + secret.shape, dtype=np.int64)
    for i, a in enumerate(alphas):
        acc = secret.copy()
        apow = np.int64(1)
        for d in range(t):
            apow = (apow * a) % p
            acc = (acc + coeffs[d] * apow) % p
        shares[i] = acc
    return shares


def BGW_decoding(shares: np.ndarray, alphas: np.ndarray, p=FIELD_PRIME) -> np.ndarray:
    """Reconstruct the secret (evaluate at 0) from >= t+1 shares taken at
    ``alphas``.  shares: [k, ...]."""
    U = lagrange_basis_at(_as_field(alphas, p), _as_field(alphas, p), np.zeros(1, dtype=np.int64), p)  # [1, k]
    k = shares.shape[0]
    flat = shares.reshape(k, -1).astype(np.int64) % p
    out = np.zeros(flat.shape[1], dtype=np.int64)
    for j in range(k):
        out = (out + U[0, j] * flat[j]) % p
    return out.reshape(shares.shape[1:])


# ---------------------------------------------------------------------------
# Lagrange Coded Computing (reference LCC_encoding_with_points :213,
# LCC_decoding_with_points :297)
# ---------------------------------------------------------------------------
def LCC_encoding_with_points(X: np.ndarray, alphas: np.ndarray, betas: np.ndarray, p=FIELD_PRIME) -> np.ndarray:
    """Encode K data chunks X[k] (interpolation values at alphas) onto
    evaluation points betas.  X: [K, ...]; returns [N, ...] with N=len(betas)."""
    alphas = _as_field(alphas, p)
    betas = _as_field(betas, p)
    U = lagrange_basis_at(alphas, alphas, betas, p)  # [N, K]
    K = X.shape[0]
    flat = _as_field(X, p).reshape(K, -1)
    out = np.zeros((betas.shape[0], flat.shape[1]), dtype=np.int64)
    for j in range(K):
        out = (out + U[:, j : j + 1] * flat[j : j + 1, :]) % p
    return out.reshape((betas.shape[0],) + X.shape[1:])


def LCC_decoding_with_points(F: np.ndarray, eval_betas: np.ndarray, target_alphas: np.ndarray, p=FIELD_PRIME) -> np.ndarray:
    """Decode: given polynomial values F[i] at eval_betas, recover values at
    target_alphas.  F: [R, ...] with R >= deg+1."""
    U = lagrange_basis_at(_as_field(eval_betas, p), _as_field(eval_betas, p), _as_field(target_alphas, p), p)
    R = F.shape[0]
    flat = _as_field(F, p).reshape(R, -1)
    out = np.zeros((U.shape[0], flat.shape[1]), dtype=np.int64)
    for j in range(R):
        out = (out + U[:, j : j + 1] * flat[j : j + 1, :]) % p
    return out.reshape((U.shape[0],) + F.shape[1:])


# ---------------------------------------------------------------------------
# DH-style key agreement (reference my_pk_gen / my_key_agreement :329-343)
# ---------------------------------------------------------------------------
def my_pk_gen(sk: int, p=FIELD_PRIME, g: int = 3) -> int:
    return int(mod_pow(np.int64(g), int(sk), p))


def my_key_agreement(my_sk: int, their_pk: int, p=FIELD_PRIME) -> int:
    return int(mod_pow(np.int64(their_pk), int(my_sk), p))


# ---------------------------------------------------------------------------
# pairwise-mask SecAgg helpers (protocol layer used by cross_silo/secagg)
# ---------------------------------------------------------------------------
def pairwise_mask(shape: Tuple[int, ...], seed: int, p=FIELD_PRIME) -> np.ndarray:
    """Deterministic field-mask from a shared seed (PRG expansion of the
    agreed key — the reference uses the same np.random construction)."""
    rng = np.random.default_rng(seed & 0x7FFFFFFF)
    return rng.integers(0, int(p), size=shape, dtype=np.int64)


def mask_model_update(z: np.ndarray, self_id: int, peer_keys: dict, p=FIELD_PRIME) -> np.ndarray:
    """Add +mask(i,j) for j>i and -mask(j,i) for j<i: masks cancel in the sum
    over all clients (classic Bonawitz-style pairwise cancellation)."""
    out = _as_field(z, p)
    for peer, key in peer_keys.items():
        if peer == self_id:
            continue
        m = pairwise_mask(z.shape, key, p)
        out = (out + m) % p if peer > self_id else (out - m) % p
    return out
