"""MPC primitives for secure aggregation.

Re-implementation of the capability of reference ``core/mpc/secagg.py`` (395
LoC) and ``core/mpc/lightsecagg.py`` (205 LoC) with vectorized integer field
arithmetic: the prime is kept below 2**31 so products fit int64 exactly —
`np.int64`/`jnp.int64` lanes, no Python bignum loops (TPU int path; cf.
SURVEY.md §7 "SecAgg in finite fields on TPU").
"""

from .field import (
    FIELD_PRIME,
    lagrange_basis_at,
    mod_inverse,
    mod_matmul,
)
from .secagg import (
    BGW_decoding,
    BGW_encoding,
    LCC_decoding_with_points,
    LCC_encoding_with_points,
    generate_additive_shares,
    my_pk_gen,
    my_key_agreement,
    transform_finite_to_tensor,
    transform_tensor_to_finite,
)
from .lightsecagg import (
    mask_encoding,
    compute_aggregate_encoded_mask,
    aggregate_mask_reconstruction,
)

__all__ = [
    "FIELD_PRIME", "mod_inverse", "mod_matmul", "lagrange_basis_at",
    "transform_tensor_to_finite", "transform_finite_to_tensor",
    "generate_additive_shares", "BGW_encoding", "BGW_decoding",
    "LCC_encoding_with_points", "LCC_decoding_with_points",
    "my_pk_gen", "my_key_agreement",
    "mask_encoding", "compute_aggregate_encoded_mask", "aggregate_mask_reconstruction",
]
