"""LightSecAgg mask encoding / aggregate-mask reconstruction.

Capability parity with reference ``core/mpc/lightsecagg.py:97-146``
(``mask_encoding`` / aggregate-mask recovery): each client LCC-encodes its
local random mask into N sub-masks (tolerating up to ``d`` dropouts given
privacy threshold ``t``); the server reconstructs only the *sum* of surviving
clients' masks from any ``u = t + k`` surviving encoded shares — individual
masks stay hidden.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .field import FIELD_PRIME, _as_field
from .secagg import LCC_decoding_with_points, LCC_encoding_with_points


def _split_points(n: int, t: int, u: int, p=FIELD_PRIME):
    """alpha (data/noise interpolation) and beta (share evaluation) points.
    k = u - t data chunks, t noise chunks, n shares."""
    alphas = np.arange(1, u + 1, dtype=np.int64)           # k data + t noise
    betas = np.arange(u + 1, u + n + 1, dtype=np.int64)    # n evaluation points
    return alphas, betas


def mask_encoding(
    d: int, n: int, t: int, u: int, local_mask: np.ndarray, rng: np.random.Generator, p=FIELD_PRIME
) -> np.ndarray:
    """Encode a client's length-``d`` mask into ``n`` sub-masks.

    Parity with reference ``lightsecagg.py:97-123``: pad the mask to k=u-t
    equal chunks, append t uniform noise chunks, LCC-encode at n points.
    Returns [n, d//k padded] — row j goes to client j.
    """
    k = u - t
    chunk = -(-d // k)  # ceil
    mask = _as_field(local_mask, p).reshape(-1)
    padded = np.zeros(chunk * k, dtype=np.int64)
    padded[:d] = mask[:d]
    data = padded.reshape(k, chunk)
    noise = rng.integers(0, int(p), size=(t, chunk), dtype=np.int64)
    X = np.concatenate([data, noise], axis=0)  # [u, chunk]
    alphas, betas = _split_points(n, t, u, p)
    return LCC_encoding_with_points(X, alphas, betas, p)  # [n, chunk]


def compute_aggregate_encoded_mask(
    encoded_mask_rows: Dict[int, np.ndarray], surviving: Sequence[int], p=FIELD_PRIME
) -> np.ndarray:
    """Each surviving client j sums the encoded rows it received from all
    surviving peers (reference ``compute_aggregate_encoded_mask``)."""
    acc = None
    for cid in surviving:
        row = _as_field(encoded_mask_rows[cid], p)
        acc = row if acc is None else (acc + row) % p
    return acc


def aggregate_mask_reconstruction(
    agg_encoded: Dict[int, np.ndarray], t: int, u: int, d: int, p=FIELD_PRIME
) -> np.ndarray:
    """Server-side: from >= u aggregate-encoded points (keyed by client id,
    1-based), decode the sum of surviving masks (reference :126-146)."""
    ids = sorted(agg_encoded.keys())[:u]
    n_total = max(ids)
    k = u - t
    _, betas_all = _split_points(n_total, t, u, p)
    eval_betas = np.array([betas_all[i - 1] for i in ids], dtype=np.int64)
    F = np.stack([_as_field(agg_encoded[i], p) for i in ids], axis=0)  # [u, chunk]
    target_alphas = np.arange(1, k + 1, dtype=np.int64)  # data chunks only
    decoded = LCC_decoding_with_points(F, eval_betas, target_alphas, p)  # [k, chunk]
    return decoded.reshape(-1)[:d]
