"""Prime-field arithmetic on int64 lanes.

The reference does field math with Python ints and numpy object arrays
(``core/mpc/secagg.py:41-82`` modular inverse / Lagrange coefficients).  Here
every op is a vectorized int64 expression with the invariant ``p < 2**31`` so
``a*b`` never overflows int64; this is the layout that maps onto TPU integer
lanes (and is ~100x faster on host too).
"""

from __future__ import annotations

import numpy as np

# 2**31 - 1 (Mersenne prime M31). Products of two residues fit in int64.
FIELD_PRIME = np.int64(2147483647)


def _as_field(a, p=FIELD_PRIME) -> np.ndarray:
    return np.mod(np.asarray(a, dtype=np.int64), p)


def mod_pow(base, exp: int, p=FIELD_PRIME) -> np.ndarray:
    """Vectorized modular exponentiation (square-and-multiply on int64)."""
    base = _as_field(base, p)
    result = np.ones_like(base)
    e = int(exp)
    while e > 0:
        if e & 1:
            result = (result * base) % p
        base = (base * base) % p
        e >>= 1
    return result


def mod_inverse(a, p=FIELD_PRIME) -> np.ndarray:
    """Fermat inverse a^(p-2) mod p (reference ``modular_inv`` secagg.py:41)."""
    return mod_pow(a, int(p) - 2, p)


def mod_matmul(A: np.ndarray, B: np.ndarray, p=FIELD_PRIME) -> np.ndarray:
    """(A @ B) mod p without overflow: row-by-row accumulate with reduction.

    A: [m, k], B: [k, n] int64 residues.  Accumulates in chunks small enough
    that sums of k products (< 2**62 each... p^2 ~ 2**62) stay exact: reduce
    after every partial product.
    """
    A = _as_field(A, p)
    B = _as_field(B, p)
    m, k = A.shape
    out = np.zeros((m, B.shape[1]), dtype=np.int64)
    # p^2 < 2**62, int64 max ~ 9.2e18 = 2**63; sum of 2 products can overflow,
    # so reduce after each rank-1 update (vectorized over m*n).
    for t in range(k):
        out = (out + A[:, t : t + 1] * B[t : t + 1, :]) % p
    return out


def lagrange_basis_at(eval_points: np.ndarray, interp_points: np.ndarray, targets: np.ndarray, p=FIELD_PRIME) -> np.ndarray:
    """Matrix U[t, j] = prod_{l != j} (targets[t]-interp[l]) / (interp[j]-interp[l]) mod p.

    Generalizes the reference's ``gen_Lagrange_coeffs`` (secagg.py:62-82):
    decoding a degree-(k-1) polynomial known at ``interp_points`` onto
    ``targets``.
    """
    interp = _as_field(interp_points, p).reshape(-1)
    targets = _as_field(targets, p).reshape(-1)
    k = interp.shape[0]
    U = np.zeros((targets.shape[0], k), dtype=np.int64)
    for j in range(k):
        num = np.ones_like(targets)
        den = np.int64(1)
        for l in range(k):
            if l == j:
                continue
            num = (num * ((targets - interp[l]) % p)) % p
            den = (den * ((interp[j] - interp[l]) % p)) % p
        U[:, j] = (num * mod_inverse(den, p)) % p
    return U
