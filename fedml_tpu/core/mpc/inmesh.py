"""Compiled finite-field reduction for SecAgg: M31 residue ops on uint32 lanes.

The SecAgg servers fold masked field vectors with a host numpy loop
(``total = (total + v) % p`` per client) — exact, but O(clients) Python
iterations over model-size arrays.  Here the same fold is ONE jitted
``lax.scan`` over the stacked residues in uint32 lanes:

* ``FIELD_PRIME = 2**31 - 1`` fits uint32, and ``a + b <= 2p - 2 < 2**32``,
  so a single conditional subtract after each add is exact — no widening,
  no overflow, and the op maps onto integer vector lanes.
* Field addition is associative and exact, so ANY reduction order gives the
  same residues: the compiled fold is bit-identical to the host loop by
  arithmetic, not by tolerance — ``secagg_plane=compiled`` can never drift.

Mask *application* stays element-wise (:func:`field_add` / :func:`field_sub`
host wrappers over the same jitted kernels) so the dropout-unmask correction
in :mod:`.dropout` runs through identical code on either plane.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .field import FIELD_PRIME

_P32 = np.uint32(int(FIELD_PRIME))


def _mod_add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    s = a + b  # residues are < p, so s <= 2p - 2 < 2**32: exact in uint32
    return jnp.where(s >= _P32, s - _P32, s)


def _mod_sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    # a - b == a + (p - b); b == 0 gives a transient operand of exactly p,
    # and a + p <= 2p - 1 < 2**32 still holds before the reduce
    return _mod_add(a, _P32 - b)


_KERNELS: Dict[Any, Any] = {}


def _kernel(name: str, build):
    fn = _KERNELS.get(name)
    if fn is None:
        fn = jax.jit(build)
        _KERNELS[name] = fn
    return fn


def _fold(stack):
    def body(acc, row):
        return _mod_add(acc, row), None

    acc, _ = jax.lax.scan(
        body, jnp.zeros(stack.shape[1:], jnp.uint32), stack)
    return acc


def _check_residues(arr: np.ndarray) -> np.ndarray:
    arr = np.asarray(arr, np.int64)
    if arr.size and (arr.min() < 0 or arr.max() >= int(FIELD_PRIME)):
        raise ValueError(
            "field_sum input must hold residues in [0, p); got range "
            f"[{arr.min()}, {arr.max()}]")
    return arr


def field_sum(stack: np.ndarray) -> np.ndarray:
    """Sum ``stack`` ([n, ...] int64 field residues) over the leading axis
    mod p, as one compiled scan.  Exact integer math — bit-identical to the
    per-client host loop in any order."""
    arr = _check_residues(stack)
    out = _kernel("fold", _fold)(jnp.asarray(arr.astype(np.uint32)))
    return np.asarray(out).astype(np.int64)


def field_add(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(a + b) mod p element-wise through the compiled kernel."""
    a, b = _check_residues(a), _check_residues(b)
    out = _kernel("add", _mod_add)(
        jnp.asarray(a.astype(np.uint32)), jnp.asarray(b.astype(np.uint32)))
    return np.asarray(out).astype(np.int64)


def field_sub(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(a - b) mod p element-wise through the compiled kernel."""
    a, b = _check_residues(a), _check_residues(b)
    out = _kernel("sub", _mod_sub)(
        jnp.asarray(a.astype(np.uint32)), jnp.asarray(b.astype(np.uint32)))
    return np.asarray(out).astype(np.int64)


def reset_kernels() -> None:
    """Drop the cached jitted kernels (tests)."""
    _KERNELS.clear()
