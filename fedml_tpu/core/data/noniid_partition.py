"""Non-IID data partitioning.

Parity with reference ``core/data/noniid_partition.py`` (Dirichlet LDA,
``non_iid_partition_with_dirichlet_distribution`` :6 and
``partition_class_samples_with_dirichlet_distribution`` :87), plus the
homogeneous split used by the hetero/homo ``partition_method`` switch in the
data loaders, and a quantity-skew partition.  All numpy-side (host data prep
— partitioning never runs on device).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def record_data_stats(y_train: np.ndarray, net_dataidx_map: Dict[int, np.ndarray]):
    net_cls_counts = {}
    for net_i, dataidx in net_dataidx_map.items():
        unq, unq_cnt = np.unique(y_train[dataidx], return_counts=True)
        net_cls_counts[net_i] = {int(u): int(c) for u, c in zip(unq, unq_cnt)}
    return net_cls_counts


def partition_class_samples_with_dirichlet_distribution(
    N: int,
    alpha: float,
    client_num: int,
    idx_batch: List[List[int]],
    idx_k: np.ndarray,
    rng: np.random.RandomState,
):
    """Split one class's sample indices across clients ~ Dir(alpha), balancing
    so no client exceeds N/client_num (the standard LDA recipe,
    arXiv:1909.06335; reference :87-117)."""
    rng.shuffle(idx_k)
    shares = rng.dirichlet(alpha * np.ones(client_num))
    # capacity-balance: clients already holding >= N/client_num samples are
    # frozen out of this class's draw, and the rest renormalized
    sizes = np.array([len(b) for b in idx_batch], dtype=np.float64)
    shares = np.where(sizes < N / client_num, shares, 0.0)
    shares /= shares.sum()
    # convert shares to split points over this class's samples
    cuts = np.floor(np.cumsum(shares[:-1]) * len(idx_k)).astype(np.int64)
    for client, chunk in enumerate(np.split(idx_k, cuts)):
        idx_batch[client] = idx_batch[client] + chunk.tolist()
    min_size = min(len(b) for b in idx_batch)
    return idx_batch, min_size


def non_iid_partition_with_dirichlet_distribution(
    label_list: np.ndarray,
    client_num: int,
    classes: int,
    alpha: float,
    seed: int = 0,
    task: str = "classification",
) -> Dict[int, np.ndarray]:
    """LDA partition (reference :6-60): per class, draw client shares from
    Dir(alpha); resample until every client has at least ~10 samples."""
    rng = np.random.RandomState(seed)
    net_dataidx_map: Dict[int, np.ndarray] = {}
    min_size = 0
    N = len(label_list)
    idx_batch: List[List[int]] = [[] for _ in range(client_num)]
    guard = 0
    while min_size < min(10, max(1, N // max(client_num, 1) // 2)) and guard < 1000:
        guard += 1
        idx_batch = [[] for _ in range(client_num)]
        for k in range(classes):
            idx_k = np.where(label_list == k)[0]
            if len(idx_k) == 0:
                continue
            idx_batch, min_size = partition_class_samples_with_dirichlet_distribution(
                N, alpha, client_num, idx_batch, idx_k, rng
            )
    for i in range(client_num):
        rng.shuffle(idx_batch[i])
        net_dataidx_map[i] = np.array(idx_batch[i], dtype=np.int64)
    return net_dataidx_map


def homo_partition(n_samples: int, client_num: int, seed: int = 0) -> Dict[int, np.ndarray]:
    """IID split: shuffle indices and deal them round-robin-equally."""
    rng = np.random.RandomState(seed)
    idxs = rng.permutation(n_samples)
    batch_idxs = np.array_split(idxs, client_num)
    return {i: np.asarray(batch_idxs[i], dtype=np.int64) for i in range(client_num)}


def quantity_skew_partition(
    n_samples: int, client_num: int, alpha: float, seed: int = 0
) -> Dict[int, np.ndarray]:
    """Sample counts ~ Dir(alpha) (label distribution stays IID)."""
    rng = np.random.RandomState(seed)
    idxs = rng.permutation(n_samples)
    proportions = rng.dirichlet(np.repeat(alpha, client_num))
    cuts = (np.cumsum(proportions) * n_samples).astype(int)[:-1]
    parts = np.split(idxs, cuts)
    return {i: np.asarray(parts[i], dtype=np.int64) for i in range(client_num)}
