"""Server ingest-pipeline primitives: deferred acks + zero-copy decode.

The staged receive path (PR 10) splits the server's per-upload work across
three actors:

* the **io thread** (``comm_manager._IngestPipeline``) owns framing, crc and
  msg-id dedup and feeds a bounded queue;
* the **dispatch worker** runs the registered handler, which journals the
  upload via :meth:`UpdateJournal.append_async` instead of blocking on its
  own fsync;
* the **group-commit thread** (``checkpoint.UpdateJournal``) makes a whole
  batch durable with one fsync and only then releases the acks.

This module holds the two seams those actors share and that neither the
transport nor the durability layer may own directly (circular import):

* a thread-local **ticket sink** — while a handler runs inside
  :func:`deferred_ack_scope`, every journal ticket it produces is collected
  instead of awaited, and the pipeline sends the transport ack only once all
  of them are durable.  The PR 4 "ack implies journaled" contract is
  preserved exactly; only the fsync is amortized.
* a **zero-copy decoder** — per-slot preallocated numpy arenas that upload
  payloads are copied (or msgpack-decoded) straight into, eliminating the
  per-upload allocate+copy the PR 8 ``upload.decode_seconds`` histogram
  attributes most ingest time to.  Arena reuse is safe for the same reason
  the async flush path is: a slot's previous tree is always consumed
  (aggregated) before the same slot accepts the next round's upload.
* a **reorder window** — the edge-aggregator tier's streaming fold must
  consume uploads in leaf-index order (the fold order is part of the
  round's bit-exactness contract) while the wire delivers them in
  arrival order; :class:`ReorderWindow` releases items in index order,
  holding only the out-of-order tail, so in-order traffic streams
  straight into the accumulator with O(1) staging.
* the **chunk reassembly stage** — chunked resumable uploads
  (:mod:`~fedml_tpu.core.distributed.chunking`) accumulate crc-framed
  chunks into per-stream buffers and hand the dispatch worker only
  COMPLETED inner messages; each accepted chunk is journaled before its
  transport ack through the same ticket sink above, so "ack implies
  journaled" holds at sub-message granularity too.  This module and
  ``core/distributed/chunking.py`` are the only two files allowed to
  parse chunk headers or touch reassembly buffers (fedlint
  ``chunk-reassembly-seam``); :class:`ChunkReassembler` is re-exported
  here as the ingest-facing name of that stage.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import obs
from .distributed.chunking import ChunkError, ChunkReassembler  # noqa: F401 — the ingest-facing seam surface

logger = logging.getLogger(__name__)


def pipeline_enabled(args: Any) -> bool:
    """Truthy read of the ``ingest_pipeline`` knob (bool or on/off string)."""
    v = getattr(args, "ingest_pipeline", False)
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "on", "yes")
    return bool(v)


# ---------------------------------------------------------------------------
# deferred-ack ticket sink (thread-local ambient collector)
# ---------------------------------------------------------------------------
class TicketSink:
    """Journal tickets produced while dispatching ONE message.

    The pipeline's dispatch worker opens a :func:`deferred_ack_scope` around
    the handler call; ``_journal_upload`` drops its
    :class:`~fedml_tpu.core.checkpoint.JournalTicket` here instead of
    blocking, and the pipeline acks the message once every collected ticket
    reports durable."""

    __slots__ = ("tickets",)

    def __init__(self) -> None:
        self.tickets: List[Any] = []

    def add(self, ticket: Any) -> None:
        self.tickets.append(ticket)


_tls = threading.local()


def current_sink() -> Optional[TicketSink]:
    """The ambient sink of the innermost :func:`deferred_ack_scope` on this
    thread, or None when the caller runs on the host (blocking) path."""
    return getattr(_tls, "sink", None)


@contextlib.contextmanager
def deferred_ack_scope():
    """Collect journal tickets produced by the enclosed dispatch."""
    prev = getattr(_tls, "sink", None)
    sink = TicketSink()
    _tls.sink = sink
    try:
        yield sink
    finally:
        _tls.sink = prev


# ---------------------------------------------------------------------------
# zero-copy decode: per-slot preallocated arenas
# ---------------------------------------------------------------------------
class _Arena:
    __slots__ = ("treedef", "shapes", "dtypes", "leaves")

    def __init__(self, treedef, shapes, dtypes, leaves):
        self.treedef = treedef
        self.shapes = shapes
        self.dtypes = dtypes
        self.leaves = leaves


class ZeroCopyDecoder:
    """Unpack upload payloads into preallocated per-slot numpy arenas.

    Two entry points, one per payload plane:

    * :meth:`intern` — the pytree plane (cross-silo / async): the tree is
      already deserialized; its leaves are copied into the slot's arena so
      the slot table holds stable, reusable storage instead of a fresh
      allocation per upload.
    * :meth:`decode` — the bytes plane (bench firehose, journal-format
      blobs): flax-msgpack bytes are unpacked with an ``ext_hook`` that
      writes each ndarray leaf directly into the arena in encounter order —
      no intermediate ``np.frombuffer`` copy, no throwaway tree.

    The first payload a slot sees is the learning pass: it fixes the
    signature ``(treedef, shapes, dtypes)`` (the PR 6 cached
    :func:`~fedml_tpu.core.aggregate.leaf_paths` treedef interning makes the
    comparison cheap) and allocates the arena.  Any later mismatch — new
    structure, resized leaf, non-array leaf, chunked-array layout — falls
    back to the original decode, counted on ``ingest.decode_fallbacks``;
    correctness never depends on the fast path.
    """

    def __init__(self) -> None:
        self._arenas: Dict[Any, _Arena] = {}
        # the bytes plane keeps its own arenas: an intern arena indexes the
        # FULL tree flatten (scalars included), a blob arena indexes only the
        # ndarray ext frames in wire encounter order — the two signatures
        # disagree whenever a payload mixes arrays with plain scalars.
        self._blob_arenas: Dict[Any, _Arena] = {}
        self._lock = threading.Lock()

    # -- pytree plane --------------------------------------------------------
    def intern(self, slot: Any, tree: Any) -> Any:
        import jax

        try:
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            arena = self._arena_for(slot, treedef, leaves)
            if arena is None:
                obs.counter_inc("ingest.decode_fallbacks")
                return tree
            for dst, src in zip(arena.leaves, leaves):
                np.copyto(dst, src)
            return jax.tree_util.tree_unflatten(treedef, list(arena.leaves))
        except Exception as e:
            logger.debug("zero-copy intern fell back for slot %r: %s", slot, e)
            obs.counter_inc("ingest.decode_fallbacks")
            return tree

    def _arena_for(self, slot, treedef, leaves) -> Optional[_Arena]:
        shapes = tuple(np.shape(l) for l in leaves)
        try:
            dtypes = tuple(np.asarray(l).dtype for l in leaves)
        except Exception:
            return None
        with self._lock:
            arena = self._arenas.get(slot)
            if arena is None:
                storage = [np.empty(s, d) for s, d in zip(shapes, dtypes)]
                arena = _Arena(treedef, shapes, dtypes, storage)
                self._arenas[slot] = arena
                return arena
        if (arena.treedef != treedef or arena.shapes != shapes
                or arena.dtypes != dtypes):
            return None
        return arena

    # -- bytes plane ---------------------------------------------------------
    def decode(self, slot: Any, blob: bytes) -> Any:
        """Decode flax-msgpack ``blob`` into the slot's arena.

        The learning pass unpacks the blob once, keeping the freshly decoded
        ndarray leaves as the slot's arena storage; steady state re-unpacks
        with an ext_hook that fills those same leaves in wire encounter
        order — for a fixed payload layout msgpack emits ext frames in a
        deterministic order, so encounter order is a stable index.  Any
        drift (leaf count, shape, dtype, chunked/scalar ext codes) raises
        and falls back to a plain ``msgpack_restore``."""
        with self._lock:
            arena = self._blob_arenas.get(slot)
        if arena is None:
            return self._learn_blob(slot, blob)
        try:
            return self._decode_into(arena, blob)
        except Exception as e:
            logger.debug("zero-copy decode fell back for slot %r: %s", slot, e)
            obs.counter_inc("ingest.decode_fallbacks")
            return self._restore(blob)

    def _learn_blob(self, slot: Any, blob: bytes) -> Any:
        """Learning pass: decode once, keep the ndarray leaves as storage."""
        import msgpack  # lint_perf: allow — the zero-copy seam itself

        leaves: List[np.ndarray] = []

        def ext_hook(code: int, data: bytes) -> Any:
            if code != 1:  # npscalar (3) or chunked layout: stay unlearned
                raise ValueError(f"unsupported ext type {code}")
            shape, dtype_name, buffer = msgpack.unpackb(data, raw=True)
            # .copy() detaches from the read-only wire buffer so the array
            # is writable, owned storage the steady state can refill
            arr = (np.frombuffer(buffer, dtype=np.dtype(dtype_name.decode()))
                   .reshape(tuple(shape)).copy())
            leaves.append(arr)
            return arr

        try:
            tree = msgpack.unpackb(blob, ext_hook=ext_hook, raw=False)
        except Exception as e:
            logger.debug("zero-copy learn fell back for slot %r: %s", slot, e)
            obs.counter_inc("ingest.decode_fallbacks")
            return self._restore(blob)
        if leaves:
            arena = _Arena(None, tuple(a.shape for a in leaves),
                           tuple(a.dtype for a in leaves), leaves)
            with self._lock:
                self._blob_arenas[slot] = arena
        return tree

    @staticmethod
    def _restore(blob: bytes) -> Any:
        from flax import serialization  # lint_perf: allow — learning/fallback pass

        return serialization.msgpack_restore(blob)  # lint_perf: allow

    def _decode_into(self, arena: _Arena, blob: bytes) -> Any:
        import msgpack  # lint_perf: allow — the zero-copy seam itself

        cursor = [0]
        leaves = arena.leaves
        n_leaves = len(leaves)
        unpackb = msgpack.unpackb

        def ext_hook(code: int, data: bytes) -> Any:
            # flax _MsgpackExtType.ndarray == 1; payload is
            # msgpack((shape, dtype_name, buffer)) — see _ndarray_to_bytes
            if code != 1:
                raise ValueError(f"unexpected ext type {code} in payload")
            i = cursor[0]
            if i >= n_leaves:
                raise ValueError("payload has more array leaves than arena")
            shape, dtype_name, buffer = unpackb(data, raw=True)
            dst = leaves[i]
            if (tuple(shape) != dst.shape
                    or dtype_name.decode() != dst.dtype.name):
                raise ValueError(
                    f"leaf {i} signature changed: {shape}/{dtype_name!r} "
                    f"vs arena {dst.shape}/{dst.dtype.name}")
            cursor[0] = i + 1
            # one copy, straight from the wire buffer into the arena —
            # np.frombuffer is a view, copyto is the only data movement
            np.copyto(dst, np.frombuffer(buffer, dtype=dst.dtype)
                      .reshape(dst.shape))
            return dst

        # NOTE: no treedef re-check here on purpose.  unpackb builds the
        # returned tree from the blob itself, with each arena leaf placed
        # exactly where its ext frame appeared — the result is correct even
        # if the payload's structure drifted from the arena's.  The per-leaf
        # shape/dtype checks plus the count check below are what guard the
        # storage mapping; a structural change with a different leaf count
        # or leaf signature raises and falls back.
        tree = unpackb(blob, ext_hook=ext_hook, raw=False)
        if cursor[0] != n_leaves:
            raise ValueError(
                f"payload has {cursor[0]} array leaves, arena expects "
                f"{n_leaves}")
        return tree

    def forget(self, slot: Any) -> None:
        with self._lock:
            self._arenas.pop(slot, None)
            self._blob_arenas.pop(slot, None)


# ---------------------------------------------------------------------------
# in-order release window (streaming edge fold)
# ---------------------------------------------------------------------------
class ReorderWindow:
    """Release staged items in a fixed index order regardless of arrival.

    The edge aggregator's streaming fold (``core/hierarchy``) consumes
    one leaf upload at a time in the block's leaf-index order — the fold
    order IS the bit-exactness contract — but transports deliver in
    arrival order.  ``stage(key, item)`` parks an item; ``release()``
    yields every ``(key, item)`` that is now contiguous with the release
    cursor, dropping staged references as it goes, so the common in-order
    case stages nothing and the out-of-order tail is all that is ever
    held.  Not thread-safe by design: the single dispatch worker (or the
    transport thread on the sync path) is the only caller, the same
    single-threaded-handler invariant every manager assumes.
    """

    def __init__(self, order: List[Any]):
        self._order = list(order)
        self._cursor = 0
        self._staged: Dict[Any, Any] = {}

    @property
    def expected(self) -> Optional[Any]:
        """The next key the window will release, or None when done."""
        if self._cursor >= len(self._order):
            return None
        return self._order[self._cursor]

    def pending(self) -> int:
        """Items parked out of order (the memory the window is holding)."""
        return len(self._staged)

    def done(self) -> bool:
        return self._cursor >= len(self._order)

    def stage(self, key: Any, item: Any) -> List[Tuple[Any, Any]]:
        """Park ``item`` and return the (possibly empty) newly contiguous
        run, in order.  Unknown keys raise; re-staging a key that was
        already released or parked is the caller's dedup bug."""
        if key not in self._order:
            raise KeyError(f"key {key!r} not in this window's order")
        if key in self._staged or self._order.index(key) < self._cursor:
            raise ValueError(f"key {key!r} staged twice")
        self._staged[key] = item
        return self.release()

    def release(self) -> List[Tuple[Any, Any]]:
        out: List[Tuple[Any, Any]] = []
        while self._cursor < len(self._order):
            key = self._order[self._cursor]
            if key not in self._staged:
                break
            out.append((key, self._staged.pop(key)))
            self._cursor += 1
        return out
