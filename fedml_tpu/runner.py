"""Runner dispatch: (training_type, backend, role) -> runner with .run().

Parity with reference ``runner.py:14-123`` (``FedMLRunner``).
"""

from __future__ import annotations

from .constants import (
    FEDML_TRAINING_PLATFORM_CROSS_DEVICE,
    FEDML_TRAINING_PLATFORM_CROSS_SILO,
    FEDML_TRAINING_PLATFORM_SIMULATION,
)


class FedMLRunner:
    def __init__(self, args, device, dataset, model, client_trainer=None, server_aggregator=None):
        self.args = args
        training_type = str(getattr(args, "training_type", FEDML_TRAINING_PLATFORM_SIMULATION))
        if training_type == FEDML_TRAINING_PLATFORM_SIMULATION:
            self.runner = self._init_simulation_runner(args, device, dataset, model)
        elif training_type == FEDML_TRAINING_PLATFORM_CROSS_SILO:
            self.runner = self._init_cross_silo_runner(
                args, device, dataset, model, client_trainer, server_aggregator
            )
        elif training_type == FEDML_TRAINING_PLATFORM_CROSS_DEVICE:
            self.runner = self._init_cross_device_runner(args, device, dataset, model, server_aggregator)
        else:
            raise ValueError(f"unknown training_type {training_type!r}")

    def _init_simulation_runner(self, args, device, dataset, model):
        from .simulation.simulator import create_simulator

        return create_simulator(args, device, dataset, model)

    def _init_cross_silo_runner(self, args, device, dataset, model, client_trainer, server_aggregator):
        role = str(getattr(args, "role", "client"))
        if role == "server":
            from .cross_silo.server.server import Server

            return Server(args, device, dataset, model, server_aggregator)
        from .cross_silo.client.client import Client

        return Client(args, device, dataset, model, client_trainer)

    def _init_cross_device_runner(self, args, device, dataset, model, server_aggregator):
        from .cross_device.server import ServerDevice

        return ServerDevice(args, device, dataset, model, server_aggregator)

    def run(self):
        return self.runner.run()
