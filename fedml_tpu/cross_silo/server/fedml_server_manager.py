"""Cross-silo server round state machine.

Parity with reference ``cross_silo/server/fedml_server_manager.py:12-207``:
wait for every client's ONLINE status, push init config (round-0 model +
assigned client index), then per round: collect models → aggregate → test →
select next participants → sync model; after the final round send FINISH and
stop.  Message vocabulary in :mod:`..message_define`.

Beyond-reference: straggler tolerance.  The reference (and our default)
blocks a round forever on a dead client; setting ``round_timeout_s`` arms a
per-round timer — on expiry, if at least ``round_timeout_min_clients``
models arrived, the round closes with the partial cohort (weighted
aggregate over the received silos) and stale uploads from the previous
round are dropped by their round tag; with fewer, the timer re-arms and
waits (aggregating nothing is worse than waiting).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

from ...core.distributed.comm_manager import FedMLCommManager
from ...core.distributed.communication.message import Message
from ..message_define import MyMessage

logger = logging.getLogger(__name__)


class FedMLServerManager(FedMLCommManager):
    def __init__(self, args, aggregator, comm=None, client_rank: int = 0, client_num: int = 0, backend: str = "LOOPBACK"):
        super().__init__(args, comm, client_rank, client_num + 1, backend)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 1))
        self.args.round_idx = 0
        self.client_num = int(client_num)
        self.client_online_status: Dict[int, bool] = {}
        self.is_initialized = False
        self.client_id_list_in_this_round: List[int] = []
        self.data_silo_index_of_client: Dict[int, int] = {}
        self.eval_history: List[Dict[str, Any]] = []
        # straggler tolerance (0 = reference semantics: wait forever)
        self.round_timeout_s = float(getattr(args, "round_timeout_s", 0) or 0)
        self.round_timeout_min_clients = int(
            getattr(args, "round_timeout_min_clients", 1) or 1
        )
        self._round_lock = threading.Lock()  # handler thread vs timeout timer
        self._round_timer: Optional[threading.Timer] = None
        self._handshake_timer: Optional[threading.Timer] = None
        self._gen = 0  # phase generation: stale timer callbacks no-op
        self._finished = False

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> None:
        super().run()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", self.handle_message_connection_ready)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.handle_message_client_status_update
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.handle_message_receive_model_from_client
        )

    # -- handlers -----------------------------------------------------------
    def handle_message_connection_ready(self, msg: Message) -> None:
        # Probe all clients for status (reference sends CHECK_CLIENT_STATUS
        # until every silo reports ONLINE, fedml_server_manager.py:58-79).
        for client_id in range(1, self.client_num + 1):
            m = Message(MyMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self.rank, client_id)
            self._send_safe(m)

    def handle_message_client_status_update(self, msg: Message) -> None:
        status = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS)
        sender = int(msg.get_sender_id())
        with self._round_lock:
            if status == MyMessage.CLIENT_STATUS_ONLINE:
                self.client_online_status[sender] = True
            logger.info("client %s status=%s (%d/%d online)", sender, status,
                        sum(self.client_online_status.values()), self.client_num)
            if self.is_initialized:
                return
            if all(self.client_online_status.get(cid, False)
                   for cid in range(1, self.client_num + 1)):
                self.is_initialized = True
                self.send_init_msg()
            elif self.round_timeout_s > 0 and self._handshake_timer is None:
                # a client that never comes ONLINE must not wedge the run:
                # bound the handshake wait with the same round timeout
                self._start_phase_timer("_handshake_timer", self._on_handshake_timeout)

    def _on_handshake_timeout(self, gen: int) -> None:
        with self._round_lock:
            if self.is_initialized or self._finished or gen != self._gen:
                return
            online = sum(self.client_online_status.values())
            if online < max(1, self.round_timeout_min_clients):
                logger.warning(
                    "handshake timeout with %d/%d online (< min %d): waiting on",
                    online, self.client_num, self.round_timeout_min_clients,
                )
                self._start_phase_timer("_handshake_timer", self._on_handshake_timeout)
                return
            logger.warning(
                "handshake timeout: starting round 0 with %d/%d clients online "
                "(the round timer covers their missing uploads)",
                online, self.client_num,
            )
            self.is_initialized = True
            self.send_init_msg()

    def send_init_msg(self) -> None:
        """Round-0 kick-off (reference send_message_init_config :182)."""
        self._gen += 1  # the handshake phase closes; its timers go stale
        self.client_id_list_in_this_round = self.aggregator.client_selection(
            self.args.round_idx, list(range(1, self.client_num + 1)),
            int(getattr(self.args, "client_num_per_round", self.client_num)),
        )
        self.data_silo_index_of_client = dict(zip(
            self.client_id_list_in_this_round,
            self.aggregator.data_silo_selection(
                self.args.round_idx,
                int(getattr(self.args, "client_num_in_total", self.client_num)),
                len(self.client_id_list_in_this_round),
            ),
        ))
        global_model = self.aggregator.get_global_model_params()
        for client_id in self.client_id_list_in_this_round:
            m = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.rank, client_id)
            m.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model)
            m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, self.data_silo_index_of_client[client_id])
            m.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.args.round_idx)
            self._send_safe(m)
        self._arm_round_timer()

    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        from ...core.compression import is_compressed, maybe_decompress_update

        sender = int(msg.get_sender_id())
        with self._round_lock:
            if self._finished:
                return
            msg_round = msg.get(MyMessage.MSG_ARG_KEY_ROUND_INDEX, None)
            if msg_round is not None and int(msg_round) != int(self.args.round_idx):
                # straggler upload for an already-closed round: the client
                # will pick up the current sync next (reference has no tag
                # and would silently fold it into the wrong round)
                logger.warning("dropping stale round-%s upload from client %d "
                               "(current round %d)", msg_round, sender,
                               self.args.round_idx)
                return
            if sender not in self.client_id_list_in_this_round:
                logger.warning("dropping upload from non-participant %d", sender)
                return
            raw = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
            is_delta = is_compressed(raw) and bool(raw.get("is_delta"))
            model_params = maybe_decompress_update(raw)
            if is_delta:
                # compressed uploads carry the UPDATE; rebase onto the global
                # params this round distributed
                import jax
                import jax.numpy as jnp

                base = self.aggregator.get_global_model_params()
                model_params = jax.tree_util.tree_map(
                    lambda g, d: jnp.asarray(g) + jnp.asarray(d), base, model_params
                )
            local_sample_number = msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES)
            self.aggregator.add_local_trained_result(
                self.client_id_list_in_this_round.index(sender), model_params,
                local_sample_number,
            )
            if not self.aggregator.check_whether_all_receive():
                return
            self._cancel_round_timer()
            self._finalize_safely(None)

    def _finalize_safely(self, indices: Optional[List[int]]) -> None:
        """(lock held) Finalize with the error policy both close paths share:
        with straggler tolerance on, a finalize failure shuts the run down
        cleanly (flags are already consumed and no timer may be armed — an
        escaped exception would wedge the run the feature exists to prevent);
        with the knob off, the exception propagates loudly as the reference
        semantics would."""
        if self.round_timeout_s <= 0:
            self._finalize_round(indices)
            return
        try:
            self._finalize_round(indices)
        except Exception:
            logger.exception("round finalize failed; shutting down")
            self._finished = True
            self.send_finish_msg()
            self.finish()

    def _finalize_round(self, indices: Optional[List[int]]) -> None:
        """Close the current round (caller holds the lock): aggregate the
        ``indices`` cohort (None = every silo), eval, then either finish or
        open the next round."""
        self._gen += 1  # this round's phase closes; its timers go stale
        self.aggregator.aggregate(indices)
        freq = int(getattr(self.args, "frequency_of_the_test", 1) or 0)
        if freq and (self.args.round_idx % freq == 0 or self.args.round_idx == self.round_num - 1):
            self.eval_history.append(
                self.aggregator.test_on_server_for_all_clients(self.args.round_idx)
            )

        self.args.round_idx += 1
        if self.args.round_idx >= self.round_num:
            self._finished = True
            self.send_finish_msg()
            self.finish()
            return

        # next round participants + model sync (reference :202)
        self.client_id_list_in_this_round = self.aggregator.client_selection(
            self.args.round_idx, list(range(1, self.client_num + 1)),
            int(getattr(self.args, "client_num_per_round", self.client_num)),
        )
        self.data_silo_index_of_client = dict(zip(
            self.client_id_list_in_this_round,
            self.aggregator.data_silo_selection(
                self.args.round_idx,
                int(getattr(self.args, "client_num_in_total", self.client_num)),
                len(self.client_id_list_in_this_round),
            ),
        ))
        global_model = self.aggregator.get_global_model_params()
        for client_id in self.client_id_list_in_this_round:
            m = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, client_id)
            m.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model)
            m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, self.data_silo_index_of_client[client_id])
            m.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.args.round_idx)
            self._send_safe(m)
        self._arm_round_timer()

    def _send_safe(self, m: Message) -> None:
        """Fan-out send that survives a dead receiver: a transport error for
        one client (e.g. gRPC connection-refused after its process died)
        must not abort the loop delivering to the live ones.  Swallowing is
        only safe when the round timer covers the lost message — with the
        knob off (reference wait-forever semantics) the error re-raises, a
        loud failure instead of a silent infinite wait."""
        try:
            self.send_message(m)
        except Exception as e:
            logger.warning("send %s -> client %s failed: %s",
                           m.get_type(), m.get_receiver_id(), e)
            if self.round_timeout_s <= 0 and not self._finished:
                # loud failure in the wait-forever default — but never on
                # the FINISH fan-out, where aborting the loop would leave
                # the surviving clients (and this server) hanging instead
                raise

    # -- straggler tolerance ------------------------------------------------
    def _start_phase_timer(self, attr: str, callback) -> None:
        """(lock held) Arm the daemon timer stored at ``attr``, tagging the
        callback with the CURRENT phase generation: ``Timer.cancel`` cannot
        stop a callback that already fired and is waiting on the lock, so
        every phase change bumps ``self._gen`` and a stale callback no-ops
        on the mismatch instead of closing the next phase prematurely."""
        old = getattr(self, attr)
        if old is not None:
            old.cancel()
        t = threading.Timer(self.round_timeout_s, callback, args=(self._gen,))
        t.daemon = True
        t.start()
        setattr(self, attr, t)

    def _arm_round_timer(self) -> None:
        if self.round_timeout_s <= 0 or self._finished:
            return
        self._start_phase_timer("_round_timer", self._on_round_timeout)

    def _cancel_round_timer(self) -> None:
        if self._round_timer is not None:
            self._round_timer.cancel()
            self._round_timer = None

    def _on_round_timeout(self, gen: int) -> None:
        with self._round_lock:
            if self._finished or gen != self._gen:
                return  # stale callback: its phase already closed
            got = self.aggregator.received_indices()
            if len(got) < max(1, self.round_timeout_min_clients):
                logger.warning(
                    "round %d timeout with %d/%d models (< min %d): waiting on",
                    self.args.round_idx, len(got), len(self.client_id_list_in_this_round),
                    self.round_timeout_min_clients,
                )
                self._arm_round_timer()
                return
            logger.warning(
                "round %d timeout: closing with %d/%d silos (stragglers dropped)",
                self.args.round_idx, len(got), len(self.client_id_list_in_this_round),
            )
            self._finalize_safely(self.aggregator.consume_received())

    def send_finish_msg(self) -> None:
        for client_id in range(1, self.client_num + 1):
            self._send_safe(Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, client_id))
