"""Cross-silo server round state machine.

Parity with reference ``cross_silo/server/fedml_server_manager.py:12-207``:
wait for every client's ONLINE status, push init config (round-0 model +
assigned client index), then per round: collect models → aggregate → test →
select next participants → sync model; after the final round send FINISH and
stop.  Message vocabulary in :mod:`..message_define`.

Beyond-reference: straggler tolerance.  The reference (and our default)
blocks a round forever on a dead client; setting ``round_timeout_s`` arms a
per-round timer — on expiry, if at least ``round_timeout_min_clients``
models arrived, the round closes with the partial cohort (weighted
aggregate over the received silos) and stale uploads from the previous
round are dropped by their round tag; with fewer, the timer re-arms and
waits (aggregating nothing is worse than waiting).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ...core import ingest, obs
from ...core.async_fl import AsyncBufferedServerMixin
from ...core.checkpoint import ServerRecoveryMixin
from ...core.distributed.comm_manager import FedMLCommManager
from ...core.distributed.communication.message import Message
from ...core.distributed.communication.serialization import CachedPayload
from ...core.distributed.straggler import RoundTimeoutMixin
from ...core.obs.rounds import RoundObsMixin
from ...core.population import PopulationPacingMixin
from ..message_define import MyMessage

logger = logging.getLogger(__name__)


class FedMLServerManager(RoundObsMixin, ServerRecoveryMixin,
                         AsyncBufferedServerMixin, PopulationPacingMixin,
                         RoundTimeoutMixin, FedMLCommManager):
    def __init__(self, args, aggregator, comm=None, client_rank: int = 0, client_num: int = 0, backend: str = "LOOPBACK"):
        super().__init__(args, comm, client_rank, client_num + 1, backend)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 1))
        self.args.round_idx = 0
        self.client_num = int(client_num)
        self.per_round = int(getattr(args, "client_num_per_round", self.client_num) or self.client_num)
        self.client_online_status: Dict[int, bool] = {}
        self.is_initialized = False
        self.client_id_list_in_this_round: List[int] = []
        self.data_silo_index_of_client: Dict[int, int] = {}
        self.eval_history: List[Dict[str, Any]] = []
        # broadcast-payload cache: one serialized blob per round's fan-out
        self._bcast_cache: tuple = (None, None)
        # shard-addressable broadcast: per-(round, shard) CachedPayload memo
        # (server_state=sharded; clients/edge aggregators fetch slices)
        self._bcast_shard_cache: tuple = (None, {})
        # zero-copy ingest arenas (per-sender), active with the pipeline
        self._zero_copy = (ingest.ZeroCopyDecoder()
                           if ingest.pipeline_enabled(args) else None)
        # straggler tolerance (0 = reference semantics: wait forever) —
        # the shared machinery lives in core/distributed/straggler.py
        self.init_straggler_tolerance(args)
        # fleet registry + selection policy + pacer (core/population); the
        # uniform policy reproduces client_selection's legacy pcg64 schedule
        self.init_population(args, list(range(1, self.client_num + 1)),
                             rng_style="pcg64")
        # buffered-async mode (core/async_fl) — needs the population
        # registry, must precede recovery (journal replay fills the buffer)
        self.init_async_fl(args)
        # crash recovery last: a restore overwrites round_idx / participant
        # list / registry columns and replays the open round's journal
        self.init_server_recovery(args)
        if self.is_initialized:
            # restored mid-round: hold the open round's root span without
            # re-emitting its start (the dead incarnation opened it)
            self._obs_adopt_round()
            if self.async_enabled:
                # the snapshot's participants are the run's pool; their
                # ONLINE re-reports resync them into the open cycle
                self._async_active.update(self.client_id_list_in_this_round)

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> None:
        super().run()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", self.handle_message_connection_ready)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.handle_message_client_status_update
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.handle_message_receive_model_from_client
        )
        self.register_message_receive_handler(
            obs.TOPIC_TELEMETRY, self.handle_message_telemetry
        )

    def _telemetry_merger(self):
        """This server's telemetry fan-in (lazily bound, per-instance so
        the in-process test harness keeps nodes' sequence spaces apart).
        On first creation the merger's counters are hung on the flight
        recorder's dump meta."""
        merger = getattr(self, "_telemetry", None)
        if merger is None:
            merger = obs.make_telemetry_merger()
            self._telemetry = merger
            if merger is not None:
                flight = obs.flight_recorder()
                if flight is not None:
                    flight.meta_provider = merger.counters
        return merger

    def handle_message_telemetry(self, msg: Message) -> None:
        """Standalone telemetry flush (async mode's periodic blob)."""
        merger = self._telemetry_merger()
        if merger is not None:
            merger.absorb(msg)

    # -- handlers -----------------------------------------------------------
    def handle_message_connection_ready(self, msg: Message) -> None:
        # Probe all clients for status (reference sends CHECK_CLIENT_STATUS
        # until every silo reports ONLINE, fedml_server_manager.py:58-79).
        for client_id in range(1, self.client_num + 1):
            m = Message(MyMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self.rank, client_id)
            self._send_safe(m)

    def handle_message_client_status_update(self, msg: Message) -> None:
        status = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS)
        sender = int(msg.get_sender_id())
        epoch = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_EPOCH)
        with self._round_lock:
            if status == MyMessage.CLIENT_STATUS_ONLINE:
                if self._note_client_online(sender, epoch):
                    self._resync_rejoined_client(sender)
            logger.info("client %s status=%s (%d/%d online)", sender, status,
                        sum(self.client_online_status.values()), self.client_num)
            self._handshake_check()
            # restored round whose journal already held the full cohort:
            # close it now that the transport is live
            self._maybe_close_recovered_round()

    def _resync_rejoined_client(self, client_id: int) -> None:
        """(lock held) A silo died and came back mid-run: hand it the current
        round's model so it rejoins THIS round instead of being ignored until
        the run ends (the reference behavior this layer replaces)."""
        if self._finished:
            # run is over — release the rejoined silo instead of leaving it
            # waiting for a FINISH that already went to its dead predecessor
            self._send_safe(Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, client_id))
            return
        if self.async_enabled:
            self._async_resync(client_id)
            return
        if client_id not in self.client_id_list_in_this_round:
            return  # sitting this round out; selection may pick it up later
        pos = self.client_id_list_in_this_round.index(client_id)
        if pos in self.aggregator.received_indices():
            return  # its upload already landed; the round-close sync suffices
        m = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, client_id)
        m.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, self._broadcast_payload())
        m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, self.data_silo_index_of_client[client_id])
        m.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.args.round_idx)
        self._send_safe(m)

    def _broadcast_payload(self) -> CachedPayload:
        """The round's global model wrapped for serialize-once fan-out: every
        invite/sync/resync (and the reliable link's retransmits, which reuse
        the tracked Message object) of one round shares ONE wire blob instead
        of re-pickling the identical tree per client."""
        key = int(self.args.round_idx)
        cached_key, payload = self._bcast_cache
        if cached_key != key:
            payload = CachedPayload(self.aggregator.get_global_model_params())
            self._bcast_cache = (key, payload)
        return payload

    def shard_payload(self, shard_idx: int) -> CachedPayload:
        """Shard-addressable broadcast: one :class:`CachedPayload` per
        (round, shard) slice of the global model, memoized exactly like the
        full-tree payload — a client (or a future edge aggregator) that
        needs only its slice fetches ``broadcast_shards - 1`` fewer bytes.
        Shard layout comes from ``parallel.agg_plane.broadcast_shards``;
        ``assemble_shards`` reassembles the tree exactly."""
        from ...parallel.agg_plane import broadcast_shards

        num = int(getattr(self.args, "broadcast_shards", 1) or 1)
        key = int(self.args.round_idx)
        cached_key, payloads = self._bcast_shard_cache
        if cached_key != key:
            shards = broadcast_shards(
                self.aggregator.get_global_model_params(), num)
            payloads = {s["shard"]: CachedPayload(s) for s in shards}
            self._bcast_shard_cache = (key, payloads)
        if int(shard_idx) not in payloads:
            raise ValueError(
                f"shard {shard_idx} out of range for broadcast_shards={num}")
        return payloads[int(shard_idx)]

    def send_init_msg(self) -> None:
        """Round-0 kick-off (reference send_message_init_config :182)."""
        self._obs_open_round()
        with self._obs_phase("select", k=self.per_round):
            self.client_id_list_in_this_round = self._population_round_list(
                self.args.round_idx, self.per_round
            )
            self.data_silo_index_of_client = dict(zip(
                self.client_id_list_in_this_round,
                self.aggregator.data_silo_selection(
                    self.args.round_idx,
                    int(getattr(self.args, "client_num_in_total", self.client_num)),
                    len(self.client_id_list_in_this_round),
                ),
            ))
        global_model = self._broadcast_payload()
        # durable round-open point: participants + silo map are fixed, no
        # upload has been accepted yet — a crash from here on resumes round 0
        self._save_round_start()
        with self._obs_phase(
                "invite", fanout=len(self.client_id_list_in_this_round)) as inv:
            for client_id in self.client_id_list_in_this_round:
                m = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.rank, client_id)
                m.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model)
                m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, self.data_silo_index_of_client[client_id])
                m.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.args.round_idx)
                # clients parent their train/upload spans under the invite
                obs.inject(m, inv.ctx)
                self._send_safe(m)
        if self.async_enabled:
            # cycle 0 of the buffered mode: the init fan-out IS the first
            # dispatch wave; the flush deadline replaces the round timer
            self._async_note_dispatch_wave(self.client_id_list_in_this_round)
            return
        self._arm_round_timer()

    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        from ...core.compression import is_compressed, maybe_decompress_update

        sender = int(msg.get_sender_id())
        with self._round_lock:
            # best-effort telemetry merge first: even a stale or dropped
            # upload's piggybacked blob is valid observability data
            merger = self._telemetry_merger()
            measured = None
            if merger is not None:
                merger.absorb(msg)
                measured = merger.train_seconds(sender)
            if self._finished:
                return
            if not self.async_enabled and self._is_stale_upload(
                    msg.get(MyMessage.MSG_ARG_KEY_ROUND_INDEX, None), sender):
                return
            if not self.async_enabled and sender not in self.client_id_list_in_this_round:
                logger.warning("dropping upload from non-participant %d", sender)
                return
            raw = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
            is_delta = is_compressed(raw) and bool(raw.get("is_delta"))
            t_dec = time.perf_counter()
            model_params = maybe_decompress_update(raw)
            obs.histogram_observe("upload.decode_seconds",
                                  time.perf_counter() - t_dec,
                                  labels={"plane": "cross_silo"})
            if is_delta:
                # compressed uploads carry the UPDATE; rebase onto the global
                # params this round distributed (async: onto the CURRENT
                # global — delta-application semantics, docs/ASYNC.md)
                import jax
                import jax.numpy as jnp

                base = self.aggregator.get_global_model_params()
                model_params = jax.tree_util.tree_map(
                    lambda g, d: jnp.asarray(g) + jnp.asarray(d), base, model_params
                )
            local_sample_number = msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES)
            if self.async_enabled:
                # buffered mode: the version tag + in-flight match replace
                # the round-tag staleness check and the participant gate
                self._async_handle_upload(
                    sender, model_params, local_sample_number,
                    msg.get(MyMessage.MSG_ARG_KEY_ROUND_INDEX, None),
                    parent_ctx=obs.extract(msg),
                    measured_seconds=measured)
                return
            # durably journal the accepted upload BEFORE it enters the slot
            # table; the transport ack goes out only after this handler
            # returns, so an acked upload is always journaled.  False means
            # this sender already landed this round (retransmit into a new
            # incarnation) — discard instead of double-count.
            with self._obs_phase("journal.append", parent=obs.extract(msg),
                                 seq=sender, sender=sender) as jsp:
                ok = self._journal_upload(sender, model_params=model_params,
                                          n_samples=local_sample_number)
                if not ok:
                    jsp.event("dup", side="journal", sender=sender)
            if not ok:
                return
            if self._zero_copy is not None:
                # accepted: land the leaves in this sender's preallocated
                # arena (reused next round, AFTER aggregation consumed it)
                model_params = self._zero_copy.intern(sender, model_params)
            self.aggregator.add_local_trained_result(
                self.client_id_list_in_this_round.index(sender), model_params,
                local_sample_number,
            )
            self._note_population_report(sender, local_sample_number,
                                         seconds=measured)
            self._close_round_if_complete()

    def _finalize_round(self, indices: Optional[List[int]]) -> None:
        """Close the current round (caller holds the lock): aggregate the
        ``indices`` cohort (None = every silo), eval, then either finish or
        open the next round."""
        self._gen += 1  # this round's phase closes; its timers go stale
        closing_idx = int(self.args.round_idx)
        closing_ctx = self._obs_round_ctx()
        closing_root = self._obs_round
        with self._obs_phase(
                "aggregate",
                n_uploads=(len(indices) if indices is not None
                           else len(self.client_id_list_in_this_round))):
            self.aggregator.aggregate(indices)
            freq = int(getattr(self.args, "frequency_of_the_test", 1) or 0)
            if freq and (self.args.round_idx % freq == 0 or self.args.round_idx == self.round_num - 1):
                self.eval_history.append(
                    self.aggregator.test_on_server_for_all_clients(self.args.round_idx)
                )
        obs.maybe_export_metrics()

        self.args.round_idx += 1
        if self.args.round_idx >= self.round_num:
            self._finished = True
            with self._obs_phase("broadcast", parent=closing_ctx,
                                 round_idx=closing_idx, final=True):
                self.send_finish_msg()
            self._obs_close_round(reason="run_complete")
            self.finish()
            return

        # next round participants + model sync (reference :202) — the
        # population policy replaces direct client_selection (over-commit
        # inflates the invite list when pacing is on).  Span handoff: the
        # closing round's root stays open until its aggregate is broadcast;
        # the broadcast span sits under the OLD root while the invite span
        # (whose context rides the sync messages) sits under the NEW one.
        self._obs_round = None
        self._obs_open_round()
        with self._obs_phase("select", k=self.per_round):
            self.client_id_list_in_this_round = self._population_round_list(
                self.args.round_idx, self.per_round
            )
            self.data_silo_index_of_client = dict(zip(
                self.client_id_list_in_this_round,
                self.aggregator.data_silo_selection(
                    self.args.round_idx,
                    int(getattr(self.args, "client_num_in_total", self.client_num)),
                    len(self.client_id_list_in_this_round),
                ),
            ))
        global_model = self._broadcast_payload()
        # durable round-open point (see send_init_msg): a crash during or
        # after the sync sends resumes THIS round, and clients that already
        # got the sync are re-synced idempotently on their next ONLINE
        self._save_round_start()
        bcast = self._obs_phase("broadcast", parent=closing_ctx,
                                round_idx=closing_idx)
        with self._obs_phase(
                "invite", fanout=len(self.client_id_list_in_this_round)) as inv:
            for client_id in self.client_id_list_in_this_round:
                m = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, client_id)
                m.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model)
                m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, self.data_silo_index_of_client[client_id])
                m.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.args.round_idx)
                obs.inject(m, inv.ctx)
                self._send_safe(m)
        bcast.end()
        if closing_root is not None:
            closing_root.end(reason="closed")
        self._arm_round_timer()

    def send_finish_msg(self) -> None:
        for client_id in range(1, self.client_num + 1):
            self._send_safe(Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, client_id))

    # -- AsyncBufferedServerMixin hook (core/async_fl/server.py) -------------
    def _async_send_model(self, client_id: int, parent_ctx=None) -> None:
        """(lock held) One async dispatch: current global + version tag (the
        client echoes the tag on its upload — the staleness bookkeeping
        rides the existing wire)."""
        cid = int(client_id)
        m = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, cid)
        m.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                     self._broadcast_payload())
        m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                     self.data_silo_index_of_client.get(cid, cid - 1))
        m.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.args.round_idx)
        obs.inject(m, parent_ctx)
        self._send_safe(m)

    # -- ServerRecoveryMixin hooks (core/checkpoint.py) ----------------------
    def _capture_global_params(self):
        return self.aggregator.get_global_model_params()

    def _restore_global_params(self, tree) -> None:
        self.aggregator.set_global_model_params(tree)

    def _round_start_extras(self) -> Dict[str, Any]:
        # dicts with int keys don't survive msgpack: the silo index map rides
        # as two parallel columns aligned with the participant list
        return {
            "silo_clients": np.asarray(
                list(self.data_silo_index_of_client.keys()), np.int64),
            "silo_indices": np.asarray(
                list(self.data_silo_index_of_client.values()), np.int64),
            "eval_history": list(self.eval_history),
        }

    def _restore_round_extras(self, state: Dict[str, Any]) -> None:
        self.data_silo_index_of_client = {
            int(c): int(i) for c, i in zip(state["silo_clients"],
                                           state["silo_indices"])
        }
        self.eval_history = [dict(r) for r in state.get("eval_history", [])]

    def _capture_server_opt_state(self):
        return self.aggregator.export_server_opt_state()

    def _restore_server_opt_state(self, state) -> None:
        self.aggregator.restore_server_opt_state(state)

    def _replay_upload(self, record: Dict[str, Any]) -> bool:
        """Push one journaled upload back into the aggregator slot table —
        the same inserts the live handler performs, minus the transport."""
        if self.async_enabled:
            return self._async_replay_upload(record)
        sender = int(record["sender"])
        if sender not in self.client_id_list_in_this_round:
            return False
        self.aggregator.add_local_trained_result(
            self.client_id_list_in_this_round.index(sender),
            record["model_params"], record["n_samples"],
        )
        self._note_population_report(sender, record["n_samples"])
        return True
