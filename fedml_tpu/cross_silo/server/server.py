"""Cross-silo server facade (reference ``cross_silo/server/fedml_server.py`` +
``server_initializer.py``): builds aggregator + manager and runs the loop."""

from __future__ import annotations

import jax.numpy as jnp

from ...ml.aggregator.default_aggregator import DefaultServerAggregator
from ...ml.engine.train import init_variables
from .fedml_aggregator import FedMLAggregator
from .fedml_server_manager import FedMLServerManager


class Server:
    def __init__(self, args, device, dataset, model, server_aggregator=None):
        self.args = args
        (
            train_data_num,
            test_data_num,
            train_data_global,
            test_data_global,
            train_data_local_num_dict,
            train_data_local_dict,
            test_data_local_dict,
            class_num,
        ) = dataset
        if server_aggregator is None:
            server_aggregator = DefaultServerAggregator(model, args)
        if server_aggregator.get_model_params() is None:
            sample = jnp.asarray(train_data_global[0][:1])
            server_aggregator.set_model_params(
                init_variables(model, sample, seed=int(getattr(args, "random_seed", 0)))
            )
        worker_num = int(getattr(args, "client_num_per_round", getattr(args, "client_num_in_total", 1)))
        # with over-commit the manager invites ceil(K * overcommit) silos per
        # round — the aggregator's slot table must cover the whole invite
        # list or uploads past slot K would be invisible to received_indices
        from ...core.population import RoundPacer

        slots = RoundPacer.from_args(args).invite_count(worker_num)
        aggregator = FedMLAggregator(
            test_data_global, train_data_global, train_data_num, slots,
            device, args, server_aggregator,
        )
        backend = str(getattr(args, "backend", "LOOPBACK"))
        client_num = int(getattr(args, "client_num_in_total", worker_num))
        # building the manager may RESUME a crashed run: with
        # args.server_checkpoint_dir set it restores the latest round
        # snapshot, replays the upload journal, and bumps its incarnation
        # epoch (core/checkpoint.ServerRecoveryMixin)
        self.server_manager = FedMLServerManager(
            args, aggregator, client_rank=0, client_num=client_num, backend=backend
        )

    @property
    def resumed(self) -> bool:
        """True when this incarnation restored a crashed predecessor's round
        (supervisors use this to tell resume from cold start)."""
        return int(getattr(self.server_manager, "server_epoch", 0)) > 0

    def run(self):
        self.server_manager.run()
        return self.server_manager.eval_history
