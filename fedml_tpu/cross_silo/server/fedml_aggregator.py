"""Server-side buffered aggregator for cross-silo FL.

Parity with reference ``cross_silo/server/fedml_aggregator.py:12-180``:
``add_local_trained_result`` buffers per-client (n, params) until
``check_whether_all_receive``; ``aggregate`` runs the ServerAggregator hook
chain (attack-injection / defense / central DP at the reference positions);
``data_silo_selection`` + ``client_selection`` pick round participants.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


class FedMLAggregator:
    def __init__(self, test_global, train_global, all_train_data_num, client_num, device, args, server_aggregator):
        self.aggregator = server_aggregator
        self.args = args
        self.test_global = test_global
        self.train_global = train_global
        self.all_train_data_num = all_train_data_num
        self.client_num = int(client_num)
        self.device = device
        self.model_dict: Dict[int, Any] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self.flag_client_model_uploaded_dict: Dict[int, bool] = {i: False for i in range(self.client_num)}

    def get_global_model_params(self):
        return self.aggregator.get_model_params()

    def set_global_model_params(self, model_parameters):
        self.aggregator.set_model_params(model_parameters)

    # -- sharded server state (server_state=sharded) ------------------------
    def export_server_opt_state(self):
        """Numpy snapshot of the sharded optimizer/params state for the
        recovery store (None on the replicated path or before round 1)."""
        updater = getattr(self.aggregator, "round_updater", None)
        return updater.export_state() if updater is not None else None

    def restore_server_opt_state(self, state) -> None:
        """Re-install the restored globals into the round plane and load
        the optimizer state bit-identically (recovery restore path)."""
        updater = getattr(self.aggregator, "round_updater", None)
        if updater is not None and state is not None:
            updater.restore_state(self.get_global_model_params(), state)

    def add_local_trained_result(self, index: int, model_params, sample_num) -> None:
        logger.info("add_model index=%d n=%s", index, sample_num)
        self.model_dict[int(index)] = model_params
        self.sample_num_dict[int(index)] = float(sample_num)
        self.flag_client_model_uploaded_dict[int(index)] = True

    def check_whether_all_receive(self) -> bool:
        if not all(self.flag_client_model_uploaded_dict.get(i, False) for i in range(self.client_num)):
            return False
        for i in range(self.client_num):
            self.flag_client_model_uploaded_dict[i] = False
        return True

    def received_indices(self) -> List[int]:
        """Silo indices whose model arrived this round (unconsumed flags)."""
        return [i for i in range(self.client_num)
                if self.flag_client_model_uploaded_dict.get(i, False)]

    def consume_received(self, got: Optional[List[int]] = None) -> List[int]:
        """Straggler-tolerant round close: return the received indices and
        reset their flags (the partial-aggregation analogue of
        check_whether_all_receive's reset).  ``got`` lets a caller that
        already scanned under the lock skip the second scan."""
        if got is None:
            got = self.received_indices()
        for i in got:
            self.flag_client_model_uploaded_dict[i] = False
        return got

    def aggregate(self, indices: Optional[List[int]] = None):
        """Weighted aggregate over ``indices`` (default: every silo — the
        reference's all-received path)."""
        t0 = time.time()
        if indices is None:
            indices = list(range(self.client_num))
        raw: List[Tuple[float, Any]] = [
            (self.sample_num_dict[i], self.model_dict[i]) for i in indices
        ]
        raw = self.aggregator.on_before_aggregation(raw)
        # ServerAggregator.aggregate -> FedMLAggOperator.agg, which routes to
        # parallel/agg_plane when args.agg_plane == "compiled"
        averaged = self.aggregator.aggregate(raw)
        averaged = self.aggregator.on_after_aggregation(averaged)
        self.aggregator.set_model_params(averaged)
        logger.info("aggregate %d silos in %.3fs plane=%s", len(raw),
                    time.time() - t0,
                    getattr(self.args, "agg_plane", "host") or "host")
        return averaged

    def aggregate_buffered(self, weighted_updates: List[Tuple[float, Any]]):
        """Async-flush aggregate: the caller (core/async_fl) supplies the
        ``(weight, params)`` list directly — weights already carry the
        ``n_samples * staleness_weight`` discount and the list is in the
        buffer's canonical drain order.  Runs the same ServerAggregator
        hook chain (and therefore the same ``agg_plane`` routing) as
        :meth:`aggregate`, so a constant-weight full-cohort flush is
        bit-identical to the sync path."""
        t0 = time.time()
        raw = self.aggregator.on_before_aggregation(list(weighted_updates))
        averaged = self.aggregator.aggregate(raw)
        averaged = self.aggregator.on_after_aggregation(averaged)
        self.aggregator.set_model_params(averaged)
        logger.info("buffered aggregate of %d deltas in %.3fs plane=%s",
                    len(raw), time.time() - t0,
                    getattr(self.args, "agg_plane", "host") or "host")
        return averaged

    # -- participant selection (reference :87-135) --------------------------
    def data_silo_selection(self, round_idx: int, data_silo_num_in_total: int, client_num_in_total: int) -> List[int]:
        """Map each of ``client_num_in_total`` FL client processes to a data
        silo index (uniform with per-round seed, reference :87-111)."""
        if data_silo_num_in_total == client_num_in_total:
            return list(range(data_silo_num_in_total))
        rng = np.random.default_rng(round_idx)
        return rng.choice(data_silo_num_in_total, client_num_in_total, replace=True).tolist()

    def client_selection(self, round_idx: int, client_id_list_in_total: List[int], client_num_per_round: int) -> List[int]:
        """Sample real edge ids for the round (reference :113-135).  The
        draw is the population subsystem's pcg64 uniform schedule — the
        server manager now selects through its ``PopulationManager``, and
        this method delegates to the same implementation so both surfaces
        stay bit-identical."""
        from ...core.population import uniform_id_choice

        return uniform_id_choice(round_idx, client_id_list_in_total, client_num_per_round)

    def test_on_server_for_all_clients(self, round_idx: int) -> Dict[str, Any]:
        stats = self.aggregator.test(self.test_global, self.device, self.args)
        total = max(stats.get("test_total", 0.0), 1.0)
        out = {
            "round": round_idx,
            "test_acc": round(float(stats.get("test_correct", 0.0)) / total, 4),
            "test_loss": round(float(stats.get("test_loss", 0.0)) / total, 4),
        }
        logger.info("server eval: %s", out)
        return out
