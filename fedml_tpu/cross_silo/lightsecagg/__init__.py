from .lsa_fedml_api import run_lightsecagg_topology_in_threads  # noqa: F401
