"""Cross-silo FedAvg with LightSecAgg (dropout-tolerant secure aggregation).

Scenario parity with reference ``cross_silo/lightsecagg/`` (lsa_fedml_api.py,
lsa_fedml_aggregator.py, ~1200 LoC): each client one-time-pad-masks its
quantized update with a LOCAL random mask, LCC-encodes that mask into N
sub-masks exchanged client-to-client, and the server reconstructs only the
SUM of surviving clients' masks from any ``u`` surviving aggregate-encoded
shares (core/mpc/lightsecagg.py) — so aggregation survives dropouts without
ever revealing an individual mask or update.

Round protocol:
  S2C LSA_INIT (global model, n/t/u params)
  client: draw mask z_i, LCC-encode -> C2C ENCODED_MASK rows
  client: local train -> quantized update + z_i -> C2S MASKED_MODEL
          (a client configured to drop sends C2S DROP instead — standing in
          for the transport-level liveness timeout that detects real deaths)
  server: surviving set = masked-model senders -> S2C REQUEST_AGG_MASK
  client: sum of received rows over surviving set -> C2S AGG_ENCODED_MASK
  server: reconstruct aggregate mask, subtract, dequantize, average -> SYNC/FINISH
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from ...core.distributed.comm_manager import FedMLCommManager
from ...core.distributed.communication.message import Message
from ...core.mpc.field import FIELD_PRIME
from ...core.mpc.lightsecagg import (
    aggregate_mask_reconstruction,
    compute_aggregate_encoded_mask,
    mask_encoding,
)
from ...ml.engine.train import init_variables, make_eval_fn
from ...ml.trainer.cls_trainer import ModelTrainerCLS
from ..secagg.flatten import flatten_to_finite, unflatten_from_finite

logger = logging.getLogger(__name__)

Q_BITS = 16


class LSAMessage:
    MSG_TYPE_S2C_INIT = "lsa_init"
    MSG_TYPE_S2C_SYNC = "lsa_sync"
    MSG_TYPE_S2C_REQUEST_AGG_MASK = "lsa_req_agg_mask"
    MSG_TYPE_S2C_FINISH = "lsa_finish"
    MSG_TYPE_C2C_ENCODED_MASK = "lsa_encoded_mask"
    MSG_TYPE_C2S_MASKED_MODEL = "lsa_masked_model"
    MSG_TYPE_C2S_DROP = "lsa_drop"
    MSG_TYPE_C2S_AGG_ENCODED_MASK = "lsa_agg_encoded_mask"
    MSG_TYPE_C2S_STATUS = "lsa_status"


class LightSecAggServerManager(FedMLCommManager):
    def __init__(self, args, dataset, model, backend: str = "LOOPBACK"):
        client_num = int(getattr(args, "client_num_in_total", 1))
        super().__init__(args, rank=0, size=client_num + 1, backend=backend)
        (_, _, _, self.test_global, _, _, _, _) = dataset
        self.module = model
        self.n = client_num
        self.t = int(getattr(args, "lsa_privacy_t", 1))
        self.u = int(getattr(args, "lsa_threshold_u", max(self.t + 1, client_num - 1)))
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        import jax.numpy as jnp

        sample = jnp.asarray(self.test_global[0][:1])
        self.global_params = init_variables(model, sample, seed=int(getattr(args, "random_seed", 0)))
        self.online: Dict[int, bool] = {}
        self.masked: Dict[int, np.ndarray] = {}
        self.dropped: set = set()
        self.agg_masks: Dict[int, np.ndarray] = {}
        self.meta: Optional[dict] = None
        self.eval_history: List[Dict[str, Any]] = []
        self._eval_fn = None
        self._requested = False

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", lambda m: None)
        self.register_message_receive_handler(LSAMessage.MSG_TYPE_C2S_STATUS, self._on_status)
        self.register_message_receive_handler(LSAMessage.MSG_TYPE_C2S_MASKED_MODEL, self._on_masked)
        self.register_message_receive_handler(LSAMessage.MSG_TYPE_C2S_DROP, self._on_drop)
        self.register_message_receive_handler(LSAMessage.MSG_TYPE_C2S_AGG_ENCODED_MASK, self._on_agg_mask)

    def _on_status(self, msg: Message) -> None:
        self.online[int(msg.get_sender_id())] = True
        if len(self.online) == self.n and self.round_idx == 0 and not self.masked:
            self._send_round(LSAMessage.MSG_TYPE_S2C_INIT)

    def _send_round(self, msg_type: str) -> None:
        for cid in range(1, self.n + 1):
            m = Message(msg_type, 0, cid)
            m.add_params("model_params", self.global_params)
            m.add_params("round_idx", self.round_idx)
            m.add_params("lsa_n", self.n)
            m.add_params("lsa_t", self.t)
            m.add_params("lsa_u", self.u)
            self.send_message(m)

    def _on_masked(self, msg: Message) -> None:
        sender = int(msg.get_sender_id())
        self.masked[sender] = np.asarray(msg.get("masked_vector"))
        if self.meta is None:
            self.meta = {"treedef": msg.get("treedef"), "shapes": msg.get("shapes"), "d": int(msg.get("d"))}
        self._maybe_request_agg_masks()

    def _on_drop(self, msg: Message) -> None:
        self.dropped.add(int(msg.get_sender_id()))
        self._maybe_request_agg_masks()

    def _maybe_request_agg_masks(self) -> None:
        if self._requested or len(self.masked) + len(self.dropped) < self.n:
            return
        surviving = sorted(self.masked.keys())
        if len(surviving) < self.u:
            raise RuntimeError(f"too many dropouts: {len(surviving)} < u={self.u}")
        self._requested = True
        for cid in surviving:
            m = Message(LSAMessage.MSG_TYPE_S2C_REQUEST_AGG_MASK, 0, cid)
            m.add_params("surviving", surviving)
            self.send_message(m)

    def _on_agg_mask(self, msg: Message) -> None:
        if not self._requested:
            return  # straggler from a phase that already reconstructed (u < survivors)
        self.agg_masks[int(msg.get_sender_id())] = np.asarray(msg.get("agg_encoded_mask"))
        surviving = sorted(self.masked.keys())
        if len(self.agg_masks) < min(self.u, len(surviving)):
            return
        d = self.meta["d"]
        agg_mask = aggregate_mask_reconstruction(
            {cid: self.agg_masks[cid] for cid in sorted(self.agg_masks)[: self.u]},
            self.t, self.u, d,
        )
        total = np.zeros(d, dtype=np.int64)
        for v in self.masked.values():
            total = np.mod(total + v, FIELD_PRIME)
        unmasked_sum = np.mod(total - agg_mask, FIELD_PRIME)
        # uniform average over surviving clients (reference LSA behavior)
        mean_params = unflatten_from_finite(unmasked_sum, self.meta["treedef"], self.meta["shapes"], q_bits=Q_BITS)
        import jax

        k = float(len(surviving))
        self.global_params = jax.tree_util.tree_map(lambda x: x / k, mean_params)
        self.masked.clear(); self.dropped.clear(); self.agg_masks.clear(); self._requested = False
        self.eval_history.append(self._evaluate())
        self.round_idx += 1
        if self.round_idx >= self.round_num:
            for cid in range(1, self.n + 1):
                self.send_message(Message(LSAMessage.MSG_TYPE_S2C_FINISH, 0, cid))
            self.finish()
            return
        self._send_round(LSAMessage.MSG_TYPE_S2C_SYNC)

    def _evaluate(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        if self._eval_fn is None:
            self._eval_fn = make_eval_fn(self.module)
        x, y = self.test_global
        xs, ys = jnp.asarray(x), jnp.asarray(y)
        m = jnp.ones((xs.shape[0],), jnp.float32)
        l, c, t = self._eval_fn(self.global_params, xs, ys, m)
        out = {"round": self.round_idx, "test_acc": round(float(c) / max(float(t), 1.0), 4),
               "test_loss": round(float(l) / max(float(t), 1.0), 4)}
        logger.info("lightsecagg eval: %s", out)
        return out


class LightSecAggClientManager(FedMLCommManager):
    def __init__(self, args, dataset, model, rank: int, backend: str = "LOOPBACK", drop: bool = False):
        client_num = int(getattr(args, "client_num_in_total", 1))
        super().__init__(args, rank=rank, size=client_num + 1, backend=backend)
        (_, _, _, _, self.train_num_dict, self.train_dict, _, _) = dataset
        self.args = args
        self.n = client_num
        self.trainer = ModelTrainerCLS(model, args)
        self.client_index = rank - 1
        self.drop = bool(drop)  # simulate dropout after the sub-mask exchange
        self._sent_online = False
        self.local_mask: Optional[np.ndarray] = None
        self.received_rows: Dict[int, np.ndarray] = {}
        self.rng = np.random.default_rng(7000 + rank)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", self._on_ready)
        self.register_message_receive_handler(LSAMessage.MSG_TYPE_S2C_INIT, self._on_round)
        self.register_message_receive_handler(LSAMessage.MSG_TYPE_S2C_SYNC, self._on_round)
        self.register_message_receive_handler(LSAMessage.MSG_TYPE_C2C_ENCODED_MASK, self._on_row)
        self.register_message_receive_handler(LSAMessage.MSG_TYPE_S2C_REQUEST_AGG_MASK, self._on_request)
        self.register_message_receive_handler(LSAMessage.MSG_TYPE_S2C_FINISH, lambda m: self.finish())

    def _on_ready(self, msg: Message) -> None:
        if not self._sent_online:
            self._sent_online = True
            self.send_message(Message(LSAMessage.MSG_TYPE_C2S_STATUS, self.rank, 0))

    def _on_round(self, msg: Message) -> None:
        global_params = msg.get("model_params")
        n, t, u = int(msg.get("lsa_n")), int(msg.get("lsa_t")), int(msg.get("lsa_u"))
        # advance the trainer's per-round RNG stream (one call per round)
        self.trainer.round_idx = int(getattr(self.trainer, "round_idx", -1)) + 1
        self.trainer.set_model_params(global_params)
        train_data = self.train_dict[self.client_index]
        self.trainer.train(train_data, None, self.args)
        z, treedef, shapes = flatten_to_finite(self.trainer.get_model_params(), q_bits=Q_BITS)
        d = z.shape[0]
        self.local_mask = self.rng.integers(0, int(FIELD_PRIME), size=d, dtype=np.int64)
        rows = mask_encoding(d, n, t, u, self.local_mask, self.rng)  # [n, chunk]
        for peer in range(1, n + 1):
            m = Message(LSAMessage.MSG_TYPE_C2C_ENCODED_MASK, self.rank, peer)
            m.add_params("row", rows[peer - 1])
            self.send_message(m)
        if self.drop:
            self.send_message(Message(LSAMessage.MSG_TYPE_C2S_DROP, self.rank, 0))
            return
        masked = np.mod(z + self.local_mask, FIELD_PRIME)
        m = Message(LSAMessage.MSG_TYPE_C2S_MASKED_MODEL, self.rank, 0)
        m.add_params("masked_vector", masked)
        m.add_params("treedef", treedef)
        m.add_params("shapes", shapes)
        m.add_params("d", d)
        self.send_message(m)

    def _on_row(self, msg: Message) -> None:
        self.received_rows[int(msg.get_sender_id())] = np.asarray(msg.get("row"))

    def _on_request(self, msg: Message) -> None:
        surviving = [int(s) for s in msg.get("surviving")]
        agg = compute_aggregate_encoded_mask(self.received_rows, surviving)
        m = Message(LSAMessage.MSG_TYPE_C2S_AGG_ENCODED_MASK, self.rank, 0)
        m.add_params("agg_encoded_mask", agg)
        self.send_message(m)
        self.received_rows.clear()


def run_lightsecagg_topology_in_threads(args, dataset_fn, model_fn, backend: str = "LOOPBACK",
                                        drop_ranks: Optional[List[int]] = None):
    dataset, out_dim = dataset_fn(args)
    model = model_fn(args, out_dim)
    drop_ranks = set(drop_ranks or [])
    server = LightSecAggServerManager(args, dataset, model, backend=backend)
    clients = [
        LightSecAggClientManager(args, dataset, model_fn(args, out_dim), rank=r,
                                 backend=backend, drop=(r in drop_ranks))
        for r in range(1, int(args.client_num_in_total) + 1)
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for c in clients:
        c.finish()
    for t in threads:
        t.join(timeout=30)
    return server.eval_history
