"""Cross-silo message contract.

Same message-type/argument vocabulary as the reference
(``cross_silo/server/message_define.py`` + ``client/message_define.py``) so
protocol traces are comparable side by side.
"""


class MyMessage:
    # server -> client
    MSG_TYPE_S2C_INIT_CONFIG = 1
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
    MSG_TYPE_S2C_CHECK_CLIENT_STATUS = 6
    MSG_TYPE_S2C_FINISH = 7

    # client -> server
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3
    MSG_TYPE_C2S_SEND_STATS_TO_SERVER = 4
    MSG_TYPE_C2S_CLIENT_STATUS = 5

    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"

    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_MODEL_PARAMS_URL = "model_params_url"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_CLIENT_STATUS = "client_status"
    MSG_ARG_KEY_ROUND_INDEX = "round_idx"
    # reliability protocol (beyond-reference, additive): transport-level
    # message id for ack/dedup, and the client's per-incarnation epoch nonce
    # carried in ONLINE status — an epoch change after init marks a mid-run
    # rejoin that the server answers with a current-round model resync
    MSG_ARG_KEY_MSG_ID = "msg_id"
    MSG_ARG_KEY_CLIENT_EPOCH = "client_epoch"

    MSG_ARG_KEY_TRAIN_CORRECT = "train_correct"
    MSG_ARG_KEY_TRAIN_ERROR = "train_error"
    MSG_ARG_KEY_TRAIN_NUM = "train_num_sample"

    CLIENT_STATUS_OFFLINE = "OFFLINE"
    CLIENT_STATUS_IDLE = "IDLE"
    CLIENT_STATUS_ONLINE = "ONLINE"
