from .sa_fedml_api import run_secagg_topology_in_threads  # noqa: F401
