"""SecAgg message vocabulary (reference ``cross_silo/secagg/message_defined.py``)."""


class SAMessage:
    # server -> client
    MSG_TYPE_S2C_INIT_CONFIG = "sa_init"
    MSG_TYPE_S2C_BROADCAST_PUBLIC_KEYS = "sa_pks"
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = "sa_sync"
    MSG_TYPE_S2C_FINISH = "sa_finish"

    # client -> server
    MSG_TYPE_C2S_PUBLIC_KEY = "sa_pk"
    MSG_TYPE_C2S_MASKED_MODEL = "sa_masked_model"
    MSG_TYPE_C2S_CLIENT_STATUS = "sa_status"

    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_MASKED_VECTOR = "masked_vector"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_PUBLIC_KEY = "public_key"
    MSG_ARG_KEY_PK_TABLE = "pk_table"
    MSG_ARG_KEY_CLIENT_STATUS = "client_status"
    MSG_ARG_KEY_ROUND_INDEX = "round_idx"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
