"""Pytree <-> flat-field-vector conversion for secure aggregation.

The MPC plane works on one flat int64 residue vector per client; these
helpers bridge model pytrees to that plane (the reference operates on ordered
torch state_dicts; a flat vector is the same idea, engine-free).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import numpy as np

from ...core.mpc.secagg import transform_finite_to_tensor, transform_tensor_to_finite


def flatten_to_finite(params: Any, q_bits: int = 16) -> Tuple[np.ndarray, Any, list]:
    """-> (field_vector, treedef, [leaf shapes])."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = [np.shape(l) for l in leaves]
    flat = np.concatenate([np.ravel(np.asarray(l, dtype=np.float64)) for l in leaves]) if leaves else np.zeros(0)
    return transform_tensor_to_finite(flat, q_bits=q_bits), treedef, shapes


def unflatten_from_finite(z: np.ndarray, treedef, shapes, q_bits: int = 16, dtype=np.float32) -> Any:
    flat = transform_finite_to_tensor(z, q_bits=q_bits).astype(dtype)
    leaves = []
    off = 0
    for shp in shapes:
        n = int(np.prod(shp)) if shp else 1
        leaves.append(flat[off : off + n].reshape(shp))
        off += n
    return jax.tree_util.tree_unflatten(treedef, leaves)
