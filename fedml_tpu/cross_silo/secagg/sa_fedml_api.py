"""Cross-silo FedAvg with pairwise-mask secure aggregation.

Scenario parity with reference ``cross_silo/secagg/`` (sa_fedml_api.py,
sa_fedml_server_manager.py, sa_fedml_client_manager.py, ~1100 LoC): the server
NEVER sees an individual client update — clients quantize their params into
the prime field, add pairwise masks derived from DH-agreed keys (Bonawitz
et al. cancellation), and the server field-sums the masked vectors; the masks
cancel and the dequantized mean becomes the next global model.

Round protocol:
  S2C INIT (participant table + global model)
  C2S PUBLIC_KEY  -> server collects, S2C BROADCAST_PUBLIC_KEYS
  client: local train -> quantize -> pairwise-mask -> C2S MASKED_MODEL
  server: field-sum, dequantize, weight by samples -> S2C SYNC / FINISH
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from ...core.distributed.comm_manager import FedMLCommManager
from ...core.distributed.communication.message import Message
from ...core.mpc.field import FIELD_PRIME
from ...core.mpc.secagg import mask_model_update, my_key_agreement, my_pk_gen
from ...ml.engine.train import init_variables
from ...ml.trainer.cls_trainer import ModelTrainerCLS
from .flatten import flatten_to_finite, unflatten_from_finite
from .sa_message_define import SAMessage

logger = logging.getLogger(__name__)

Q_BITS = 16  # default; configs override via secagg_quantize_bits


def _check_q_bits(q_bits: int, n_clients: int) -> int:
    """Quantized weights must fit the field's SIGNED range WITH headroom for
    the n-client sum — out-of-range bits would WRAP under the modulus and
    silently corrupt the aggregate rather than erroring.  Decoding is signed
    (transform_finite_to_tensor maps the upper half of the field to negative
    values), so the usable magnitude is (p-1)/2 ~ 2^30, not the full 31
    bits: the bound is 30 minus the sum headroom."""
    import math

    headroom = math.ceil(math.log2(max(int(n_clients), 1) + 1))
    limit = 30 - headroom
    if not 1 <= q_bits <= limit:
        raise ValueError(
            f"secagg_quantize_bits={q_bits} out of range [1, {limit}] for "
            f"{n_clients} clients (31-bit field minus {headroom} sum-headroom bits)"
        )
    return q_bits


class SecAggServerManager(FedMLCommManager):
    def __init__(self, args, dataset, model, backend: str = "LOOPBACK"):
        client_num = int(getattr(args, "client_num_in_total", 1))
        super().__init__(args, rank=0, size=client_num + 1, backend=backend)
        (_, _, _, self.test_global, _, _, _, _) = dataset
        self.module = model
        self.client_num = client_num
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        import jax.numpy as jnp

        sample = jnp.asarray(self.test_global[0][:1])
        self.global_params = init_variables(model, sample, seed=int(getattr(args, "random_seed", 0)))
        self.q_bits = _check_q_bits(
            int(getattr(args, "secagg_quantize_bits", Q_BITS)), client_num
        )
        self.secagg_plane = str(
            getattr(args, "secagg_plane", "host") or "host").lower()
        self.online: Dict[int, bool] = {}
        self.pk_table: Dict[int, int] = {}
        self.masked: Dict[int, np.ndarray] = {}
        self.sample_nums: Dict[int, float] = {}
        self.treedef = None
        self.shapes = None
        self.eval_history: List[Dict[str, Any]] = []
        self._eval_fn = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", self._on_ready)
        self.register_message_receive_handler(SAMessage.MSG_TYPE_C2S_CLIENT_STATUS, self._on_status)
        self.register_message_receive_handler(SAMessage.MSG_TYPE_C2S_PUBLIC_KEY, self._on_pk)
        self.register_message_receive_handler(SAMessage.MSG_TYPE_C2S_MASKED_MODEL, self._on_masked)

    def _on_ready(self, msg: Message) -> None:
        pass  # clients announce themselves

    def _on_status(self, msg: Message) -> None:
        self.online[int(msg.get_sender_id())] = True
        if len(self.online) == self.client_num and self.round_idx == 0 and not self.pk_table:
            self._send_init()

    def _send_init(self) -> None:
        for cid in range(1, self.client_num + 1):
            m = Message(SAMessage.MSG_TYPE_S2C_INIT_CONFIG, 0, cid)
            m.add_params(SAMessage.MSG_ARG_KEY_MODEL_PARAMS, self.global_params)
            m.add_params(SAMessage.MSG_ARG_KEY_CLIENT_INDEX, cid - 1)
            m.add_params(SAMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            self.send_message(m)

    def _on_pk(self, msg: Message) -> None:
        self.pk_table[int(msg.get_sender_id())] = int(msg.get(SAMessage.MSG_ARG_KEY_PUBLIC_KEY))
        if len(self.pk_table) == self.client_num:
            for cid in range(1, self.client_num + 1):
                m = Message(SAMessage.MSG_TYPE_S2C_BROADCAST_PUBLIC_KEYS, 0, cid)
                m.add_params(SAMessage.MSG_ARG_KEY_PK_TABLE, dict(self.pk_table))
                self.send_message(m)

    def _on_masked(self, msg: Message) -> None:
        sender = int(msg.get_sender_id())
        self.masked[sender] = np.asarray(msg.get(SAMessage.MSG_ARG_KEY_MASKED_VECTOR))
        self.sample_nums[sender] = float(msg.get(SAMessage.MSG_ARG_KEY_NUM_SAMPLES))
        if self.treedef is None:
            self.treedef = msg.get("treedef")
            self.shapes = msg.get("shapes")
        if len(self.masked) < self.client_num:
            return
        # field-sum: pairwise masks cancel (server never unmasked an individual)
        if self.secagg_plane == "compiled":
            from ...core.mpc.inmesh import field_sum

            total = field_sum(np.stack(
                [self.masked[s] for s in sorted(self.masked)]))
        else:
            total = np.zeros_like(next(iter(self.masked.values())))
            for v in self.masked.values():  # fedlint: allow[sec-host-fallback] — retained host oracle for the compiled field fold
                total = np.mod(total + v, FIELD_PRIME)
        # clients pre-scale by n_i/N, so the field sum IS the weighted mean
        self.global_params = unflatten_from_finite(total, self.treedef, self.shapes, q_bits=self.q_bits)
        self.masked.clear()
        self.pk_table.clear()
        self.eval_history.append(self._evaluate())
        self.round_idx += 1
        if self.round_idx >= self.round_num:
            for cid in range(1, self.client_num + 1):
                self.send_message(Message(SAMessage.MSG_TYPE_S2C_FINISH, 0, cid))
            self.finish()
            return
        for cid in range(1, self.client_num + 1):
            m = Message(SAMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, cid)
            m.add_params(SAMessage.MSG_ARG_KEY_MODEL_PARAMS, self.global_params)
            m.add_params(SAMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            self.send_message(m)

    def _evaluate(self) -> Dict[str, Any]:
        from ...ml.engine.train import make_eval_fn

        import jax.numpy as jnp

        if self._eval_fn is None:
            self._eval_fn = make_eval_fn(self.module)
        x, y = self.test_global
        xs, ys = jnp.asarray(x), jnp.asarray(y)
        m = jnp.ones((xs.shape[0],), jnp.float32)
        l, c, t = self._eval_fn(self.global_params, xs, ys, m)
        out = {"round": self.round_idx, "test_acc": round(float(c) / max(float(t), 1.0), 4),
               "test_loss": round(float(l) / max(float(t), 1.0), 4)}
        logger.info("secagg eval: %s", out)
        return out


class SecAggClientManager(FedMLCommManager):
    def __init__(self, args, dataset, model, rank: int, backend: str = "LOOPBACK"):
        client_num = int(getattr(args, "client_num_in_total", 1))
        super().__init__(args, rank=rank, size=client_num + 1, backend=backend)
        (_, _, _, _, self.train_num_dict, self.train_dict, _, _) = dataset
        self.args = args
        self.client_num = client_num
        self.trainer = ModelTrainerCLS(model, args)
        self.q_bits = _check_q_bits(
            int(getattr(args, "secagg_quantize_bits", Q_BITS)), client_num
        )
        self.client_index = rank - 1
        self.sk = int(np.random.default_rng(1000 + rank).integers(2, 2**30))
        self.total_samples = float(sum(self.train_num_dict[i] for i in range(client_num)))
        self._sent_online = False
        self._pending_train: Optional[dict] = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", self._on_ready)
        self.register_message_receive_handler(SAMessage.MSG_TYPE_S2C_INIT_CONFIG, self._on_init)
        self.register_message_receive_handler(SAMessage.MSG_TYPE_S2C_BROADCAST_PUBLIC_KEYS, self._on_pks)
        self.register_message_receive_handler(SAMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self._on_sync)
        self.register_message_receive_handler(SAMessage.MSG_TYPE_S2C_FINISH, lambda m: self.finish())

    def _on_ready(self, msg: Message) -> None:
        if not self._sent_online:
            self._sent_online = True
            m = Message(SAMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
            m.add_params(SAMessage.MSG_ARG_KEY_CLIENT_STATUS, "ONLINE")
            self.send_message(m)

    def _on_init(self, msg: Message) -> None:
        self.client_index = int(msg.get(SAMessage.MSG_ARG_KEY_CLIENT_INDEX))
        self._train_and_stash(msg.get(SAMessage.MSG_ARG_KEY_MODEL_PARAMS))
        self._send_pk()

    def _on_sync(self, msg: Message) -> None:
        self._train_and_stash(msg.get(SAMessage.MSG_ARG_KEY_MODEL_PARAMS))
        self._send_pk()

    def _send_pk(self) -> None:
        m = Message(SAMessage.MSG_TYPE_C2S_PUBLIC_KEY, self.rank, 0)
        m.add_params(SAMessage.MSG_ARG_KEY_PUBLIC_KEY, my_pk_gen(self.sk))
        self.send_message(m)

    def _train_and_stash(self, global_params) -> None:
        # advance the trainer's per-round RNG stream (one call per round)
        self.trainer.round_idx = int(getattr(self.trainer, "round_idx", -1)) + 1
        self.trainer.set_model_params(global_params)
        train_data = self.train_dict[self.client_index]
        n = float(self.train_num_dict[self.client_index])
        self.trainer.on_before_local_training(train_data, None, self.args)
        self.trainer.train(train_data, None, self.args)
        self.trainer.on_after_local_training(train_data, None, self.args)
        # pre-scale by n_i / N so the server's field-sum is the weighted mean
        import jax

        w = self.trainer.get_model_params()
        scaled = jax.tree_util.tree_map(lambda x: np.asarray(x, np.float64) * (n / self.total_samples), w)
        z, treedef, shapes = flatten_to_finite(scaled, q_bits=self.q_bits)
        self._pending_train = {"z": z, "treedef": treedef, "shapes": shapes, "n": n}

    def _on_pks(self, msg: Message) -> None:
        pk_table = {int(k): int(v) for k, v in msg.get(SAMessage.MSG_ARG_KEY_PK_TABLE).items()}
        assert self._pending_train is not None
        peer_keys = {
            peer: my_key_agreement(self.sk, pk)
            for peer, pk in pk_table.items() if peer != self.rank
        }
        masked = mask_model_update(self._pending_train["z"], self.rank, peer_keys)
        m = Message(SAMessage.MSG_TYPE_C2S_MASKED_MODEL, self.rank, 0)
        m.add_params(SAMessage.MSG_ARG_KEY_MASKED_VECTOR, masked)
        m.add_params(SAMessage.MSG_ARG_KEY_NUM_SAMPLES, self._pending_train["n"])
        m.add_params("treedef", self._pending_train["treedef"])
        m.add_params("shapes", self._pending_train["shapes"])
        self.send_message(m)


def run_secagg_topology_in_threads(args, dataset_fn, model_fn, backend: str = "LOOPBACK"):
    """Test/demo harness: server + N clients in threads; returns eval history."""
    dataset, out_dim = dataset_fn(args)
    model = model_fn(args, out_dim)
    server = SecAggServerManager(args, dataset, model, backend=backend)
    clients = [
        SecAggClientManager(args, dataset, model_fn(args, out_dim), rank=r, backend=backend)
        for r in range(1, int(args.client_num_in_total) + 1)
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30)
    return server.eval_history
