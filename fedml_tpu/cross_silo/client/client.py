"""Cross-silo client facade (reference ``cross_silo/client/fedml_client.py`` +
``client_initializer.py``)."""

from __future__ import annotations

from .fedml_client_master_manager import ClientMasterManager
from .trainer_dist_adapter import TrainerDistAdapter


class Client:
    def __init__(self, args, device, dataset, model, model_trainer=None):
        self.args = args
        (
            train_data_num,
            test_data_num,
            train_data_global,
            test_data_global,
            train_data_local_num_dict,
            train_data_local_dict,
            test_data_local_dict,
            class_num,
        ) = dataset
        client_rank = int(getattr(args, "rank", 1))
        adapter = TrainerDistAdapter(
            args, device, client_rank, model, train_data_num,
            train_data_local_num_dict, train_data_local_dict, test_data_local_dict,
            model_trainer,
        )
        # multi-process silo: only proc 0 (master) owns the WAN connection;
        # other processes run the slave loop (reference client_initializer.py
        # rank-in-silo dispatch)
        if int(getattr(args, "proc_rank_in_silo", 0) or 0) > 0:
            from .fedml_client_slave_manager import ClientSlaveManager

            self.manager = ClientSlaveManager(args, adapter)
            return
        backend = str(getattr(args, "backend", "LOOPBACK"))
        size = int(getattr(args, "client_num_in_total", 1)) + 1
        self.manager = ClientMasterManager(args, adapter, rank=client_rank, size=size, backend=backend)

    def run(self):
        self.manager.run()
