"""Intra-silo slave process loop.

Parity with reference ``cross_silo/client/fedml_client_slave_manager.py:6-48``
(``ClientSlaveManager``): a slave process joins the silo's host-plane
process group, then loops — await the master's broadcast of
(round_idx, model_params, client_index, finished), train its shard, join
the weighted allreduce — until the master signals FINISH.  The slave never
talks to the FL server; only the silo master holds the WAN connection.
"""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)


class ClientSlaveManager:
    def __init__(self, args, trainer_dist_adapter):
        self.args = args
        self.trainer_dist_adapter = trainer_dist_adapter
        self.finished = False

    def train(self) -> None:
        if not self.trainer_dist_adapter.train_slave_shard():
            self.finish()

    def finish(self) -> None:
        self.trainer_dist_adapter.finish_silo()
        self.finished = True
        logger.info(
            "slave proc %d in silo rank %s finished",
            int(getattr(self.args, "proc_rank_in_silo", 0)),
            getattr(self.args, "rank", "?"),
        )

    def run(self) -> None:
        while not self.finished:
            self.train()
