"""Cross-silo client state machine (silo rank-0 process).

Parity with reference ``cross_silo/client/fedml_client_master_manager.py:17-157``:
ONLINE handshake on connection-ready, init-config consumption, per-round
train→report, FINISH teardown.  The reference's ``sync_process_group``
slave broadcast lives inside the adapter: single-process silos shard the
batch over the in-process device mesh, and with ``n_proc_in_silo > 1`` the
adapter's ``train``/``finish_silo`` sync the slave processes over the
host-plane ProcessGroup (see trainer_dist_adapter.py).
"""

from __future__ import annotations

import logging
import time
import uuid

from ...core import obs
from ...core.distributed.comm_manager import FedMLCommManager
from ...core.distributed.communication.message import Message
from ..message_define import MyMessage

logger = logging.getLogger(__name__)


class ClientMasterManager(FedMLCommManager):
    def __init__(self, args, trainer_dist_adapter, comm=None, rank: int = 0, size: int = 0, backend: str = "LOOPBACK"):
        super().__init__(args, comm, rank, size, backend)
        self.trainer_dist_adapter = trainer_dist_adapter
        self.num_rounds = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        self.rank = int(rank)
        self.has_sent_online_msg = False
        # incarnation epoch: fresh per manager instance, carried in every
        # ONLINE status — the server detects a mid-run crash-and-rejoin by
        # the epoch CHANGE and resyncs this silo with the current round
        self.client_epoch = uuid.uuid4().hex[:8]

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", self.handle_message_connection_ready)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self.handle_message_check_status
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.handle_message_receive_model_from_server
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self.handle_message_finish
        )

    # -- handlers -----------------------------------------------------------
    def handle_message_connection_ready(self, msg: Message) -> None:
        if not self.has_sent_online_msg:
            self.has_sent_online_msg = True
            self.send_client_status(0, MyMessage.CLIENT_STATUS_ONLINE)

    def handle_message_check_status(self, msg: Message) -> None:
        self.send_client_status(0, MyMessage.CLIENT_STATUS_ONLINE)

    def handle_message_init(self, msg: Message) -> None:
        global_model_params = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        client_index = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX))
        self.round_idx = 0
        self._invite_ctx = obs.extract(msg)  # server invite span (or None)
        self._last_global = global_model_params  # delta base for compression
        self._update_client_index(client_index)
        t0 = time.monotonic()
        self.trainer_dist_adapter.set_model_params(global_model_params)
        self._load_s = time.monotonic() - t0
        self.__train()

    def handle_message_receive_model_from_server(self, msg: Message) -> None:
        global_model_params = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        client_index = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX))
        self.round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx + 1))
        self._invite_ctx = obs.extract(msg)
        self._last_global = global_model_params
        self._update_client_index(client_index)
        self._maybe_flush_telemetry()
        t0 = time.monotonic()
        self.trainer_dist_adapter.set_model_params(global_model_params)
        self._load_s = time.monotonic() - t0
        self.__train()

    def _update_client_index(self, client_index: int) -> None:
        """EF-top-k residuals are per-client state: when the server reassigns
        this process to a different simulated client, the previous client's
        dropped-mass residual must not leak into the new client's delta."""
        if int(client_index) != self.trainer_dist_adapter.client_index:
            self._compress_residuals = None
        self.trainer_dist_adapter.update_dataset(client_index)

    def handle_message_finish(self, msg: Message) -> None:
        logger.info("client rank %d: FINISH", self.rank)
        self.trainer_dist_adapter.finish_silo()  # release silo slaves (no-op single-proc)
        self.finish()

    # -- actions ------------------------------------------------------------
    def send_client_status(self, receive_id: int, status: str) -> None:
        m = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, receive_id)
        m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS, status)
        m.add_params(MyMessage.MSG_ARG_KEY_CLIENT_EPOCH, self.client_epoch)
        self.send_message(m)

    def send_model_to_server(self, receive_id: int, weights, local_sample_num) -> None:
        method = str(getattr(self.args, "compression", "") or "").lower()
        if method and method != "none" and getattr(self, "_last_global", None) is not None:
            # communication compression (reference utils/compression.py):
            # top-k / EF-top-k / quantize / qsgd applied to the UPDATE
            # (trained - global) — sparsifying raw weights would zero the
            # model; the server adds the decompressed delta back onto the
            # global params it distributed
            import jax
            import jax.numpy as jnp

            from ...core.compression import compress_update

            delta = jax.tree_util.tree_map(
                lambda w, g: jnp.asarray(w) - jnp.asarray(g), weights, self._last_global
            )
            payload, self._compress_residuals = compress_update(
                delta, method,
                ratio=float(getattr(self.args, "compression_ratio", 0.05)),
                bits=int(getattr(self.args, "quantize_level", 8)),
                key=jax.random.PRNGKey(
                    int(getattr(self.args, "random_seed", 0)) * 1000 + self.round_idx
                ),
                residuals=getattr(self, "_compress_residuals", None),
            )
            payload["is_delta"] = True
            weights = payload
        m = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, receive_id)
        m.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, weights)
        m.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, local_sample_num)
        # round tag: lets a straggler-tolerant server drop uploads that
        # arrive after their round was closed by round_timeout_s
        m.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
        with obs.span("upload", getattr(self, "_invite_ctx", None),
                      round_idx=self.round_idx, node=self.rank) as up:
            # the upload's own context rides the message: the server's
            # journal.append and any retransmit attempts parent under it
            obs.inject(m, up.ctx)
            cap = self._telemetry_capture()
            if cap is not None:
                cap.attach(m)  # retransmits re-carry this same blob
            self.send_message(m)

    def _telemetry_capture(self):
        """This silo's telemetry ring (lazily bound: obs is configured by
        mlops.init, which may run after the manager is constructed)."""
        cap = getattr(self, "_telemetry", None)
        if cap is None:
            cap = obs.make_client_telemetry(self.rank)
            self._telemetry = cap
        return cap

    def _maybe_flush_telemetry(self) -> None:
        """Standalone flush for records that outlived the piggyback window
        (async mode can leave a client idle between uploads)."""
        cap = self._telemetry_capture()
        if cap is None or not cap.flush_due(obs.telemetry_flush_s()):
            return
        m = cap.flush_message(self.rank, 0)
        if m is not None:
            self.send_message(m)

    def _record_train_telemetry(self, dur_s: float, compile_s: float) -> None:
        """Mirror the train interior into the telemetry ring: the server
        grafts these into its round tree (same deterministic span ids as
        the locally emitted spans, so in-process runs dedup cleanly)."""
        cap = self._telemetry_capture()
        if cap is None:
            return
        invite = getattr(self, "_invite_ctx", None)
        train_ctx = cap.record_span(
            "client.train", dur_s, parent=invite, round_idx=self.round_idx,
            client_index=int(self.trainer_dist_adapter.client_index))
        load_s = float(getattr(self, "_load_s", 0.0) or 0.0)
        if load_s > 0:
            cap.record_span("client.train.load", load_s, parent=train_ctx,
                            round_idx=self.round_idx)
        if compile_s > 0:
            cap.record_span("client.train.compile", compile_s,
                            parent=train_ctx, round_idx=self.round_idx)
        cap.record_span("client.train.step",
                        max(dur_s - compile_s, 0.0), parent=train_ctx,
                        round_idx=self.round_idx)
        cap.sample_resources()
        snap = self.comm_stats_snapshot()
        prev = getattr(self, "_tele_comm_prev", {})
        for k, v in snap.items():
            delta = int(v) - int(prev.get(k, 0))
            if delta:
                cap.record_counter(f"comm.{k}", delta)
        self._tele_comm_prev = snap

    def __train(self) -> None:
        logger.info("client rank %d: train round %d (silo idx %d)",
                    self.rank, self.round_idx, self.trainer_dist_adapter.client_index)
        t0 = time.monotonic()
        c0 = obs.compile_seconds_total()
        with obs.span("client.train", getattr(self, "_invite_ctx", None),
                      round_idx=self.round_idx, node=self.rank,
                      annotate=True,
                      client_index=int(self.trainer_dist_adapter.client_index)):
            weights, local_sample_num = self.trainer_dist_adapter.train(self.round_idx)
        self._record_train_telemetry(time.monotonic() - t0,
                                     obs.compile_seconds_total() - c0)
        self.send_model_to_server(0, weights, local_sample_num)
