from .client import Client

__all__ = ["Client"]
