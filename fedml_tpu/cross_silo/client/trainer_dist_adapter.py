"""Bridges the WAN FL loop to intra-silo device parallelism ("Cheetah").

Parity with reference ``cross_silo/client/fedml_trainer_dist_adapter.py:9-93``,
replaced TPU-first: where the reference wraps the model in torch DDP across
torchrun-spawned slave processes (``model_ddp``, ``process_group_manager.py``),
here the silo is one process and the local batch axis is sharded over the
silo's jax devices via a ``Mesh`` — XLA compiles the same gradient all-reduce
DDP would issue through NCCL, but over ICI and fused into the step.  The
"slave manager"/"process group" machinery therefore has no equivalent; its
job is done by the compiler.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import jax

from ...ml.trainer.cls_trainer import ModelTrainerCLS

logger = logging.getLogger(__name__)


class TrainerDistAdapter:
    def __init__(self, args, device, client_rank: int, model, train_data_num,
                 train_data_local_num_dict, train_data_local_dict, test_data_local_dict,
                 model_trainer: Optional[Any] = None):
        self.args = args
        self.device = device
        self.client_rank = int(client_rank)
        self.client_index = self.client_rank - 1
        self.train_data_local_dict = train_data_local_dict
        self.train_data_local_num_dict = train_data_local_num_dict
        self.test_data_local_dict = test_data_local_dict
        if model_trainer is None:
            model_trainer = ModelTrainerCLS(model, args)
        self.trainer = model_trainer
        self.trainer.set_id(self.client_index)

        # hierarchical scenario: local training runs through the mesh-sharded
        # DistributedTrainer (batch over dp; grad all-reduce compiled to ICI)
        scenario = str(getattr(args, "scenario", "horizontal"))
        n_dev = len(jax.devices())
        self.dist_trainer = None
        if scenario == "hierarchical" and n_dev > 1:
            from ...distributed import DistributedTrainer
            from ...parallel.mesh import create_train_mesh

            self.dist_trainer = DistributedTrainer(
                model, args, mesh=create_train_mesh(dp=n_dev)
            )
            logger.info("silo rank %d: intra-silo dp over %d devices (mesh-sharded batch)",
                        client_rank, n_dev)

    def get_model_params(self):
        return self.trainer.get_model_params()

    def set_model_params(self, model_params) -> None:
        self.trainer.set_model_params(model_params)

    def update_dataset(self, client_index: int) -> None:
        self.client_index = int(client_index)
        self.trainer.set_id(self.client_index)

    def train(self, round_idx: int):
        """One local-training pass; returns (params, local_sample_num)."""
        self.trainer.round_idx = int(round_idx)  # advance the per-round RNG stream
        train_data = self.train_data_local_dict[self.client_index]
        n = self.train_data_local_num_dict[self.client_index]
        if self.dist_trainer is not None:
            # hierarchical: global model in -> mesh-dp local epochs -> host out
            self.dist_trainer.init_from(self.trainer.get_model_params())
            x, y = train_data
            self.dist_trainer.fit(
                x, y, epochs=int(getattr(self.args, "epochs", 1)),
                seed=int(round_idx) * 1000 + self.client_rank,
            )
            params = self.dist_trainer.get_variables()
            self.trainer.set_model_params(params)
            return params, n
        self.trainer.on_before_local_training(train_data, self.device, self.args)
        self.trainer.train(train_data, self.device, self.args)
        self.trainer.on_after_local_training(train_data, self.device, self.args)
        return self.trainer.get_model_params(), n
