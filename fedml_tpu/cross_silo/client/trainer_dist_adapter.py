"""Bridges the WAN FL loop to intra-silo device parallelism ("Cheetah").

Parity with reference ``cross_silo/client/fedml_trainer_dist_adapter.py:9-93``:
two nested levels of intra-silo parallelism, both TPU-first:

* WITHIN a process, the local batch axis is sharded over the process's jax
  devices via a ``Mesh`` — XLA compiles the gradient all-reduce torch DDP
  would issue through NCCL, but over ICI and fused into the step.
* ACROSS silo processes/hosts (``n_proc_in_silo > 1`` — the reference's
  torchrun-spawned slave processes, ``process_group_manager.py`` +
  ``fedml_client_slave_manager.py``), a host-plane ``ProcessGroup``
  (core/distributed/collective.py) synchronizes the round: the master
  broadcasts (round, params, client_index), every process trains a
  disjoint stride-shard of the client's local data, and a weighted
  allreduce-mean merges the results — host-level data parallelism whose
  heavy per-step traffic still never leaves each process's compiled step.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import jax

from ...ml.trainer.cls_trainer import ModelTrainerCLS

logger = logging.getLogger(__name__)




class TrainerDistAdapter:
    def __init__(self, args, device, client_rank: int, model, train_data_num,
                 train_data_local_num_dict, train_data_local_dict, test_data_local_dict,
                 model_trainer: Optional[Any] = None):
        self.args = args
        self.device = device
        self.client_rank = int(client_rank)
        self.client_index = self.client_rank - 1
        self.train_data_local_dict = train_data_local_dict
        self.train_data_local_num_dict = train_data_local_num_dict
        self.test_data_local_dict = test_data_local_dict
        if model_trainer is None:
            model_trainer = ModelTrainerCLS(model, args)
        self.trainer = model_trainer
        self.trainer.set_id(self.client_index)

        # hierarchical scenario: local training runs through the mesh-sharded
        # DistributedTrainer (batch over dp; grad all-reduce compiled to ICI)
        scenario = str(getattr(args, "scenario", "horizontal"))
        n_dev = len(jax.devices())
        self.dist_trainer = None
        if scenario == "hierarchical" and n_dev > 1:
            from ...distributed import DistributedTrainer
            from ...parallel.mesh import create_train_mesh

            self.dist_trainer = DistributedTrainer(
                model, args, mesh=create_train_mesh(dp=n_dev)
            )
            logger.info("silo rank %d: intra-silo dp over %d devices (mesh-sharded batch)",
                        client_rank, n_dev)

        # multi-process silo (reference torchrun slaves): host-plane pg
        self.n_proc = int(getattr(args, "n_proc_in_silo", 1) or 1)
        self.proc_rank = int(getattr(args, "proc_rank_in_silo", 0) or 0)
        if self.proc_rank >= self.n_proc:
            raise ValueError(
                f"proc_rank_in_silo={self.proc_rank} requires "
                f"n_proc_in_silo > {self.proc_rank} (got {self.n_proc})"
            )
        self.pg = None
        if self.n_proc > 1:
            from ...core.distributed.collective import ProcessGroup

            addr = (str(getattr(args, "pg_master_address", "127.0.0.1")),
                    int(getattr(args, "pg_master_port", 29500)))
            # per-run shared secret: the hub rejects joins without it (frames
            # are pickled, so only authenticated peers may reach the port)
            token = str(getattr(args, "pg_token", None)
                        or f"{getattr(args, 'run_id', '0')}-pg")
            self.pg = ProcessGroup(self.proc_rank, self.n_proc, addr=addr,
                                   token=token)
            logger.info("silo rank %d: host pg up (proc %d/%d @ %s:%d)",
                        client_rank, self.proc_rank, self.n_proc, *addr)

    def get_model_params(self):
        return self.trainer.get_model_params()

    def set_model_params(self, model_params) -> None:
        self.trainer.set_model_params(model_params)

    def update_dataset(self, client_index: int) -> None:
        self.client_index = int(client_index)
        self.trainer.set_id(self.client_index)

    def train(self, round_idx: int):
        """One local-training pass; returns (params, local_sample_num).
        With a multi-process silo, the MASTER calls this: it syncs the
        slaves, trains its own shard, and merges via weighted allreduce."""
        if self.pg is not None:
            assert self.proc_rank == 0, "slaves train via train_slave_shard"
            self.pg.broadcast([int(round_idx), self.trainer.get_model_params(),
                               int(self.client_index), False])
            return self._train_silo_shard(round_idx)
        return self._train_local(round_idx)

    def train_slave_shard(self):
        """SLAVE side of one silo round: await the master's sync, train this
        process's shard, join the allreduce.  Returns False when the master
        signalled FINISH (reference ClientSlaveManager.await_sync_process_group)."""
        round_idx, params, client_index, finished = self.pg.broadcast(None)
        if finished:
            return False
        self.update_dataset(int(client_index))
        self.set_model_params(params)
        self._train_silo_shard(int(round_idx))
        return True

    def finish_silo(self) -> None:
        """Master: release the slaves and tear down the host pg."""
        if self.pg is not None and self.proc_rank == 0:
            self.pg.broadcast([0, None, 0, True])
        if self.pg is not None:
            self.pg.close()
            self.pg = None

    def _train_silo_shard(self, round_idx: int):
        """Train this process's stride-shard, then weighted allreduce-mean."""
        x, y = self.train_data_local_dict[self.client_index]
        xs, ys = x[self.proc_rank :: self.n_proc], y[self.proc_rank :: self.n_proc]
        shard_n = len(ys)
        full_n = self.train_data_local_num_dict[self.client_index]
        if shard_n > 0:
            params, _ = self._train_local(round_idx, train_data=(xs, ys), n=shard_n)
        else:
            # sample-less shard (tiny client, many procs): contribute weight 0
            # so the stale pre-round params don't bias the merge
            params = self.trainer.get_model_params()
        merged = self.pg.allreduce_mean(params, weight=float(shard_n))
        self.trainer.set_model_params(merged)
        return merged, full_n

    def _train_local(self, round_idx: int, train_data=None, n=None):
        self.trainer.round_idx = int(round_idx)  # advance the per-round RNG stream
        if train_data is None:
            train_data = self.train_data_local_dict[self.client_index]
        if n is None:
            n = self.train_data_local_num_dict[self.client_index]
        if self.dist_trainer is not None:
            # hierarchical: global model in -> mesh-dp local epochs -> host out
            self.dist_trainer.init_from(self.trainer.get_model_params())
            x, y = train_data
            self.dist_trainer.fit(
                x, y, epochs=int(getattr(self.args, "epochs", 1)),
                seed=int(round_idx) * 1000 + self.client_rank,
            )
            params = self.dist_trainer.get_variables()
            self.trainer.set_model_params(params)
            return params, n
        self.trainer.on_before_local_training(train_data, self.device, self.args)
        self.trainer.train(train_data, self.device, self.args)
        self.trainer.on_after_local_training(train_data, self.device, self.args)
        return self.trainer.get_model_params(), n
