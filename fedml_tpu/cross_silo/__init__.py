"""Cross-silo FL ("Octopus"): host message plane + round state machines.

Parity with reference ``python/fedml/cross_silo/`` (SURVEY.md §2.6, §3.4):
the server waits for every silo's ONLINE handshake, pushes init config, then
runs the collect→aggregate→test→sample→sync round loop; each client silo
trains locally and reports.  Transport is any registered CommManager backend
(LOOPBACK for tests, GRPC for DCN, MQTT_S3 for broker+blob deployments).

TPU-native deviation: the reference's intra-silo acceleration is torch DDP
via torchrun-spawned slave processes (``fedml_client_slave_manager.py``,
``process_group_manager.py``).  Here a silo is ONE process whose local batch
is sharded over the silo's jax devices with a `Mesh` — no slave processes, no
process groups; XLA inserts the gradient all-reduce (ICI) that DDP would do
with NCCL (see client/trainer_dist_adapter.py).
"""

from .client.client import Client
from .server.server import Server

__all__ = ["Client", "Server"]
