"""Edge run supervisor.

Parity with reference ``cli/edge_deployment/client_runner.py`` (901 LoC) +
``client_daemon.py``: unpack a built package into a run directory, spawn the
training entry as a subprocess, supervise it (restart-on-crash up to a retry
budget), and report the run-status FSM transitions — to a JSONL status file
(and through ``core.mlops`` when a broker is configured).  The server-side
runner (reference ``server_runner.py``) shares this implementation: only the
status vocabulary differs.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ...core.mlops.mlops_status import ClientStatus, ServerStatus
from ..build import unpack_package

logger = logging.getLogger(__name__)


class FedMLRunnerSupervisor:
    """Spawn + supervise one run of a deployed package."""

    def __init__(
        self,
        package_path: str,
        run_dir: str,
        run_id: str = "0",
        role: str = "client",
        max_restarts: int = 2,
        extra_args: Optional[List[str]] = None,
        python_exe: Optional[str] = None,
    ):
        self.package_path = package_path
        self.run_dir = os.path.abspath(run_dir)
        self.run_id = str(run_id)
        self.role = role
        self.max_restarts = int(max_restarts)
        self.extra_args = list(extra_args or [])
        self.python_exe = python_exe or sys.executable
        # role -> status vocabulary, resolved once (client vs server FSM)
        if role == "client":
            self._init_status = ClientStatus.INITIALIZING
            self._running_status = ClientStatus.TRAINING
        else:
            self._init_status = ServerStatus.STARTING
            self._running_status = ServerStatus.RUNNING
        self.status_path = os.path.join(self.run_dir, "status.jsonl")
        self.status = "IDLE"
        self.restarts = 0
        self._proc: Optional[subprocess.Popen] = None
        self._stop = threading.Event()

    # -- status --------------------------------------------------------------
    def _report(self, status: str) -> None:
        self.status = status
        rec = {"run_id": self.run_id, "role": self.role, "status": status, "time": time.time()}
        os.makedirs(self.run_dir, exist_ok=True)
        with open(self.status_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        logger.info("run %s: %s", self.run_id, status)

    # -- lifecycle -----------------------------------------------------------
    def prepare(self) -> Dict[str, Any]:
        self._report(self._init_status)
        meta = unpack_package(self.package_path, self.run_dir)
        return meta

    def _spawn(self, meta: Dict[str, Any]) -> subprocess.Popen:
        entry = os.path.join(self.run_dir, "src", meta["entry"])
        config = os.path.join(self.run_dir, meta["config"])
        cmd = [self.python_exe, entry, "--cf", config, "--run_id", self.run_id,
               "--role", self.role] + self.extra_args
        log_path = os.path.join(self.run_dir, "run.log")
        # close the parent's handle right after the child inherits its dup —
        # a restart loop must not leak one fd per spawn
        with open(log_path, "ab") as logf:
            return subprocess.Popen(cmd, cwd=os.path.join(self.run_dir, "src"),
                                    stdout=logf, stderr=subprocess.STDOUT)

    def run(self) -> int:
        """Blocking supervise loop; returns the final exit code."""
        meta = self.prepare()
        while not self._stop.is_set():
            # owned-by: run — the supervise loop is the only writer; other
            # threads read it to signal/terminate the child, racing only
            # against a handle that stays valid after process exit
            self._proc = self._spawn(meta)  # owned-by: run
            self._report(self._running_status)
            rc = self._proc.wait()
            if self._stop.is_set():
                self._report("KILLED")
                return rc
            if rc == 0:
                self._report("FINISHED")
                return 0
            self.restarts += 1
            if self.restarts > self.max_restarts:
                self._report("FAILED")
                return rc
            logger.warning("run %s crashed (rc=%s); restart %d/%d",
                           self.run_id, rc, self.restarts, self.max_restarts)
        self._report("KILLED")
        return -1

    def run_async(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True, name=f"runner-{self.run_id}")
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._proc.kill()

    # -- introspection (``fedml_tpu status``) --------------------------------
    @staticmethod
    def read_status(run_dir: str) -> List[Dict[str, Any]]:
        path = os.path.join(run_dir, "status.jsonl")
        if not os.path.exists(path):
            return []
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]
