"""Edge deployment daemon — the long-lived login process.

Parity with reference ``cli/edge_deployment/client_daemon.py`` +
``server_runner.py`` (the ~2k-LoC platform daemons): ``fedml login``
starts one of these per device; it then
* listens for run-dispatch requests — from a local dispatch directory
  (drop ``run_<id>.json``; the zero-egress stand-in for the hosted MLOps
  request channel) and, when a broker address is configured, from the
  in-repo TCP broker topic ``mlops/deploy/<role>/<account>`` (the same
  channel the reference's MQTT daemon subscribes to),
* spawns a supervised runner per request (``FedMLRunnerSupervisor``:
  unpack package, run entry, restart-on-crash budget),
* heart-beats its pid + per-run status FSM into ``daemon.json`` so
  ``fedml status`` can introspect it from another process,
* publishes run status transitions back to the broker
  (``mlops/status/<role>/<run_id>``) when connected — the reporting leg
  of the reference's MLOps glue,
* stops when ``daemon.stop`` appears (``fedml logout``) or on SIGTERM.

Request schema (file or broker payload)::

    {"run_id": "42", "package": "/path/to/pkg.zip",
     "extra_args": [...], "max_restarts": 2}
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from typing import Any, Dict, Optional

from .client_runner import FedMLRunnerSupervisor

logger = logging.getLogger(__name__)


class FedMLDaemon:
    def __init__(
        self,
        home_dir: str,
        role: str = "client",
        account_id: str = "0",
        broker: Optional[str] = None,  # "host:port" of a LocalBroker
        poll_interval: float = 0.5,
    ):
        self.home = os.path.abspath(home_dir)
        self.role = role
        self.account_id = str(account_id)
        self.poll_interval = float(poll_interval)
        self.dispatch_dir = os.path.join(self.home, "dispatch")
        self.runs_dir = os.path.join(self.home, "runs")
        self.state_path = os.path.join(self.home, "daemon.json")
        self.stop_path = os.path.join(self.home, "daemon.stop")
        os.makedirs(self.dispatch_dir, exist_ok=True)
        os.makedirs(self.runs_dir, exist_ok=True)
        self._stop = threading.Event()
        self._runs: Dict[str, FedMLRunnerSupervisor] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._client = None
        if broker:
            host, _, port = broker.partition(":")
            self._connect_broker(host, int(port or 1883))

    # -- broker channel ------------------------------------------------------
    def _connect_broker(self, host: str, port: int) -> None:
        from ...core.distributed.communication.mqtt_s3.adapters import (
            create_broker_client,
        )

        def on_message(topic: str, payload) -> None:
            try:
                self._accept_request(dict(payload))
            except Exception:
                logger.exception("bad dispatch payload on %s", topic)

        # owned-by: main — connected during startup, before the serve loop
        # spawns; the loop and status publishers only read it
        self._client = create_broker_client(  # owned-by: main
            host, port, on_message,
            client_id=f"fedml_daemon_{self.role}_{self.account_id}",
        )
        self._client.subscribe(f"mlops/deploy/{self.role}/{self.account_id}")

    def _publish_status(self, run_id: str, status: str) -> None:
        if self._client is not None:
            self._client.publish(
                f"mlops/status/{self.role}/{run_id}",
                {"run_id": run_id, "role": self.role, "status": status,
                 "account": self.account_id, "time": time.time()},
            )

    # -- request handling ----------------------------------------------------
    def _accept_request(self, req: Dict[str, Any]) -> None:
        run_id = str(req["run_id"])
        if run_id in self._runs:
            logger.warning("run %s already dispatched; ignoring", run_id)
            return
        sup = FedMLRunnerSupervisor(
            package_path=req["package"],
            run_dir=os.path.join(self.runs_dir, run_id),
            run_id=run_id,
            role=self.role,
            max_restarts=int(req.get("max_restarts", 2)),
            extra_args=list(req.get("extra_args", [])),
        )
        # status hook: mirror every FSM transition to the broker
        orig_report = sup._report

        def report(status: str) -> None:
            orig_report(status)
            self._publish_status(run_id, status)

        sup._report = report  # type: ignore[method-assign]
        self._runs[run_id] = sup
        self._threads[run_id] = sup.run_async()
        logger.info("dispatched run %s (package=%s)", run_id, req["package"])

    def _recover_orphan_claims(self) -> None:
        """Un-claim ``.claimed.<pid>`` files whose daemon died between claim
        and accept (crash window), so the request is not orphaned forever."""
        for fn in os.listdir(self.dispatch_dir):
            base, _, pid = fn.rpartition(".claimed.")
            if not base or not pid.isdigit():
                continue
            try:
                os.kill(int(pid), 0)
                continue  # claimer is alive (possibly mid-accept)
            except PermissionError:
                continue  # alive under another user: NOT orphaned
            except (ProcessLookupError, ValueError):
                pass
            try:
                os.replace(os.path.join(self.dispatch_dir, fn),
                           os.path.join(self.dispatch_dir, base))
                logger.warning("recovered orphaned dispatch claim %s", fn)
            except OSError:
                pass

    def _scan_dispatch_dir(self) -> None:
        for fn in sorted(os.listdir(self.dispatch_dir)):
            if not fn.endswith(".json"):
                continue
            path = os.path.join(self.dispatch_dir, fn)
            # only claim files quiet for a beat: a non-atomic writer (scp,
            # editor save — the CLI itself writes tmp+rename) must not have
            # its half-written file claimed and rejected
            try:
                # quiet-period check; abs() so a future mtime (writer clock
                # ahead, NFS skew) claims immediately instead of never
                if abs(time.time() - os.stat(path).st_mtime) < self.poll_interval:
                    continue  # still (possibly) being written: next tick
            except OSError:
                continue
            # claim FIRST (atomic rename to a per-pid name): two daemons
            # sharing a home race on os.replace, and exactly one wins
            claimed = f"{path}.claimed.{os.getpid()}"
            try:
                os.replace(path, claimed)
            except FileNotFoundError:
                continue  # another daemon claimed it first
            try:
                with open(claimed) as f:
                    req = json.load(f)
                self._accept_request(req)
            except Exception:
                # mirror the broker on_message handler: a malformed request
                # (bad JSON, missing run_id/package, unreadable package) must
                # not take the daemon down
                logger.exception("rejecting dispatch file %s", fn)
                try:
                    os.replace(claimed, path + ".rejected")
                except OSError:
                    pass
            else:
                try:
                    os.replace(claimed, path + ".accepted")
                except OSError:
                    pass

    # -- heartbeat / introspection -------------------------------------------
    def _heartbeat(self) -> None:
        state = {
            "pid": os.getpid(),
            "role": self.role,
            "account_id": self.account_id,
            "time": time.time(),
            "runs": {rid: sup.status for rid, sup in self._runs.items()},
        }
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.state_path)

    @staticmethod
    def read_state(home_dir: str) -> Optional[Dict[str, Any]]:
        path = os.path.join(os.path.abspath(home_dir), "daemon.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    @staticmethod
    def request_stop(home_dir: str) -> None:
        with open(os.path.join(os.path.abspath(home_dir), "daemon.stop"), "w") as f:
            f.write(str(time.time()))

    # -- main loop -----------------------------------------------------------
    def serve(self) -> None:
        """Blocking daemon loop (the process `fedml login` leaves behind)."""
        if threading.current_thread() is threading.main_thread():
            signal.signal(signal.SIGTERM, lambda *_: self._stop.set())
        logger.info("daemon up: role=%s account=%s home=%s",
                    self.role, self.account_id, self.home)
        self._recover_orphan_claims()
        last_recover = time.time()
        try:
            while not self._stop.is_set():
                if os.path.exists(self.stop_path):
                    break
                self._scan_dispatch_dir()
                if time.time() - last_recover > 30.0:
                    # periodic: a PEER daemon sharing this home may have
                    # crashed mid-claim since we started
                    self._recover_orphan_claims()
                    last_recover = time.time()
                self._heartbeat()
                self._stop.wait(self.poll_interval)
        finally:
            self.shutdown()

    def serve_async(self) -> threading.Thread:
        t = threading.Thread(target=self.serve, daemon=True, name="fedml-daemon")
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()

    def shutdown(self) -> None:
        for sup in self._runs.values():
            sup.stop()
        for t in self._threads.values():
            t.join(timeout=10)
        self._heartbeat()
        if self._client is not None:
            self._client.disconnect()
        try:
            os.remove(self.stop_path)
        except FileNotFoundError:
            pass
        logger.info("daemon down")


def main(argv=None) -> int:
    """``python -m fedml_tpu.cli.edge_deployment.daemon`` entry."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--home", required=True)
    p.add_argument("--role", default="client", choices=["client", "server"])
    p.add_argument("--account-id", default="0")
    p.add_argument("--broker", default=None)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    FedMLDaemon(args.home, role=args.role, account_id=args.account_id,
                broker=args.broker).serve()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
