"""CLI + edge deployment (reference ``python/fedml/cli/``: the ``fedml``
click app, build packaging, client/server edge daemons, env collector)."""
