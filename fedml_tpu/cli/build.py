"""``fedml_tpu build`` — package user code for deployment.

Parity with reference ``cli/cli.py:315-350`` (``fedml build``): zip the user's
source directory + entry point + config YAML into a deployable package whose
layout the edge runner (``edge_deployment/client_runner.py``) understands:

    package.zip
    ├── fedml_package.json   (entry, config, built_at, type)
    ├── src/...              (the user source tree)
    └── config/fedml_config.yaml
"""

from __future__ import annotations

import json
import os
import time
import zipfile
from typing import Optional

PACKAGE_META = "fedml_package.json"


def build_package(
    source_dir: str,
    entry_point: str,
    config_path: str,
    dest_path: str,
    package_type: str = "client",
    ignore: Optional[list] = None,
) -> str:
    """Zip source + config into ``dest_path``; returns the package path."""
    source_dir = os.path.abspath(source_dir)
    if not os.path.isdir(source_dir):
        raise FileNotFoundError(f"source dir not found: {source_dir}")
    entry_abs = os.path.join(source_dir, entry_point)
    if not os.path.isfile(entry_abs):
        raise FileNotFoundError(f"entry point not found: {entry_abs}")
    if not os.path.isfile(config_path):
        raise FileNotFoundError(f"config not found: {config_path}")
    ignore = set(ignore or []) | {"__pycache__", ".git", ".pytest_cache"}

    meta = {
        "entry": entry_point,
        "config": "config/fedml_config.yaml",
        "type": package_type,
        "built_at": time.time(),
    }
    os.makedirs(os.path.dirname(os.path.abspath(dest_path)) or ".", exist_ok=True)
    dest_abs = os.path.abspath(dest_path)
    with zipfile.ZipFile(dest_path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(PACKAGE_META, json.dumps(meta, indent=2))
        for root, dirs, files in os.walk(source_dir):
            dirs[:] = [d for d in dirs if d not in ignore]
            for name in files:
                if name.endswith((".pyc", ".so")):
                    continue
                full = os.path.join(root, name)
                # the archive being written may live inside source_dir
                # (default dest_folder is ".") — never zip it into itself
                if os.path.abspath(full) == dest_abs:
                    continue
                rel = os.path.relpath(full, source_dir)
                z.write(full, os.path.join("src", rel))
        z.write(config_path, "config/fedml_config.yaml")
    return dest_path


def read_package_meta(package_path: str) -> dict:
    with zipfile.ZipFile(package_path) as z:
        return json.loads(z.read(PACKAGE_META))


def unpack_package(package_path: str, dest_dir: str) -> dict:
    """Extract a package; returns its metadata."""
    with zipfile.ZipFile(package_path) as z:
        for info in z.infolist():
            # zip-slip guard: refuse entries escaping dest_dir
            target = os.path.realpath(os.path.join(dest_dir, info.filename))
            if not target.startswith(os.path.realpath(dest_dir) + os.sep) and target != os.path.realpath(dest_dir):
                raise ValueError(f"unsafe path in package: {info.filename}")
        z.extractall(dest_dir)
        return json.loads(z.read(PACKAGE_META))
