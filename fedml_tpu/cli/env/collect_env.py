"""Environment collector (reference ``cli/env/collect_env.py``): print the
versions + accelerator inventory a bug report needs."""

from __future__ import annotations

import platform
import sys
from typing import Any, Dict


def collect_env(verbose: bool = False) -> Dict[str, Any]:
    info: Dict[str, Any] = {
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "machine": platform.machine(),
    }
    try:
        import fedml_tpu

        info["fedml_tpu"] = fedml_tpu.__version__
    except Exception:  # pragma: no cover
        info["fedml_tpu"] = "unknown"
    for mod in ("jax", "flax", "optax", "numpy"):
        try:
            info[mod] = __import__(mod).__version__
        except Exception:
            info[mod] = "not installed"
    if verbose:
        # device probing initializes the backend — only on request
        try:
            import jax

            info["devices"] = [str(d) for d in jax.devices()]
            info["default_backend"] = jax.default_backend()
        except Exception as e:
            info["devices"] = f"unavailable ({e})"
    return info


def print_env(verbose: bool = False) -> None:
    for k, v in collect_env(verbose).items():
        print(f"{k}: {v}")
