"""The ``fedml_tpu`` command (reference ``cli/cli.py:28-577``, the ``fedml``
click app).  argparse-based; run as ``python -m fedml_tpu.cli <cmd>``.

Commands: version, env, login, logout, build, run, status, logs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

ACCOUNT_DIR = os.path.expanduser("~/.fedml_tpu")
ACCOUNT_FILE = os.path.join(ACCOUNT_DIR, "account.json")


def cmd_version(_args) -> int:
    import fedml_tpu

    print(f"fedml_tpu version {fedml_tpu.__version__}")
    return 0


def cmd_env(args) -> int:
    from .env.collect_env import print_env

    print_env(verbose=args.verbose)
    return 0


def cmd_login(args) -> int:
    """Bind an account id (reference ``fedml login <account_id>``; the MLOps
    platform handshake is represented by the local binding file)."""
    os.makedirs(ACCOUNT_DIR, exist_ok=True)
    with open(ACCOUNT_FILE, "w") as f:
        json.dump({"account_id": args.account_id, "role": args.role}, f)
    print(f"logged in as account {args.account_id} ({args.role})")
    return 0


def cmd_logout(_args) -> int:
    try:
        os.remove(ACCOUNT_FILE)
    except FileNotFoundError:
        pass
    print("logged out")
    return 0


def cmd_build(args) -> int:
    from .build import build_package

    dest = args.dest_package or os.path.join(
        args.dest_folder or ".", f"fedml_{args.type}_package.zip"
    )
    path = build_package(
        source_dir=args.source_folder,
        entry_point=args.entry_point,
        config_path=args.config_file,
        dest_path=dest,
        package_type=args.type,
    )
    print(f"built {args.type} package: {path}")
    return 0


def cmd_run(args) -> int:
    """Run a deployed package under the supervisor (reference edge daemon)."""
    from .edge_deployment.client_runner import FedMLRunnerSupervisor

    sup = FedMLRunnerSupervisor(
        package_path=args.package,
        run_dir=args.run_dir,
        run_id=args.run_id,
        role=args.role,
        max_restarts=args.max_restarts,
        extra_args=args.extra or [],
    )
    return sup.run()


def cmd_status(args) -> int:
    from .edge_deployment.client_runner import FedMLRunnerSupervisor

    records = FedMLRunnerSupervisor.read_status(args.run_dir)
    if not records:
        print("no status recorded")
        return 1
    for rec in records:
        print(f"{rec['time']:.0f} run={rec['run_id']} role={rec['role']} {rec['status']}")
    return 0


def cmd_logs(args) -> int:
    path = os.path.join(args.run_dir, "run.log")
    if not os.path.exists(path):
        print("no logs")
        return 1
    with open(path, errors="replace") as f:
        lines = f.readlines()
    for line in lines[-args.lines:]:
        sys.stdout.write(line)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="fedml_tpu", description="fedml_tpu CLI")
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("version").set_defaults(fn=cmd_version)

    pe = sub.add_parser("env")
    pe.add_argument("-v", "--verbose", action="store_true", help="probe accelerators")
    pe.set_defaults(fn=cmd_env)

    pl = sub.add_parser("login")
    pl.add_argument("account_id")
    pl.add_argument("--role", default="client", choices=["client", "server"])
    pl.set_defaults(fn=cmd_login)

    sub.add_parser("logout").set_defaults(fn=cmd_logout)

    pb = sub.add_parser("build")
    pb.add_argument("--type", "-t", default="client", choices=["client", "server"])
    pb.add_argument("--source_folder", "-sf", required=True)
    pb.add_argument("--entry_point", "-ep", required=True)
    pb.add_argument("--config_file", "-cf", required=True)
    pb.add_argument("--dest_folder", "-df", default=".")
    pb.add_argument("--dest_package", default=None)
    pb.set_defaults(fn=cmd_build)

    pr = sub.add_parser("run")
    pr.add_argument("--package", "-p", required=True)
    pr.add_argument("--run_dir", "-d", required=True)
    pr.add_argument("--run_id", default="0")
    pr.add_argument("--role", default="client", choices=["client", "server"])
    pr.add_argument("--max_restarts", type=int, default=2)
    pr.add_argument("extra", nargs="*", help="extra args passed to the entry")
    pr.set_defaults(fn=cmd_run)

    ps = sub.add_parser("status")
    ps.add_argument("--run_dir", "-d", required=True)
    ps.set_defaults(fn=cmd_status)

    pg = sub.add_parser("logs")
    pg.add_argument("--run_dir", "-d", required=True)
    pg.add_argument("--lines", "-n", type=int, default=100)
    pg.set_defaults(fn=cmd_logs)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
