"""The ``fedml_tpu`` command (reference ``cli/cli.py:28-577``, the ``fedml``
click app).  argparse-based; run as ``python -m fedml_tpu.cli <cmd>``.

Commands: version, env, login, logout, build, run, status, logs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

ACCOUNT_DIR = os.path.expanduser("~/.fedml_tpu")
ACCOUNT_FILE = os.path.join(ACCOUNT_DIR, "account.json")


def cmd_version(_args) -> int:
    import fedml_tpu

    print(f"fedml_tpu version {fedml_tpu.__version__}")
    return 0


def cmd_env(args) -> int:
    from .env.collect_env import print_env

    print_env(verbose=args.verbose)
    return 0


def cmd_login(args) -> int:
    """Bind an account id and start the edge daemon (reference ``fedml
    login <account_id>`` boots ``client_daemon.py``; ``--no-daemon`` keeps
    just the local binding file)."""
    os.makedirs(ACCOUNT_DIR, exist_ok=True)
    record = {"account_id": args.account_id, "role": args.role}
    if not args.no_daemon:
        import subprocess

        from .edge_deployment.daemon import FedMLDaemon

        home = args.daemon_home or os.path.join(ACCOUNT_DIR, f"daemon_{args.role}")
        os.makedirs(home, exist_ok=True)
        state = FedMLDaemon.read_state(home)
        if state is not None and __import__("time").time() - state["time"] < 10:
            try:
                os.kill(int(state["pid"]), 0)
                print(f"daemon already running (pid {state['pid']}, home {home}); "
                      "logout first to restart it")
                return 1
            except (OSError, ValueError):
                pass  # stale heartbeat from a dead daemon: start a fresh one
        cmd = [sys.executable, "-m", "fedml_tpu.cli.edge_deployment.daemon",
               "--home", home, "--role", args.role, "--account-id", args.account_id]
        if args.broker:
            cmd += ["--broker", args.broker]
        with open(os.path.join(home, "daemon.log"), "ab") as logf:
            proc = subprocess.Popen(cmd, stdout=logf, stderr=subprocess.STDOUT,
                                    start_new_session=True)
        record["daemon_pid"] = proc.pid
        record["daemon_home"] = home
        print(f"daemon started (pid {proc.pid}, home {home})")
    with open(ACCOUNT_FILE, "w") as f:
        json.dump(record, f)
    print(f"logged in as account {args.account_id} ({args.role})")
    return 0


def cmd_logout(_args) -> int:
    try:
        with open(ACCOUNT_FILE) as f:
            record = json.load(f)
    except (FileNotFoundError, ValueError):
        record = {}
    home = record.get("daemon_home")
    if home:
        from .edge_deployment.daemon import FedMLDaemon

        try:
            FedMLDaemon.request_stop(home)
            print(f"daemon stop requested ({home})")
        except OSError:
            print(f"daemon home {home} gone; clearing binding anyway")
    try:
        os.remove(ACCOUNT_FILE)
    except FileNotFoundError:
        pass
    print("logged out")
    return 0


def cmd_dispatch(args) -> int:
    """Dispatch a run request to a running daemon (reference: the MLOps
    platform pushing a start-run message to the device)."""
    req = {"run_id": args.run_id, "package": os.path.abspath(args.package),
           "max_restarts": args.max_restarts, "extra_args": args.extra or []}
    home = args.daemon_home
    if home is None:
        try:
            with open(ACCOUNT_FILE) as f:
                home = json.load(f).get("daemon_home")
        except (FileNotFoundError, ValueError):
            pass
    if home is None:
        print("no daemon home (login first or pass --daemon_home)")
        return 1
    dispatch = os.path.join(home, "dispatch")
    os.makedirs(dispatch, exist_ok=True)
    path = os.path.join(dispatch, f"run_{args.run_id}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(req, f)
    os.replace(tmp, path)
    print(f"dispatched run {args.run_id} -> {path}")
    return 0


def cmd_build(args) -> int:
    from .build import build_package

    dest = args.dest_package or os.path.join(
        args.dest_folder or ".", f"fedml_{args.type}_package.zip"
    )
    path = build_package(
        source_dir=args.source_folder,
        entry_point=args.entry_point,
        config_path=args.config_file,
        dest_path=dest,
        package_type=args.type,
    )
    print(f"built {args.type} package: {path}")
    return 0


def cmd_run(args) -> int:
    """Run a deployed package under the supervisor (reference edge daemon)."""
    from .edge_deployment.client_runner import FedMLRunnerSupervisor

    sup = FedMLRunnerSupervisor(
        package_path=args.package,
        run_dir=args.run_dir,
        run_id=args.run_id,
        role=args.role,
        max_restarts=args.max_restarts,
        extra_args=args.extra or [],
    )
    return sup.run()


def cmd_status(args) -> int:
    if args.run_dir is None:
        # daemon-level status (reference `fedml status` against the platform)
        from .edge_deployment.daemon import FedMLDaemon

        home = args.daemon_home
        if home is None:
            try:
                with open(ACCOUNT_FILE) as f:
                    home = json.load(f).get("daemon_home")
            except (FileNotFoundError, ValueError):
                pass
        state = FedMLDaemon.read_state(home) if home else None
        if state is None:
            print("no daemon state (login first, or pass --run_dir)")
            return 1
        age = __import__("time").time() - state["time"]
        print(f"daemon pid={state['pid']} role={state['role']} "
              f"account={state['account_id']} heartbeat {age:.1f}s ago")
        for rid, st in sorted(state.get("runs", {}).items()):
            print(f"  run {rid}: {st}")
        return 0
    from .edge_deployment.client_runner import FedMLRunnerSupervisor

    records = FedMLRunnerSupervisor.read_status(args.run_dir)
    if not records:
        print("no status recorded")
        return 1
    for rec in records:
        print(f"{rec['time']:.0f} run={rec['run_id']} role={rec['role']} {rec['status']}")
    return 0


def cmd_logs(args) -> int:
    path = os.path.join(args.run_dir, "run.log")
    if not os.path.exists(path):
        print("no logs")
        return 1
    with open(path, errors="replace") as f:
        lines = f.readlines()
    for line in lines[-args.lines:]:
        sys.stdout.write(line)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="fedml_tpu", description="fedml_tpu CLI")
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("version").set_defaults(fn=cmd_version)

    pe = sub.add_parser("env")
    pe.add_argument("-v", "--verbose", action="store_true", help="probe accelerators")
    pe.set_defaults(fn=cmd_env)

    pl = sub.add_parser("login")
    pl.add_argument("account_id")
    pl.add_argument("--role", default="client", choices=["client", "server"])
    pl.add_argument("--no-daemon", action="store_true",
                    help="only write the account binding; don't start the daemon")
    pl.add_argument("--daemon_home", default=None)
    pl.add_argument("--broker", default=None,
                    help="host:port of a LocalBroker to take dispatches from")
    pl.set_defaults(fn=cmd_login)

    sub.add_parser("logout").set_defaults(fn=cmd_logout)

    pd = sub.add_parser("dispatch")
    pd.add_argument("--package", "-p", required=True)
    pd.add_argument("--run_id", default="0")
    pd.add_argument("--daemon_home", default=None)
    pd.add_argument("--max_restarts", type=int, default=2)
    pd.add_argument("extra", nargs="*", help="extra args passed to the entry")
    pd.set_defaults(fn=cmd_dispatch)

    pb = sub.add_parser("build")
    pb.add_argument("--type", "-t", default="client", choices=["client", "server"])
    pb.add_argument("--source_folder", "-sf", required=True)
    pb.add_argument("--entry_point", "-ep", required=True)
    pb.add_argument("--config_file", "-cf", required=True)
    pb.add_argument("--dest_folder", "-df", default=".")
    pb.add_argument("--dest_package", default=None)
    pb.set_defaults(fn=cmd_build)

    pr = sub.add_parser("run")
    pr.add_argument("--package", "-p", required=True)
    pr.add_argument("--run_dir", "-d", required=True)
    pr.add_argument("--run_id", default="0")
    pr.add_argument("--role", default="client", choices=["client", "server"])
    pr.add_argument("--max_restarts", type=int, default=2)
    pr.add_argument("extra", nargs="*", help="extra args passed to the entry")
    pr.set_defaults(fn=cmd_run)

    ps = sub.add_parser("status")
    ps.add_argument("--run_dir", "-d", default=None,
                    help="run directory (omit for daemon-level status)")
    ps.add_argument("--daemon_home", default=None)
    ps.set_defaults(fn=cmd_status)

    pg = sub.add_parser("logs")
    pg.add_argument("--run_dir", "-d", required=True)
    pg.add_argument("--lines", "-n", type=int, default=100)
    pg.set_defaults(fn=cmd_logs)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
