"""Semantic-segmentation ClientTrainer (reference ``simulation/mpi/fedseg``
eval protocol / ``app/fedcv/image_segmentation``): per-pixel CE rides the
engine's "ce" loss (the [B] sample mask broadcasts over the [B, H, W]
per-pixel loss), eval reports pixel accuracy + dataset-level mean IoU
accumulated as per-class (intersection, union) counts across batches."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .cls_trainer import ModelTrainerCLS


class ModelTrainerSeg(ModelTrainerCLS):
    loss_kind = "ce"

    def __init__(self, model, args, grad_hook=None):
        super().__init__(model, args, grad_hook=grad_hook)

        @jax.jit
        def evaluate(variables, x, masks):
            import optax

            from ...models.unet import iou_counts

            logits = model.apply(variables, x, train=False).astype(jnp.float32)
            per = optax.softmax_cross_entropy_with_integer_labels(logits, masks)
            pred = jnp.argmax(logits, axis=-1)
            correct = jnp.sum(pred == masks).astype(jnp.float32)
            inter, union = iou_counts(logits, masks, logits.shape[-1])
            return (jnp.sum(per), correct, jnp.asarray(masks.size, jnp.float32),
                    inter, union)

        self._seg_eval = evaluate

    def test(self, test_data, device, args):
        import numpy as np

        x, masks = test_data
        bs = 64
        loss = correct = total = 0.0
        inter = union = None
        for s in range(0, len(masks), bs):
            l, c, t, i, u = self._seg_eval(
                self.variables, jnp.asarray(x[s:s + bs]), jnp.asarray(masks[s:s + bs])
            )
            loss += float(l)
            correct += float(c)
            total += float(t)
            inter = np.asarray(i) if inter is None else inter + np.asarray(i)
            union = np.asarray(u) if union is None else union + np.asarray(u)
        present = union > 0
        miou = float(np.mean(inter[present] / union[present])) if present.any() else 0.0
        return {
            "test_correct": correct,  # pixel-correct count
            "test_loss": loss,
            "test_total": total,  # pixel count
            "test_miou": miou,
        }
