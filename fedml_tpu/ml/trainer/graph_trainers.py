"""Graph-task ClientTrainers beyond graph classification (reference
``app/fedgraphnn`` ego_networks_link_pred / recsys_subgraph_link_pred
and ``research/SpreadGNN`` multi-task moleculenet).

Both tasks share one masked-sentinel BCE eval (the -1 sentinel marks
unlabeled pairs / tasks, matching the reference's masked-metric convention
for link prediction and partially-labeled molecule sets); only the engine
loss key differs."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .cls_trainer import ModelTrainerCLS


class _MaskedBCETrainer(ModelTrainerCLS):
    """Shared eval: accuracy = (score > 0) vs label over labeled entries."""

    def __init__(self, model, args, grad_hook=None):
        super().__init__(model, args, grad_hook=grad_hook)

        @jax.jit
        def evaluate(variables, x, y):
            import optax

            scores = model.apply(variables, x, train=False).astype(jnp.float32)
            labeled = (y >= 0).astype(jnp.float32)
            labels = jnp.maximum(y, 0.0)
            per = optax.sigmoid_binary_cross_entropy(scores, labels)
            hit = ((scores > 0) == (labels > 0.5)).astype(jnp.float32) * labeled
            return jnp.sum(per * labeled), jnp.sum(hit), jnp.sum(labeled)

        self._bce_eval = evaluate

    def test(self, test_data, device, args):
        x, y = test_data
        l, correct, total = self._bce_eval(self.variables, jnp.asarray(x), jnp.asarray(y))
        return {
            "test_correct": float(correct),
            "test_loss": float(l),
            "test_total": float(total),
        }


class ModelTrainerLinkPred(_MaskedBCETrainer):
    """Link prediction: scores [B, N, N], labels {-1, 0, 1}."""

    loss_kind = "linkpred"


class ModelTrainerMTL(_MaskedBCETrainer):
    """Multi-task binary property prediction with partial labels
    (SpreadGNN setting): logits [B, T], labels {-1, 0, 1}."""

    loss_kind = "mtl_bce"
