"""Classification ClientTrainer over the jitted engine.

Parity with reference ``ml/trainer/my_model_trainer_classification.py:15-137``
(``ModelTrainerCLS``): same role, but ``train`` delegates to ONE compiled XLA
program per padded shape (ml/engine/train.py) instead of an eager batch loop.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ...core.alg_frame.client_trainer import ClientTrainer
from ..engine.train import make_eval_fn, make_local_train_fn, pad_to


class ModelTrainerCLS(ClientTrainer):
    loss_kind = "ce"  # subclasses override (tag prediction uses "bce")

    def __init__(self, model, args, grad_hook=None):
        super().__init__(model, args)
        self.module = model
        self.variables = None
        self.grad_hook = grad_hook  # per-step gradient transform (FedProx/SCAFFOLD/FedDyn)
        self._train_fns: Dict[Tuple[int, int], Any] = {}  # (padded_n, bs) -> fn
        self._eval_fn = make_eval_fn(model)
        # Base key is never advanced: per-call keys are fold_in(round, client)
        # so the stream is a pure function of (seed, round_idx, client id) and
        # checkpoint-resume replays it exactly (no stateful split counter).
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self.round_idx = 0

    def get_model_params(self):
        return self.variables

    def set_model_params(self, model_parameters):
        self.variables = model_parameters

    def _fn_for(self, padded_n: int, batch_size: int):
        key = (padded_n, batch_size)
        if key not in self._train_fns:
            from ..engine.train import build_local_train

            self._train_fns[key] = jax.jit(
                build_local_train(
                    self.module, self.args, batch_size, padded_n,
                    grad_hook=self.grad_hook, loss=self.loss_kind,
                )
            )
        return self._train_fns[key]

    @staticmethod
    def padded_size(n: int, batch_size: int) -> int:
        """Round client size up to a bucket (next multiple of batch_size and
        power-of-two-ish) so few distinct shapes are compiled."""
        n = max(n, batch_size)
        bucket = batch_size
        while bucket < n:
            bucket *= 2
        return bucket

    def train(self, train_data, device, args, extra=None):
        x, y = train_data
        n = len(y)
        bs = int(getattr(args, "batch_size", 32))
        padded_n = self.padded_size(n, bs)
        fn = self._fn_for(padded_n, bs)
        sub = jax.random.fold_in(
            jax.random.fold_in(self.rng, int(self.round_idx)), int(self.id or 0)
        )
        xp = pad_to(jnp.asarray(x), padded_n)
        yp = pad_to(jnp.asarray(y), padded_n)
        result = fn(self.variables, xp, yp, n, sub, extra)
        self.variables = result.variables
        self.last_result = result
        return result

    def test(self, test_data, device, args):
        x, y = test_data
        xs, ys = jnp.asarray(x), jnp.asarray(y)
        m = jnp.ones((xs.shape[0],), jnp.float32)
        l, c, t = self._eval_fn(self.variables, xs, ys, m)
        return {
            "test_correct": float(c),
            "test_loss": float(l),
            "test_total": float(t),
        }
