"""Object-detection ClientTrainer (reference ``app/fedcv/object_detection``
task family): CE + smooth-L1 box loss, class-accuracy + mean-IoU eval."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .cls_trainer import ModelTrainerCLS


def box_iou(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """IoU of [B, 4] (cx, cy, w, h) box pairs."""
    ax0, ay0 = a[:, 0] - a[:, 2] / 2, a[:, 1] - a[:, 3] / 2
    ax1, ay1 = a[:, 0] + a[:, 2] / 2, a[:, 1] + a[:, 3] / 2
    bx0, by0 = b[:, 0] - b[:, 2] / 2, b[:, 1] - b[:, 3] / 2
    bx1, by1 = b[:, 0] + b[:, 2] / 2, b[:, 1] + b[:, 3] / 2
    iw = jnp.maximum(jnp.minimum(ax1, bx1) - jnp.maximum(ax0, bx0), 0.0)
    ih = jnp.maximum(jnp.minimum(ay1, by1) - jnp.maximum(ay0, by0), 0.0)
    inter = iw * ih
    union = a[:, 2] * a[:, 3] + b[:, 2] * b[:, 3] - inter
    return inter / jnp.maximum(union, 1e-9)


class ModelTrainerDET(ModelTrainerCLS):
    loss_kind = "det"

    def __init__(self, model, args, grad_hook=None):
        super().__init__(model, args, grad_hook=grad_hook)

        @jax.jit
        def evaluate(variables, x, y):
            out = model.apply(variables, x, train=False).astype(jnp.float32)
            n_cls = out.shape[-1] - 4
            import optax

            per = optax.softmax_cross_entropy_with_integer_labels(
                out[:, :n_cls], y[:, 0].astype(jnp.int32)
            )
            pred_cls = jnp.argmax(out[:, :n_cls], axis=-1)
            correct = (pred_cls == y[:, 0].astype(jnp.int32)).astype(jnp.float32)
            iou = box_iou(out[:, n_cls:], y[:, 1:])
            return (jnp.sum(per), jnp.sum(correct), jnp.sum(iou),
                    jnp.asarray(x.shape[0], jnp.float32))

        self._det_eval = evaluate

    def test(self, test_data, device, args):
        x, y = test_data
        l, correct, iou_sum, total = self._det_eval(
            self.variables, jnp.asarray(x), jnp.asarray(y)
        )
        return {
            "test_correct": float(correct),  # class-accuracy count
            "test_loss": float(l),
            "test_total": float(total),
            "test_mean_iou": float(iou_sum) / max(float(total), 1.0),
        }
