"""Next-word-prediction ClientTrainer (reference
``ml/trainer/my_model_trainer_nwp.py`` ``ModelTrainerNWP``).

The compiled engine already treats [B, L] integer label tensors per-token
(masked CE + token accuracy, ml/engine/train.py), so the NWP trainer IS the
classification trainer with token-level metrics; this subclass exists for
factory parity and as the anchor for NWP-specific extensions."""

from __future__ import annotations

from .cls_trainer import ModelTrainerCLS


class ModelTrainerNWP(ModelTrainerCLS):
    pass
