"""Tag-prediction ClientTrainer (reference
``ml/trainer/my_model_trainer_tag_prediction.py`` ``ModelTrainerTAGPred``):
multi-label classification with sigmoid BCE on the SHARED compiled engine
(``loss="bce"``) — same padding/masking/scan machinery as every other
trainer, so no client sample is dropped or double-weighted.

Labels may be multi-hot [B, C] float or integer class ids [B] (converted to
one-hot), matching the stackoverflow_lr data either way.  Eval reports
label-position accuracy through the protocol's shared keys (test_correct /
test_total are per-label counts; loss aggregates to mean BCE per label) plus
precision/recall/F1 extras."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from .cls_trainer import ModelTrainerCLS


def _as_multihot(y: jnp.ndarray, num_classes: int) -> jnp.ndarray:
    if y.ndim == 1:
        return jax.nn.one_hot(y, num_classes)
    return y.astype(jnp.float32)


class ModelTrainerTAGPred(ModelTrainerCLS):
    loss_kind = "bce"

    def _num_classes(self) -> int:
        leaves = jax.tree_util.tree_leaves(self.variables["params"])
        return int(leaves[-1].shape[-1])

    def train(self, train_data, device, args, extra=None):
        x, y = train_data
        yh = _as_multihot(jnp.asarray(y), self._num_classes())
        return super().train((x, yh), device, args, extra=extra)

    def test(self, test_data, device, args):
        x, y = test_data
        logits = self.module.apply(self.variables, jnp.asarray(x), train=False)
        yh = _as_multihot(jnp.asarray(y), logits.shape[-1])
        pred = (jax.nn.sigmoid(logits) > 0.5).astype(jnp.float32)
        tp = float(jnp.sum(pred * yh))
        fp = float(jnp.sum(pred * (1 - yh)))
        fn = float(jnp.sum((1 - pred) * yh))
        precision = tp / max(tp + fp, 1.0)
        recall = tp / max(tp + fn, 1.0)
        f1 = 2 * precision * recall / max(precision + recall, 1e-12)
        mean_bce = float(jnp.mean(optax.sigmoid_binary_cross_entropy(logits, yh)))
        n_positions = float(yh.size)
        return {
            # shared protocol keys, all per label-position so the server's
            # correct/total and loss/total divisions stay meaningful
            "test_correct": float(jnp.sum(pred == yh)),
            "test_loss": mean_bce * n_positions,
            "test_total": n_positions,
            "test_precision": precision,
            "test_recall": recall,
            "test_f1": f1,
        }
