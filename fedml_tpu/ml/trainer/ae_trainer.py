"""Anomaly-detection ClientTrainer (reference
``iot/anomaly_detection_for_cybersecurity``): clients train an autoencoder
to reconstruct their (benign) local traffic; eval flags anomalies by
reconstruction error.

Training rides the engine's "mse" loss with targets = inputs (the dataset's
train split carries y = x).  Eval is UNSUPERVISED thresholding: the cut is
median + 3*MAD of the test-set error distribution — a robust statistic that
needs no label peeking (the reference derives its threshold from benign
training errors; a contaminated-set robust quantile plays the same role
server-side)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .cls_trainer import ModelTrainerCLS


class ModelTrainerAE(ModelTrainerCLS):
    loss_kind = "mse"

    def __init__(self, model, args, grad_hook=None):
        super().__init__(model, args, grad_hook=grad_hook)

        @jax.jit
        def evaluate(variables, x, flags):
            recon = model.apply(variables, x, train=False).astype(jnp.float32)
            flat = x.reshape((x.shape[0], -1)).astype(jnp.float32)
            err = jnp.mean(jnp.square(recon - flat), axis=-1)
            med = jnp.median(err)
            mad = jnp.median(jnp.abs(err - med))
            thresh = med + 3.0 * 1.4826 * mad
            pred = (err > thresh).astype(jnp.float32)
            flags = flags.astype(jnp.float32)
            correct = jnp.sum((pred == flags).astype(jnp.float32))
            loss = jnp.sum(err)
            # detection recall on the anomalous tail (the metric the
            # reference's IoT example reports)
            tp = jnp.sum(pred * flags)
            pos = jnp.maximum(jnp.sum(flags), 1.0)
            return loss, correct, jnp.asarray(x.shape[0], jnp.float32), tp / pos

        self._ae_eval = evaluate

    def train(self, train_data, device, args, extra=None):
        x, y = train_data
        # targets are the inputs; tolerate datasets that ship flags for train
        if y is None or jnp.asarray(y).ndim == 1:
            y = x.reshape((len(x), -1))
        return super().train((x, y), device, args, extra=extra)

    def test(self, test_data, device, args):
        x, flags = test_data
        l, correct, total, recall = self._ae_eval(
            self.variables, jnp.asarray(x), jnp.asarray(flags)
        )
        return {
            "test_correct": float(correct),
            "test_loss": float(l),
            "test_total": float(total),
            "test_anomaly_recall": float(recall),
        }
