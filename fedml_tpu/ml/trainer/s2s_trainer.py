"""Seq2seq ClientTrainer (reference ``app/fednlp/seq2seq`` summarization /
dialogue task): causal-LM teacher forcing over the packed [src ‖ SEP ‖ tgt]
sequence, loss/eval masked to target positions (engine loss kind "s2s").
Eval reports masked token accuracy (test_correct/test_total) plus exact
sequence match (test_exact)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .cls_trainer import ModelTrainerCLS


class ModelTrainerS2S(ModelTrainerCLS):
    loss_kind = "s2s"

    def __init__(self, model, args, grad_hook=None):
        super().__init__(model, args, grad_hook=grad_hook)

        @jax.jit
        def evaluate(variables, x, y):
            import optax

            logits = model.apply(variables, x, train=False).astype(jnp.float32)
            tok_mask = (y >= 0).astype(jnp.float32)
            labels = jnp.maximum(y, 0)
            per = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
            pred = jnp.argmax(logits, axis=-1)
            hit = (pred == labels).astype(jnp.float32) * tok_mask
            exact = jnp.all((pred == labels) | (tok_mask < 0.5), axis=-1)
            return (
                jnp.sum(per * tok_mask),
                jnp.sum(hit),
                jnp.sum(tok_mask),
                jnp.sum(exact.astype(jnp.float32)),
            )

        self._s2s_eval = evaluate

    def test(self, test_data, device, args):
        x, y = test_data
        l, correct, total, exact = self._s2s_eval(
            self.variables, jnp.asarray(x), jnp.asarray(y)
        )
        return {
            "test_correct": float(correct),
            "test_loss": float(l),
            "test_total": float(total),
            # normalized like det_trainer's test_mean_iou (rate, not count)
            "test_exact_match": float(exact) / max(float(len(y)), 1.0),
        }
