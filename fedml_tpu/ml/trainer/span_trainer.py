"""Span-extraction ClientTrainer (reference ``app/fednlp/span_extraction``
QA task): start/end CE loss, exact-match + endpoint accuracy eval."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .cls_trainer import ModelTrainerCLS


class ModelTrainerSpan(ModelTrainerCLS):
    loss_kind = "span"

    def __init__(self, model, args, grad_hook=None):
        super().__init__(model, args, grad_hook=grad_hook)

        @jax.jit
        def evaluate(variables, x, y):
            logits = model.apply(variables, x, train=False).astype(jnp.float32)
            import optax

            per = optax.softmax_cross_entropy_with_integer_labels(
                logits[..., 0], y[:, 0]
            ) + optax.softmax_cross_entropy_with_integer_labels(
                logits[..., 1], y[:, 1]
            )
            start = jnp.argmax(logits[..., 0], axis=-1)
            end = jnp.argmax(logits[..., 1], axis=-1)
            exact = ((start == y[:, 0]) & (end == y[:, 1])).astype(jnp.float32)
            return jnp.sum(per), jnp.sum(exact), jnp.asarray(x.shape[0], jnp.float32)

        self._span_eval = evaluate

    def test(self, test_data, device, args):
        x, y = test_data
        l, correct, total = self._span_eval(self.variables, jnp.asarray(x), jnp.asarray(y))
        return {
            "test_correct": float(correct),  # exact-match count
            "test_loss": float(l),
            "test_total": float(total),
        }
