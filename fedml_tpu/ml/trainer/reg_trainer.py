"""Graph/property regression ClientTrainer (reference
``app/fedgraphnn/moleculenet_graph_reg``: freesolv/esol/lipophilicity):
trains on the engine "mse" loss; eval reports SSE (protocol loss key) and a
within-tolerance hit rate so the shared accuracy plumbing stays meaningful
(RMSE is derivable from test_loss/test_total)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .cls_trainer import ModelTrainerCLS


class ModelTrainerReg(ModelTrainerCLS):
    loss_kind = "mse"
    tolerance = 0.5  # |err| < tol counts as a hit (test_correct)

    def __init__(self, model, args, grad_hook=None):
        super().__init__(model, args, grad_hook=grad_hook)
        tol = float(getattr(args, "regression_tolerance", self.tolerance))

        @jax.jit
        def evaluate(variables, x, y):
            pred = model.apply(variables, x, train=False).astype(jnp.float32)
            y = y.astype(jnp.float32).reshape(pred.shape)
            err = jnp.mean(jnp.square(pred - y), axis=tuple(range(1, pred.ndim)))
            hits = (jnp.abs(pred - y).max(axis=tuple(range(1, pred.ndim))) < tol)
            return (
                jnp.sum(err),
                jnp.sum(hits.astype(jnp.float32)),
                jnp.asarray(x.shape[0], jnp.float32),
            )

        self._reg_eval = evaluate

    def test(self, test_data, device, args):
        x, y = test_data
        l, correct, total = self._reg_eval(self.variables, jnp.asarray(x), jnp.asarray(y))
        return {
            "test_correct": float(correct),
            "test_loss": float(l),
            "test_total": float(total),
            "test_rmse": float(jnp.sqrt(l / jnp.maximum(total, 1.0))),
        }
