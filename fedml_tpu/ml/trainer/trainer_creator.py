"""Trainer factory (reference ``ml/trainer/trainer_creator.py:6-13``
``create_model_trainer``): dispatch on dataset family."""

from __future__ import annotations

from ...core.alg_frame.client_trainer import ClientTrainer

_NWP_DATASETS = {"shakespeare", "fed_shakespeare", "stackoverflow_nwp"}
_TAG_DATASETS = {"stackoverflow_lr", "nuswide", "nus_wide"}
# per-token classification reuses the NWP trainer (same masked per-token CE
# and token-accuracy math — reference seq_tagging task); node classification
# (ego_networks_node_clf) rides the same path with [B, N] node labels
_SEQTAG_DATASETS = {"onto_tagging", "wikiner", "ego_nodeclf"}
_REG_DATASETS = {"freesolv", "esol", "lipophilicity"}
_SPAN_DATASETS = {"squad_span"}
_DET_DATASETS = {"synthetic_det", "coco_det"}
_S2S_DATASETS = {"synthetic_s2s", "cornell_movie_dialogue"}
_LINKPRED_DATASETS = {"ego_linkpred", "recsys_linkpred"}
_MTL_DATASETS = {"moleculenet_mtl"}
_AE_DATASETS = {"iot_anomaly", "nbaiot"}
# per-pixel CE rides the "ce" engine loss (mask broadcasts over H, W);
# the seg trainer only changes EVAL (pixel acc + dataset-level mIoU)
_SEG_DATASETS = {"synthetic_seg", "fets2021", "pascal_voc"}


def loss_kind_for_dataset(dataset: str) -> str:
    """Engine loss key for a dataset family (the in-mesh XLA round plumbs
    this straight into the compiled engines; the sp path reaches the same
    key through each task trainer's ``loss_kind``).  ``bce`` datasets are
    NOT mapped here: their int->multi-hot label conversion lives in the tag
    trainer, which only the sp path runs."""
    dataset = dataset.lower()
    if dataset in _SPAN_DATASETS:
        return "span"
    if dataset in _DET_DATASETS:
        return "det"
    if dataset in _S2S_DATASETS:
        return "s2s"
    if dataset in _LINKPRED_DATASETS:
        return "linkpred"
    if dataset in _MTL_DATASETS:
        return "mtl_bce"
    if dataset in _AE_DATASETS or dataset in _REG_DATASETS:
        return "mse"
    return "ce"


def create_model_trainer(model, args, grad_hook=None) -> ClientTrainer:
    dataset = str(getattr(args, "dataset", "")).lower()
    if dataset in _NWP_DATASETS or dataset in _SEQTAG_DATASETS:
        from .nwp_trainer import ModelTrainerNWP

        return ModelTrainerNWP(model, args, grad_hook=grad_hook)
    if dataset in _TAG_DATASETS:
        from .tag_trainer import ModelTrainerTAGPred

        return ModelTrainerTAGPred(model, args)
    if dataset in _SPAN_DATASETS:
        from .span_trainer import ModelTrainerSpan

        return ModelTrainerSpan(model, args, grad_hook=grad_hook)
    if dataset in _DET_DATASETS:
        from .det_trainer import ModelTrainerDET

        return ModelTrainerDET(model, args, grad_hook=grad_hook)
    if dataset in _S2S_DATASETS:
        from .s2s_trainer import ModelTrainerS2S

        return ModelTrainerS2S(model, args, grad_hook=grad_hook)
    if dataset in _LINKPRED_DATASETS:
        from .graph_trainers import ModelTrainerLinkPred

        return ModelTrainerLinkPred(model, args, grad_hook=grad_hook)
    if dataset in _MTL_DATASETS:
        from .graph_trainers import ModelTrainerMTL

        return ModelTrainerMTL(model, args, grad_hook=grad_hook)
    if dataset in _AE_DATASETS:
        from .ae_trainer import ModelTrainerAE

        return ModelTrainerAE(model, args, grad_hook=grad_hook)
    if dataset in _SEG_DATASETS:
        from .seg_trainer import ModelTrainerSeg

        return ModelTrainerSeg(model, args, grad_hook=grad_hook)
    if dataset in _REG_DATASETS:
        from .reg_trainer import ModelTrainerReg

        return ModelTrainerReg(model, args, grad_hook=grad_hook)
    from .cls_trainer import ModelTrainerCLS

    return ModelTrainerCLS(model, args, grad_hook=grad_hook)
