"""ServerAggregator factory (reference ``ml/aggregator/aggregator_creator.py``
``create_server_aggregator``): dataset-family dispatch mirroring the trainer
factory.  The default aggregator's masked eval already computes token-level
metrics for NWP label tensors; tag prediction gets the BCE aggregator."""

from __future__ import annotations

from ...core.alg_frame.server_aggregator import ServerAggregator
from ..trainer.trainer_creator import _TAG_DATASETS
from .default_aggregator import DefaultServerAggregator


class TAGPredServerAggregator(DefaultServerAggregator):
    """Evaluates with the multi-label BCE metrics of the tag trainer."""

    def test(self, test_data, device, args):
        from ..trainer.tag_trainer import ModelTrainerTAGPred

        probe = ModelTrainerTAGPred(self.module, args)
        probe.set_model_params(self.variables)
        return probe.test(test_data, device, args)


def create_server_aggregator(model, args) -> ServerAggregator:
    dataset = str(getattr(args, "dataset", "")).lower()
    if dataset in _TAG_DATASETS:
        return TAGPredServerAggregator(model, args)
    return DefaultServerAggregator(model, args)
