"""ServerAggregator factory (reference ``ml/aggregator/aggregator_creator.py``
``create_server_aggregator``): dataset-family dispatch mirroring the trainer
factory.  The default aggregator's masked eval already computes token-level
metrics for NWP label tensors; tag prediction / span extraction / detection
evaluate through their task trainer's test() via _TrainerEvalAggregator."""

from __future__ import annotations

from ...core.alg_frame.server_aggregator import ServerAggregator
from ..trainer.trainer_creator import _TAG_DATASETS
from .default_aggregator import DefaultServerAggregator


class _TrainerEvalAggregator(DefaultServerAggregator):
    """Evaluates via a task trainer's test() (tag BCE metrics, span
    exact-match, detection class-acc + IoU).  The probe is built once — its
    jitted eval closure compiles once, not per eval round."""

    def __init__(self, model, args, trainer_cls):
        super().__init__(model, args)
        self._probe = trainer_cls(model, args)

    def test(self, test_data, device, args):
        self._probe.set_model_params(self.variables)
        return self._probe.test(test_data, device, args)


def create_server_aggregator(model, args) -> ServerAggregator:
    dataset = str(getattr(args, "dataset", "")).lower()
    from ..trainer.trainer_creator import _DET_DATASETS, _SPAN_DATASETS

    if dataset in _TAG_DATASETS:
        from ..trainer.tag_trainer import ModelTrainerTAGPred

        return _TrainerEvalAggregator(model, args, ModelTrainerTAGPred)
    if dataset in _SPAN_DATASETS:
        from ..trainer.span_trainer import ModelTrainerSpan

        return _TrainerEvalAggregator(model, args, ModelTrainerSpan)
    if dataset in _DET_DATASETS:
        from ..trainer.det_trainer import ModelTrainerDET

        return _TrainerEvalAggregator(model, args, ModelTrainerDET)
    from ..trainer.trainer_creator import (
        _LINKPRED_DATASETS, _MTL_DATASETS, _S2S_DATASETS,
    )

    if dataset in _S2S_DATASETS:
        from ..trainer.s2s_trainer import ModelTrainerS2S

        return _TrainerEvalAggregator(model, args, ModelTrainerS2S)
    if dataset in _LINKPRED_DATASETS:
        from ..trainer.graph_trainers import ModelTrainerLinkPred

        return _TrainerEvalAggregator(model, args, ModelTrainerLinkPred)
    if dataset in _MTL_DATASETS:
        from ..trainer.graph_trainers import ModelTrainerMTL

        return _TrainerEvalAggregator(model, args, ModelTrainerMTL)
    from ..trainer.trainer_creator import _AE_DATASETS, _REG_DATASETS

    if dataset in _AE_DATASETS:
        from ..trainer.ae_trainer import ModelTrainerAE

        return _TrainerEvalAggregator(model, args, ModelTrainerAE)
    if dataset in _REG_DATASETS:
        from ..trainer.reg_trainer import ModelTrainerReg

        return _TrainerEvalAggregator(model, args, ModelTrainerReg)
    from ..trainer.trainer_creator import _SEG_DATASETS

    if dataset in _SEG_DATASETS:
        from ..trainer.seg_trainer import ModelTrainerSeg

        return _TrainerEvalAggregator(model, args, ModelTrainerSeg)
    return DefaultServerAggregator(model, args)
