"""Default ServerAggregator implementation.

Parity with reference ``ml/aggregator/default_aggregator.py`` — holds the
global flax variables, evaluates with the jitted eval closure.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from ...core.alg_frame.server_aggregator import ServerAggregator
from ..engine.train import make_eval_fn, pad_to


class DefaultServerAggregator(ServerAggregator):
    def __init__(self, model, args):
        super().__init__(model, args)
        self.module = model
        self.variables = None
        self._eval_fn = make_eval_fn(model)
        self._eval_batch = int(getattr(args, "eval_batch_size", 256))

    def get_model_params(self) -> Any:
        return self.variables

    def set_model_params(self, model_parameters: Any) -> None:
        self.variables = model_parameters

    def test(self, test_data, device, args):
        """test_data: (x, y) arrays -> dict(test_correct, test_loss, test_total)."""
        x, y = test_data
        b = self._eval_batch
        n = len(y)
        steps = max(1, -(-n // b))
        loss_sum = correct = total = 0.0
        for s in range(steps):
            xs = jnp.asarray(x[s * b : (s + 1) * b])
            ys = jnp.asarray(y[s * b : (s + 1) * b])
            m = jnp.ones((xs.shape[0],), jnp.float32)
            if xs.shape[0] < b:  # pad tail batch to keep one compiled shape
                pad_n = b - xs.shape[0]
                xs = pad_to(xs, b)
                ys = pad_to(ys, b)
                m = jnp.concatenate([m, jnp.zeros((pad_n,), jnp.float32)])
            l, c, t = self._eval_fn(self.variables, xs, ys, m)
            loss_sum += float(l)
            correct += float(c)
            total += float(t)
        return {
            "test_correct": correct,
            "test_loss": loss_sum,
            "test_total": max(total, 1.0),
        }
