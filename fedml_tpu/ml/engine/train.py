"""Functional training engine: jitted local-training and eval closures.

This is the TPU-native replacement for the reference's eager per-batch torch
loops (``ml/trainer/my_model_trainer_classification.py:15-137``).  Local
training is ONE compiled XLA program: ``lax.scan`` over epochs, nested scan
over steps, per-epoch on-device shuffling, padding masked out of the loss.
The same compiled function serves every client with the same padded shape —
no per-client recompiles (the shape-bucketing that makes FL's ragged clients
XLA-friendly, cf. SURVEY.md §7 "hard parts").

Model state convention: a flax ``variables`` dict ``{"params": ...,
["batch_stats": ...]}``.  Both collections are aggregated by FedAvg (matching
torch ``state_dict`` averaging, which includes BN running stats).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

Pytree = Any


class LocalTrainResult(NamedTuple):
    variables: Pytree
    loss: jnp.ndarray  # mean masked loss over the run
    seen: jnp.ndarray  # number of (valid) samples processed
    steps: Any = 0.0  # effective optimizer steps (FedNova tau_i)


def make_optimizer(args) -> optax.GradientTransformation:
    """Client optimizer factory (reference trainer's SGD/Adam switch)."""
    name = str(getattr(args, "client_optimizer", "sgd")).lower()
    lr = float(getattr(args, "learning_rate", 0.01))
    wd = float(getattr(args, "weight_decay", 0.0))
    momentum = float(getattr(args, "momentum", 0.0))
    if name == "sgd":
        tx = optax.sgd(lr, momentum=momentum if momentum > 0 else None)
    elif name == "adam":
        tx = optax.adam(lr)
    elif name == "adamw":
        tx = optax.adamw(lr, weight_decay=wd)
    else:
        raise ValueError(f"unknown client_optimizer {name!r}")
    if wd > 0 and name in ("sgd", "adam"):
        tx = optax.chain(optax.add_decayed_weights(wd), tx)
    return tx


def softmax_ce_loss(logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray):
    """Masked CE.  Handles both [B] labels and [B, L] per-token labels (NWP):
    a per-example mask [B] broadcasts over trailing label axes.  Logits are
    promoted to fp32 so bf16 compute mode keeps a stable softmax."""
    per = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    )
    mask = mask.reshape(mask.shape + (1,) * (per.ndim - mask.ndim))
    total = jnp.sum(per * mask)
    count = jnp.maximum(jnp.sum(jnp.broadcast_to(mask, per.shape)), 1.0)
    return total / count, (total, count)


def sigmoid_bce_loss(logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray):
    """Masked multi-label BCE: labels are multi-hot [B, C] floats (tag
    prediction); per-example mask [B] broadcasts over label positions."""
    per = optax.sigmoid_binary_cross_entropy(logits.astype(jnp.float32), labels)
    mask = mask.reshape(mask.shape + (1,) * (per.ndim - mask.ndim))
    total = jnp.sum(per * mask)
    count = jnp.maximum(jnp.sum(jnp.broadcast_to(mask, per.shape)), 1.0)
    return total / count, (total, count)


def span_ce_loss(logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray):
    """Span extraction: logits [B, L, 2], labels [B, 2] = (start, end);
    CE over sequence positions for each endpoint (reference
    app/fednlp/span_extraction QA loss)."""
    start, end = logits[..., 0], logits[..., 1]
    per = optax.softmax_cross_entropy_with_integer_labels(
        start.astype(jnp.float32), labels[:, 0]
    ) + optax.softmax_cross_entropy_with_integer_labels(
        end.astype(jnp.float32), labels[:, 1]
    )
    total = jnp.sum(per * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count, (total, count)


def detection_loss(logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray,
                   box_weight: float = 5.0):
    """Single-object detection: logits [B, C+4] (class logits ‖ box),
    labels [B, 5] = (class, cx, cy, w, h) — CE + weighted smooth-L1 on the
    box (reference app/fedcv/object_detection composite loss shape)."""
    n_cls = logits.shape[-1] - 4
    cls_logits = logits[:, :n_cls].astype(jnp.float32)
    box = logits[:, n_cls:].astype(jnp.float32)
    per_cls = optax.softmax_cross_entropy_with_integer_labels(
        cls_logits, labels[:, 0].astype(jnp.int32)
    )
    diff = jnp.abs(box - labels[:, 1:])
    per_box = jnp.sum(jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5), axis=-1)
    per = per_cls + box_weight * per_box
    total = jnp.sum(per * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count, (total, count)


def seq2seq_ce_loss(logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray):
    """Seq2seq teacher-forced CE (reference app/fednlp/seq2seq, BART-style):
    logits [B, L, V] from a causal LM over the packed [src ‖ SEP ‖ tgt]
    sequence; labels [B, L] int with -1 marking non-target positions (the
    whole source prefix).  Per-token CE over target positions only."""
    tok_mask = (labels >= 0).astype(jnp.float32)
    per = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), jnp.maximum(labels, 0)
    )
    mask = mask.reshape(mask.shape + (1,) * (per.ndim - mask.ndim))
    full = tok_mask * mask
    total = jnp.sum(per * full)
    count = jnp.maximum(jnp.sum(full), 1.0)
    return total / count, (total, count)


def masked_sentinel_bce_loss(logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray):
    """BCE over labeled entries only, with -1 sentinels marking unlabeled
    positions.  Serves both link prediction ("linkpred": [B, N, N] pairwise
    scores, labeled = held-out positives + sampled negatives — reference
    app/fedgraphnn ego_networks/recsys_subgraph link_pred) and multi-task
    property prediction with partial labels ("mtl_bce": [B, T] task logits,
    the SpreadGNN / moleculenet setting)."""
    labeled = (labels >= 0).astype(jnp.float32)
    per = optax.sigmoid_binary_cross_entropy(
        logits.astype(jnp.float32), jnp.maximum(labels, 0.0)
    )
    mask = mask.reshape(mask.shape + (1,) * (per.ndim - mask.ndim))
    full = labeled * mask
    total = jnp.sum(per * full)
    count = jnp.maximum(jnp.sum(full), 1.0)
    return total / count, (total, count)


def mse_loss(logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray):
    """Masked mean-squared error (reconstruction training — the IoT
    anomaly-detection autoencoder family, reference
    ``iot/anomaly_detection_for_cybersecurity``): labels are the
    regression/reconstruction targets, same shape as logits."""
    per = jnp.mean(
        jnp.square(logits.astype(jnp.float32) - labels.astype(jnp.float32)),
        axis=tuple(range(1, logits.ndim)),
    )
    mask = mask.astype(jnp.float32)
    total = jnp.sum(per * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count, (total, count)


LOSS_FNS = {"ce": softmax_ce_loss, "bce": sigmoid_bce_loss,
            "span": span_ce_loss, "det": detection_loss,
            "s2s": seq2seq_ce_loss, "linkpred": masked_sentinel_bce_loss,
            "mtl_bce": masked_sentinel_bce_loss, "mse": mse_loss}


def resolve_grad_hook(args, grad_hook: Optional[Callable]) -> Optional[Callable]:
    """Shared grad-hook resolution for both the padded and packed engines:
    an explicit hook wins; otherwise ``args.proximal_mu`` > 0 installs the
    FedProx hook (g + mu*(p - anchor))."""
    mu = float(getattr(args, "proximal_mu", 0.0) or 0.0)
    if grad_hook is None and mu > 0:
        def grad_hook(grads, params, anchor, extra):
            return jax.tree_util.tree_map(
                lambda g, p, a: g + mu * (p - a), grads, params, anchor
            )
    return grad_hook


def build_loss_fn(module, has_dropout: bool = True, loss: str = "ce") -> Callable:
    """Shared masked-loss closure for both engines: applies the module with
    any mutable (non-param) collections threaded through, returns
    ``(loss_val, updated_collections)``."""
    loss_kind = LOSS_FNS[loss]

    def loss_fn(params, other_vars, bx, by, bmask, rng):
        variables = dict(other_vars, params=params)
        mutable = [k for k in other_vars.keys()]
        rngs = {"dropout": rng} if has_dropout else None
        if mutable:
            logits, updated = module.apply(
                variables, bx, train=True, rngs=rngs, mutable=mutable
            )
        else:
            logits = module.apply(variables, bx, train=True, rngs=rngs)
            updated = {}
        loss_val, _ = loss_kind(logits, by, bmask)
        return loss_val, updated

    return loss_fn


def make_local_train_fn(
    module,
    args,
    batch_size: int,
    padded_n: int,
    epochs: Optional[int] = None,
    has_dropout: bool = True,
) -> Callable[[Pytree, jnp.ndarray, jnp.ndarray, jnp.ndarray, jax.Array], LocalTrainResult]:
    """Jitted local-training closure (see :func:`build_local_train`)."""
    return jax.jit(build_local_train(module, args, batch_size, padded_n, epochs, has_dropout))


def build_local_train(
    module,
    args,
    batch_size: int,
    padded_n: int,
    epochs: Optional[int] = None,
    has_dropout: bool = True,
    grad_hook: Optional[Callable] = None,
    loss: str = "ce",
) -> Callable[..., LocalTrainResult]:
    """Build the PURE local-training function (not jitted — composable inside
    shard_map/scan in the XLA simulator).

    Returned fn: ``(variables, x [padded_n,...], y [padded_n], n_valid, rng,
    extra=None) -> LocalTrainResult``.  Data must be valid-first; indices >=
    n_valid are padding and masked out of loss/gradients.

    ``grad_hook(grads, params, anchor, extra) -> grads`` runs per step, where
    ``anchor`` is the round-start params.  This one hook expresses the local
    variants of the algorithm zoo: FedProx (g + mu*(p - anchor)), SCAFFOLD
    (g - c_i + c from ``extra``), FedDyn (g - h_i + alpha*(p - anchor)) —
    cf. reference fedprox/fednova trainer subclasses (SURVEY.md §2.5).
    ``args.proximal_mu`` > 0 installs the FedProx hook automatically.
    """
    tx = make_optimizer(args)
    epochs = int(epochs if epochs is not None else getattr(args, "epochs", 1))
    steps_per_epoch = max(1, -(-padded_n // batch_size))

    grad_hook = resolve_grad_hook(args, grad_hook)
    loss_fn = build_loss_fn(module, has_dropout, loss)

    def train(variables, x, y, n_valid, rng, extra=None) -> LocalTrainResult:
        params = variables["params"]
        anchor = params
        other = {k: v for k, v in variables.items() if k != "params"}
        opt_state = tx.init(params)
        n_valid = jnp.asarray(n_valid, jnp.int32)

        def epoch_body(carry, ek):
            params, other, opt_state, loss_sum, cnt_sum, step_cnt = carry
            perm = jax.random.permutation(jax.random.fold_in(ek, 0), padded_n)

            def step_body(c, sk_i):
                params, other, opt_state, lsum, csum, scnt = c
                sk, i = sk_i
                idx = jax.lax.dynamic_slice_in_dim(perm, i * batch_size, batch_size)
                bx = jnp.take(x, idx, axis=0)
                by = jnp.take(y, idx, axis=0)
                bmask = (idx < n_valid).astype(jnp.float32)
                (loss, updated), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    params, other, bx, by, bmask, sk
                )
                if grad_hook is not None:
                    grads = grad_hook(grads, params, anchor, extra)
                # Zero the step entirely if the batch is all padding.
                any_valid = jnp.sum(bmask) > 0
                updates, new_opt = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                params = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(any_valid, new, old), new_params, params
                )
                opt_state = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(any_valid, new, old), new_opt, opt_state
                )
                if updated:
                    other = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(any_valid, new, old), updated, other
                    )
                scnt = scnt + any_valid.astype(jnp.float32)
                return (params, other, opt_state, lsum + loss * jnp.sum(bmask), csum + jnp.sum(bmask), scnt), None

            step_keys = jax.random.split(jax.random.fold_in(ek, 1), steps_per_epoch)
            (params, other, opt_state, loss_sum, cnt_sum, step_cnt), _ = jax.lax.scan(
                step_body,
                (params, other, opt_state, loss_sum, cnt_sum, step_cnt),
                (step_keys, jnp.arange(steps_per_epoch)),
            )
            return (params, other, opt_state, loss_sum, cnt_sum, step_cnt), None

        epoch_keys = jax.random.split(rng, epochs)
        (params, other, opt_state, loss_sum, cnt_sum, step_cnt), _ = jax.lax.scan(
            epoch_body, (params, other, opt_state, 0.0, 0.0, 0.0), epoch_keys
        )
        out_vars = dict(other, params=params)
        return LocalTrainResult(
            out_vars, loss_sum / jnp.maximum(cnt_sum, 1.0), cnt_sum, step_cnt
        )

    return train


def make_eval_fn(module) -> Callable:
    """Jitted masked eval: ``(variables, x, y, mask) -> (loss_sum, correct, count)``."""

    @jax.jit
    def evaluate(variables, x, y, mask):
        logits = module.apply(variables, x, train=False).astype(jnp.float32)
        per = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        pred = jnp.argmax(logits, axis=-1)
        mask = mask.astype(jnp.float32)
        mask = mask.reshape(mask.shape + (1,) * (per.ndim - mask.ndim))
        full = jnp.broadcast_to(mask, per.shape)
        return (
            jnp.sum(per * full),
            jnp.sum((pred == y).astype(jnp.float32) * full),
            jnp.sum(full),
        )

    return evaluate


def pad_to(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """Pad axis 0 to length n (repeat-edge padding keeps dtypes/shapes sane)."""
    if x.shape[0] >= n:
        return x[:n]
    pad = [(0, n - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad, mode="edge")


def init_variables(module, sample_input: jnp.ndarray, seed: int = 0) -> Pytree:
    variables = module.init(jax.random.PRNGKey(seed), sample_input, train=False)
    return dict(variables)
