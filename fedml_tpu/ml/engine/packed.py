"""Packed ragged-client round: eliminate per-client padding waste.

The default in-mesh round pads EVERY client to the global max client size
(fed_sim._pack_data), so with Dirichlet-skewed clients ~half the compute is
padding (measured ~49% on the bench partition).  Per-step cost on TPU is
essentially independent of which client a batch belongs to, so this module
re-lays the round as ONE stream of batches per device:

* each client contributes ceil(n_i/B) batches per epoch (its own padding is
  at most B-1 samples), clients back-to-back;
* a ``lax.while_loop`` walks the stream: ordinary SGD steps, and at each
  client BOUNDARY the carry flushes (weighted accumulation + algorithm
  contributions + per-slot outputs) and resets params/optimizer to the
  round-start state;
* the loop trip count is a TRACED scalar (different per device and per
  round) over statically-shaped index buffers sized for the worst case —
  no recompile when the sampled client sizes change, and devices stop after
  their own last real step.

Shuffling is host-side (numpy, seeded per (round, client, epoch)) since the
batch order IS the data layout here; the device no longer permutes.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .train import LocalTrainResult, build_loss_fn, make_optimizer, resolve_grad_hook

Pytree = Any


class PackedSchedule(NamedTuple):
    """Per-device packed batch stream (leading axis n_dev, then S_max)."""

    idx: np.ndarray       # [n_dev, S_max, B] int32 rows into x_all/y_all
    mask: np.ndarray      # [n_dev, S_max, B] f32 valid-sample mask
    boundary: np.ndarray  # [n_dev, S_max] f32 1.0 on a client's last step
    weight: np.ndarray    # [n_dev, S_max] f32 client sample count (at boundary)
    slot: np.ndarray      # [n_dev, S_max] i32 schedule-slot of the running client
    n_steps: np.ndarray   # [n_dev] i32 real steps this round


def pack_round(
    ids2d: np.ndarray,
    counts2d: np.ndarray,
    client_rows: Callable[[int], np.ndarray],
    batch_size: int,
    epochs: int,
    seed: int,
    round_idx: int,
    s_max: int,
) -> PackedSchedule:
    """Build the packed stream for one round.

    ``ids2d``/``counts2d``: [n_dev, slots] scheduled client ids and their
    real sample counts (0 = dummy slot).  ``client_rows(cid)`` returns the
    client's row indices into the global data arrays.  Slot numbering is
    DEVICE-LOCAL (the cex/outs arrays are sharded over the client axis, so
    each device sees its own [slots, ...] shard).
    """
    n_dev, slots = ids2d.shape
    B = batch_size
    idx = np.zeros((n_dev, s_max, B), np.int32)
    mask = np.zeros((n_dev, s_max, B), np.float32)
    boundary = np.zeros((n_dev, s_max), np.float32)
    weight = np.zeros((n_dev, s_max), np.float32)
    slot = np.zeros((n_dev, s_max), np.int32)
    n_steps = np.zeros((n_dev,), np.int32)
    for d in range(n_dev):
        cursor = 0
        for ls in range(slots):
            n_i = int(counts2d[d, ls])
            if n_i <= 0:
                continue
            cid = int(ids2d[d, ls])
            rows = np.asarray(client_rows(cid))[:n_i]
            steps_per_epoch = -(-n_i // B)
            total = steps_per_epoch * epochs
            if cursor + total > s_max:
                raise ValueError(
                    f"packed stream overflow: device {d} needs {cursor + total} "
                    f"steps > s_max {s_max}"
                )
            for e in range(epochs):
                rng = np.random.default_rng((seed, round_idx, cid, e))
                perm = rng.permutation(rows)
                padded = np.resize(perm, steps_per_epoch * B)
                m = np.zeros(steps_per_epoch * B, np.float32)
                m[:n_i] = 1.0
                sl = np.s_[cursor : cursor + steps_per_epoch]
                idx[d, sl] = padded.reshape(steps_per_epoch, B)
                mask[d, sl] = m.reshape(steps_per_epoch, B)
                slot[d, sl] = ls
                cursor += steps_per_epoch
            boundary[d, cursor - 1] = 1.0
            weight[d, cursor - 1] = float(n_i)
        n_steps[d] = cursor
    return PackedSchedule(idx, mask, boundary, weight, slot, n_steps)


def s_max_for(max_client_n: int, slots: int, batch_size: int, epochs: int) -> int:
    """Static worst-case stream length per device (buffer size only — the
    traced trip count is the real length)."""
    return slots * (-(-max_client_n // batch_size)) * epochs


def build_packed_device_fn(
    module,
    args,
    algo,
    batch_size: int,
    slots_per_device: int,
    has_dropout: bool = True,
    loss: str = "ce",
    pregather: bool = False,
    stream: str = "while",
    post_train=None,
    capture_updates: bool = False,
):
    """The per-device round body (composed under shard_map by the simulator).

    Returns ``fn(variables, server_state, x_all, y_all, idx, mask, boundary,
    weight, slot, n_steps, rng, cex) -> (acc, wsum, lsum, cnt, ext, outs)``
    where cex has leading axis slots_per_device and outs matches it.

    ``capture_updates``: also record each slot's final (post-``post_train``)
    variables into the per-slot output buffer — ``outs`` becomes
    ``{"algo": <algo outs>, "update": <variables tree, leading slot axis>}``.
    The security layer (stacked attacks / robust aggregation) consumes this
    stack instead of the in-stream weighted sum.
    """
    tx = make_optimizer(args)
    grad_hook = resolve_grad_hook(args, algo.grad_hook())
    loss_and_updated = build_loss_fn(module, has_dropout, loss)

    from ...simulation.xla.algorithms import InMeshAlgorithm

    uses_extra = type(algo).engine_extra is not InMeshAlgorithm.engine_extra

    def device_fn(variables, server_state, x_all, y_all, idx, mask, boundary,
                  weight, slot, n_steps, rng, cex):
        if pregather:
            # ONE vectorized gather for the whole round's stream (TPU row
            # gathers are slow per-step; a single [S*B]-row gather amortizes
            # to streaming HBM bandwidth), then the loop reads contiguous
            # slices.  HBM cost: S_bucket * B * sample (the simulator trims
            # S to a power-of-two bucket of the round's real step count).
            bx_stream = jnp.take(x_all, idx.reshape(-1), axis=0).reshape(
                idx.shape + x_all.shape[1:]
            )
            by_stream = jnp.take(y_all, idx.reshape(-1), axis=0).reshape(
                idx.shape + y_all.shape[1:]
            )
        params0 = variables["params"]
        other0 = {k: v for k, v in variables.items() if k != "params"}
        opt0 = tx.init(params0)
        # where-masking of all-padding steps is only needed when state would
        # drift without it (stateful optimizer / mutable collections); plain
        # SGD takes zero-grad no-op steps for free.  The scan stream runs the
        # bucketed tail (step >= n_steps) as real iterations, and a grad hook
        # (FedProx pull, SCAFFOLD correction) is nonzero even on zero grads —
        # so scan always takes the masked path.
        scanning = stream == "scan"
        stateless = (not jax.tree_util.tree_leaves(opt0) and not other0
                     and not (scanning and grad_hook is not None))

        zeros_vars = jax.tree_util.tree_map(
            lambda v: jnp.zeros_like(v, jnp.float32), variables
        )
        ext0 = algo.zero_contrib(variables)
        out_t = algo.out_template(variables)
        if capture_updates:
            # "tau": the engine's per-client step count, captured so the
            # security tail can recompute ext contributions (FedNova's tau_i)
            # from the defended stack without re-deriving step semantics
            out_t = {"algo": out_t, "update": variables, "tau": jnp.zeros(())}
        outs0 = jax.tree_util.tree_map(
            lambda t: jnp.zeros((slots_per_device,) + t.shape, jnp.float32), out_t
        )

        def body(carry):
            (step, params, other, opt_state, c_steps, c_loss, c_cnt,
             acc, wsum, lsum, cnt, ext, outs) = carry
            if pregather:
                bx, by = bx_stream[step], by_stream[step]
            else:
                bx = jnp.take(x_all, idx[step], axis=0)
                by = jnp.take(y_all, idx[step], axis=0)
            bmask = mask[step]
            key = jax.random.fold_in(rng, step)
            (lval, updated), grads = jax.value_and_grad(
                loss_and_updated, has_aux=True
            )(params, other, bx, by, bmask, key)
            if grad_hook is not None:
                s = slot[step]  # device-local schedule slot
                extra = None
                if uses_extra:
                    cex_i = jax.tree_util.tree_map(
                        lambda t: jax.lax.dynamic_index_in_dim(t, s, keepdims=False),
                        cex,
                    )
                    extra = algo.engine_extra(cex_i, server_state)
                grads = grad_hook(grads, params, params0, extra)
            updates, new_opt = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if stateless:
                params, opt_state = new_params, new_opt
                if updated:
                    other = updated
            else:
                any_valid = jnp.sum(bmask) > 0
                params = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(any_valid, n, o), new_params, params)
                opt_state = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(any_valid, n, o), new_opt, opt_state)
                if updated:
                    other = jax.tree_util.tree_map(
                        lambda n, o: jnp.where(any_valid, n, o), updated, other)
            c_steps = c_steps + (jnp.sum(bmask) > 0).astype(jnp.float32)
            c_loss = c_loss + lval * jnp.sum(bmask)
            c_cnt = c_cnt + jnp.sum(bmask)

            def flush(ops):
                (params, other, opt_state, c_steps, c_loss, c_cnt,
                 acc, wsum, lsum, cnt, ext, outs) = ops
                w = weight[step]
                real = (w > 0).astype(jnp.float32)
                out_vars = dict(other, params=params)
                if post_train is not None:
                    # in-mesh local DP: noise this client's update at its
                    # boundary, keyed by (device rng, stream position)
                    out_vars = post_train(
                        out_vars, jax.random.fold_in(rng, step + 104729)
                    )
                result = LocalTrainResult(
                    out_vars,
                    c_loss / jnp.maximum(c_cnt, 1.0),
                    c_cnt,
                    c_steps,
                )
                s = slot[step]
                # cex feeds client_contrib/client_out for ALL algorithms
                # (uses_extra only gates the grad-hook extra, not this)
                cex_i = jax.tree_util.tree_map(
                    lambda t: jax.lax.dynamic_index_in_dim(t, s, keepdims=False), cex
                )
                acc = jax.tree_util.tree_map(
                    lambda a, p: a + w * p.astype(jnp.float32), acc, out_vars
                )
                ext = jax.tree_util.tree_map(
                    jnp.add, ext,
                    algo.client_contrib(variables, result, w, real, cex_i, server_state),
                )
                out_i = algo.client_out(variables, result, real, cex_i, server_state)
                if capture_updates:
                    out_i = {"algo": out_i, "update": out_vars, "tau": c_steps}
                outs = jax.tree_util.tree_map(
                    lambda buf, o: jax.lax.dynamic_update_index_in_dim(
                        buf, o.astype(jnp.float32), s, axis=0
                    ),
                    outs, out_i,
                )
                return (params0, other0, opt0, 0.0, 0.0, 0.0,
                        acc, wsum + w, lsum + c_loss, cnt + c_cnt, ext, outs)

            def keep(ops):
                return ops

            (params, other, opt_state, c_steps, c_loss, c_cnt,
             acc, wsum, lsum, cnt, ext, outs) = jax.lax.cond(
                boundary[step] > 0, flush, keep,
                (params, other, opt_state, c_steps, c_loss, c_cnt,
                 acc, wsum, lsum, cnt, ext, outs),
            )
            return (step + 1, params, other, opt_state, c_steps, c_loss, c_cnt,
                    acc, wsum, lsum, cnt, ext, outs)

        init = (jnp.int32(0), params0, other0, opt0, 0.0, 0.0, 0.0,
                zeros_vars, 0.0, 0.0, 0.0, ext0, outs0)
        if scanning:
            # static-length scan over the bucketed stream: XLA can pipeline
            # iterations (no traced trip count); tail steps beyond n_steps
            # carry all-zero masks so they are exact no-ops
            def scan_body(carry, step):
                return body((step,) + carry)[1:], None

            final, _ = jax.lax.scan(
                scan_body, init[1:], jnp.arange(idx.shape[0], dtype=jnp.int32)
            )
            (_, _, _, _, _, _, acc, wsum, lsum, cnt, ext, outs) = final
        else:
            final = jax.lax.while_loop(lambda c: c[0] < n_steps, body, init)
            (_, _, _, _, _, _, _, acc, wsum, lsum, cnt, ext, outs) = final
        return acc, wsum, lsum, cnt, ext, outs

    return device_fn
