"""Backend-selection helpers for environments that pin a TPU backend.

The deployment environment registers a tunneled-TPU ("axon") jax backend in
every Python process via sitecustomize, so ``JAX_PLATFORMS=cpu`` alone is not
enough to keep unit tests / dry runs off the TPU: the factory must also be
deregistered before first backend use (its PJRT init can block the process).
Shared by ``tests/conftest.py`` and ``__graft_entry__._dryrun_impl``.
"""

from __future__ import annotations


def force_cpu_backend() -> None:
    """Force jax onto the host-CPU backend even if a TPU factory is registered.

    Must run before jax initializes a backend.  Device COUNT
    (``--xla_force_host_platform_device_count``) must still be set via
    ``XLA_FLAGS`` in the environment before the jax import.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:  # pragma: no cover - jax internals may move
        pass
