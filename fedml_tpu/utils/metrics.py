"""Minimal metrics sink used by runtimes.

Stands in for the reference's ``mlops.log`` → MQTT/wandb fan-out
(``core/mlops/__init__.py:152``): appends JSON lines to
``tracking_args.log_file_dir`` and mirrors to python logging.  The full MLOps
event bus lives in fedml_tpu/core/mlops/.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("fedml_tpu.metrics")


class MetricsLogger:
    def __init__(self, args: Any = None):
        self.run_id = str(getattr(args, "run_id", "0")) if args is not None else "0"
        log_dir = getattr(args, "log_file_dir", None) if args is not None else None
        self._fh = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._path = os.path.join(log_dir, f"metrics_{self.run_id}.jsonl")
            self._fh = open(self._path, "a")

    def log(self, metrics: Dict[str, Any], step: Optional[int] = None) -> None:
        rec = dict(metrics)
        rec.setdefault("ts", round(time.time(), 3))
        if step is not None:
            rec.setdefault("step", step)
        logger.info("%s", rec)
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
