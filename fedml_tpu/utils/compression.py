"""Import-path parity with reference ``fedml/utils/compression.py``: the
compressor set lives in :mod:`fedml_tpu.core.compression` (functional,
pytree-level); this module re-exports it under the reference's path."""

from ..core.compression import (  # noqa: F401
    compress_update,
    decompress_update,
    is_compressed,
    maybe_decompress_update,
    qsgd_leaf,
    quantize_leaf,
    topk_leaf,
)
