"""One-line cross-device server launcher (reference ``launch_cross_device.py``
``run_mnn_server``)."""

from __future__ import annotations


def run_device_server():
    from fedml_tpu.constants import FEDML_TRAINING_PLATFORM_CROSS_DEVICE
    from fedml_tpu.launch_cross_silo import launch

    return launch(FEDML_TRAINING_PLATFORM_CROSS_DEVICE, role="server")
