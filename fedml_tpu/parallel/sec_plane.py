"""Compiled defense & privacy stages for the sharded round plane.

The seed's threat-model stack (``core/security`` robust aggregation,
``core/dp`` noise, ``core/mpc`` SecAgg) is host-side Python: per-update
loops and ``tree_map`` walks whose cost scales with Python object overhead.
This module restates the subset that belongs on the round path as PURE jnp
stage functions over the stacked client-delta chunk, shared VERBATIM by

* the fused round program (:class:`~fedml_tpu.parallel.agg_plane.
  ShardedRoundPlane` inserts them as pre-reduce stages, pinned off the fold
  by ``optimization_barrier``), and
* the retained host oracle (:func:`host_secure_round_update` — the same
  stage/fold/tail functions as three separately-jitted programs),

so "compiled == host" is a bitwise contract, not a tolerance.

Stage order is DP first (per-client clip + counter-keyed noise — local DP
happens before anyone aggregates), then the defense filter.  Inside the
fused program the stage runs on a REPLICATED copy of the chunk
(``with_sharding_constraint``): the cross-coordinate reductions (row norms,
Krum's pairwise-distance matmul) must not be split across the model axis,
where GSPMD's partial-sum order would break bit-exactness against the
oracle.  The elementwise fold that follows stays model-sharded.

DP noise is a COUNTER-BASED stream: ``fold_in(fold_in(key(seed), round),
client_id)`` — a pure function of (seed, round, client), so replaying a
round, resuming from a checkpoint, or shrinking the mesh 4→2 regenerates
identical noise.  The split-threaded stream in
:mod:`fedml_tpu.core.dp.fedml_differential_privacy` stays for the host
mechanisms; the accountant still drives the scale (``sigma`` is a RUNTIME
scalar input, never part of the program cache key).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

#: per-stage placement knobs on the agg plane
SEC_PLANES = ("host", "compiled")

#: defenses with an in-mesh (stacked, pre-reduce) form on the round plane
PLANE_DEFENSES = ("krum", "multi_krum", "norm_diff_clipping",
                  "coordinate_wise_trimmed_mean")


# ---------------------------------------------------------------------------
# knob + spec resolution (specs are hashable — they key the program cache)
# ---------------------------------------------------------------------------
def stage_plane(args: Any, knob: str) -> str:
    v = str(getattr(args, knob, "host") or "host").lower()
    if v not in SEC_PLANES:
        raise ValueError(f"{knob} must be one of {SEC_PLANES} (got {v!r})")
    return v


def defense_spec(args: Any) -> Optional[Tuple]:
    """Hashable defense-stage spec for the enabled defense, or None when no
    defense is enabled.  Raises when the enabled defense has no in-mesh
    form — the caller asked for ``defense_plane=compiled`` and silently
    running undefended would be a security hole, not a degrade."""
    if not bool(getattr(args, "enable_defense", False)):
        return None
    t = str(getattr(args, "defense_type", "") or "")
    if t == "norm_diff_clipping":
        return ("norm_clip", float(getattr(args, "norm_bound", 5.0)))
    if t == "coordinate_wise_trimmed_mean":
        return ("trimmed_mean", float(getattr(args, "beta", 0.1)))
    if t in ("krum", "multi_krum"):
        byz = int(getattr(args, "byzantine_client_num", 1))
        m = (max(int(getattr(args, "krum_param_m", 1)), 1)
             if t == "multi_krum" else 1)
        return ("krum", byz, m)
    raise ValueError(
        f"defense_type {t!r} has no compiled (in-mesh) stage; supported: "
        f"{PLANE_DEFENSES} — set defense_plane=host for the others")


def dp_spec(args: Any) -> Optional[Tuple]:
    """Hashable DP-stage spec (mechanism, clip, seed), or None when DP is
    off.  The noise SCALE is deliberately absent: sigma is a runtime scalar
    the budget accountant drives per round, so budget decay never forces a
    recompile."""
    if not bool(getattr(args, "enable_dp", False)):
        return None
    mech = str(getattr(args, "mechanism_type", "gaussian") or "gaussian").lower()
    if mech not in ("gaussian", "laplace"):
        raise ValueError(f"unknown DP mechanism: {mech!r}")
    clip = float(getattr(args, "sensitivity", 1.0))
    seed = int(getattr(args, "random_seed", 0))
    return (mech, clip, seed)


def plane_security(args: Any) -> Tuple[Optional[Tuple], Optional[Tuple]]:
    """(defense, dp) stage specs for the round plane — each stage rides the
    compiled path only when its knob opts in; ``host`` keeps the existing
    host hooks authoritative."""
    d = defense_spec(args) if stage_plane(args, "defense_plane") == "compiled" else None
    p = dp_spec(args) if stage_plane(args, "dp_plane") == "compiled" else None
    return d, p


def dp_runtime_sigma(args: Any) -> float:
    """This round's noise scale from the mechanism formulas (the budget
    accountant gates whether the round may spend at all; the scale itself
    is the classic calibration)."""
    spec = dp_spec(args)
    if spec is None:
        return 0.0
    mech, clip, _ = spec
    eps = float(getattr(args, "epsilon", 1.0))
    if mech == "gaussian":
        from ..core.dp.mechanisms import Gaussian
        return Gaussian.compute_sigma(eps, float(getattr(args, "delta", 1e-5)), clip)
    return clip / eps  # laplace scale


# ---------------------------------------------------------------------------
# the shared fold / tail (agg_plane builds its fused program from THESE, the
# host oracle jits the same closures standalone — one definition, two paths)
# ---------------------------------------------------------------------------
def make_fold_fn(mode: str):
    """Left-to-right scan fold of the (k, ...) chunk into the accumulator.
    ``mean`` scales the whole chunk BEFORE the scan: the product must
    materialize at the while-loop boundary so it rounds to f32 exactly like
    the host path's ``tree_scale`` — inside the loop body LLVM would
    contract ``a + v*w`` into an fma and break bit-exactness."""

    def fold(acc, chunk, w):
        if mode == "mean":
            chunk = [c.astype(a.dtype)
                     * w.reshape((-1,) + (1,) * (c.ndim - 1)).astype(a.dtype)
                     for a, c in zip(acc, chunk)]

        def body(carry, x):
            return [a + v.astype(a.dtype)
                    for a, v in zip(carry, x)], None

        acc, _ = jax.lax.scan(body, acc, chunk)
        return acc

    return fold


def make_tail_fn(tx, opt_idx: Sequence[int], out_dtypes: Sequence[Any]):
    """Server-optimizer tail over the reduced accumulator: cast to the host
    output dtypes, pseudo-gradient = params − aggregate over the optimizer
    leaves, one optax update, scatter back."""

    def tail(params, opt_state, acc):
        out = [a.astype(dt) if a.dtype != dt else a
               for a, dt in zip(acc, out_dtypes)]
        if tx is None:
            return out, opt_state
        import optax
        opt_params = [params[i].astype(out_dtypes[i]) for i in opt_idx]
        pseudo_grad = [p - a for p, a in
                       zip(opt_params, [out[i] for i in opt_idx])]
        updates, new_state = tx.update(pseudo_grad, opt_state, opt_params)
        stepped = optax.apply_updates(opt_params, updates)
        new = list(out)
        for i, v in zip(opt_idx, stepped):
            new[i] = v
        return new, new_state

    return tail


# ---------------------------------------------------------------------------
# the pre-reduce stage: DP then defense, over the stacked chunk
# ---------------------------------------------------------------------------
def make_stage_fn(defense: Optional[Tuple], dp: Optional[Tuple], mode: str,
                  n: int):
    """-> ``stage(chunk, w, params, round_idx, client_ids, sigma) ->
    (chunk', w', rejected)``, pure jnp over the per-leaf chunk lists.

    ``n`` is the STATIC number of client rows and must equal the chunk's
    leading dim: the stage forbids zero-padded rows (a sort/median defense
    would rank padding as the consensus), so the plane always runs the
    staged program fused at ``k == n``.

    Selection semantics per aggregation mode: ``mean`` rejects clients
    through the weight vector (zero + renormalize — exactly the surviving
    clients' ``n_i / N_surviving``); ``sum`` zeroes the rejected rows
    (sum-mode folds never read weights).  Aggregate-replacing defenses
    (trimmed mean) broadcast their consensus into row 0 with a one-hot
    weight, which the fold reproduces exactly (``t * 1.0 == t``).
    """
    from ..core.security.defense_funcs import krum_scores

    def stage(chunk, w, params, round_idx, client_ids, sigma):
        k = chunk[0].shape[0]
        G = jnp.concatenate(
            [c.reshape(k, -1).astype(jnp.float32) for c in chunk], axis=1)
        p_vec = jnp.concatenate(
            [p.reshape(-1).astype(jnp.float32) for p in params])
        rejected = jnp.zeros((), jnp.float32)
        if dp is not None:
            mech, clip, seed = dp
            delta = G - p_vec[None, :]
            nrm = jnp.linalg.norm(delta, axis=1, keepdims=True)
            delta = delta * jnp.minimum(1.0, clip / jnp.maximum(nrm, 1e-12))
            # counter-based keys: a pure function of (seed, round, client) —
            # seed-deterministic and replay/remesh-stable by construction
            base = jax.random.fold_in(
                jax.random.PRNGKey(seed), round_idx.astype(jnp.uint32))
            keys = jax.vmap(
                lambda c: jax.random.fold_in(base, c.astype(jnp.uint32))
            )(client_ids)
            sample = (jax.random.normal if mech == "gaussian"
                      else jax.random.laplace)
            noise = jax.vmap(
                lambda key: sample(key, (G.shape[1],), jnp.float32))(keys)
            G = p_vec[None, :] + delta + sigma.astype(jnp.float32) * noise
        if defense is not None:
            kind = defense[0]
            if kind == "norm_clip":
                bound = defense[1]
                diff = G - p_vec[None, :]
                nrm = jnp.linalg.norm(diff, axis=1, keepdims=True)
                G = p_vec[None, :] + diff * jnp.minimum(
                    1.0, bound / jnp.maximum(nrm, 1e-12))
            elif kind == "krum":
                byz, m = defense[1], defense[2]
                # pairwise distances over the clients axis are ONE matmul
                # (krum_scores: ||xi||^2 + ||xj||^2 - 2 xi.xj)
                scores = krum_scores(G, byz)
                chosen = jnp.argsort(scores)[:m]
                sel = jnp.zeros((k,), jnp.float32).at[chosen].set(1.0)
                rejected = jnp.asarray(k, jnp.float32) - jnp.sum(sel)
                if mode == "mean":
                    ws = w * sel
                    w = ws / jnp.sum(ws)
                else:
                    G = G * sel[:, None]
            elif kind == "trimmed_mean":
                beta = defense[1]
                kk = max(0, min(int(n * float(beta)), (n - 1) // 2))
                srt = jnp.sort(G, axis=0)
                t = jnp.mean(srt[kk: n - kk], axis=0)
                G = jnp.zeros_like(G).at[0].set(t)
                w = jnp.zeros((k,), jnp.float32).at[0].set(1.0)
                rejected = jnp.asarray(2 * kk, jnp.float32)
            else:
                raise ValueError(f"unknown defense stage {kind!r}")
        out, off = [], 0
        for c in chunk:
            size = int(np.prod(c.shape[1:]) or 1)
            out.append(G[:, off:off + size].reshape(c.shape).astype(c.dtype))
            off += size
        return out, w, rejected

    return stage


# ---------------------------------------------------------------------------
# the retained host oracle
# ---------------------------------------------------------------------------
_HOST_PROGRAMS: Dict[Any, Any] = {}


def host_secure_round_update(params_tree: Pytree,
                             updates: Sequence[Tuple[float, Pytree]],
                             mode: str = "mean",
                             policy: Tuple = ("fedavg",),
                             opt_state: Any = None,
                             defense: Optional[Tuple] = None,
                             dp: Optional[Tuple] = None,
                             round_idx: int = 0,
                             client_ids: Optional[np.ndarray] = None,
                             dp_sigma: float = 0.0):
    """Host-path round update with the security stages applied: the SAME
    stage/fold/tail closures the fused round program traces, run as three
    separately-jitted host programs (stage → materialize → fold →
    materialize → tail — the boundaries the plane pins with
    ``optimization_barrier``).  Bit-exact reference for
    :meth:`~fedml_tpu.parallel.agg_plane.ShardedRoundPlane.round_update`
    with stages active; with ``defense=dp=None`` it reduces to the plain
    stage-free fold + tail.

    Returns ``(new_global_tree, new_opt_state, rejected_clients)``.
    """
    from ..core.aggregate import flatten_checked, leaf_paths, opt_leaf_indices
    from ..parallel.agg_plane import _policy_tx

    if mode not in ("mean", "sum"):
        raise ValueError(f"agg mode must be mean|sum (got {mode!r})")
    if not updates:
        raise ValueError("no updates to aggregate")
    ns = [float(x) for x, _ in updates]
    leaves_list, treedef = flatten_checked([t for _, t in updates])
    n = len(leaves_list)
    p_leaves, p_treedef = jax.tree_util.tree_flatten(params_tree)
    if p_treedef != treedef:
        raise ValueError(
            f"global params structure {p_treedef} differs from the client "
            f"updates {treedef}")
    if mode == "mean":
        total = float(sum(ns))
        if total <= 0:
            raise ValueError("total sample count must be positive")
        w_all = np.asarray([x / total for x in ns], np.float32)
    else:
        w_all = np.ones(n, np.float32)
    shapes = tuple(tuple(np.shape(l)) for l in leaves_list[0])
    upd_dtypes = tuple(jnp.dtype(jnp.result_type(l)) for l in leaves_list[0])
    param_dtypes = tuple(jnp.dtype(jnp.result_type(l)) for l in p_leaves)
    names = leaf_paths(treedef)
    tx = _policy_tx(tuple(policy))
    opt_idx = tuple(opt_leaf_indices(names, param_dtypes)) if tx is not None else ()
    # the plane's _leaf_plan dtype policy, host-side: floats accumulate f32
    # and keep their dtype; ints accumulate/stay integer under sum and
    # promote to f32 under mean
    acc_dtypes, out_dtypes = [], []
    for dt in upd_dtypes:
        if jnp.issubdtype(dt, jnp.floating):
            acc_dtypes.append(jnp.dtype(jnp.float32))
            out_dtypes.append(dt)
        elif mode == "sum":
            acc_dtypes.append(dt)
            out_dtypes.append(dt)
        else:
            acc_dtypes.append(jnp.dtype(jnp.float32))
            out_dtypes.append(jnp.dtype(jnp.float32))

    key = (treedef, shapes, upd_dtypes, param_dtypes, opt_idx, n, mode,
           tuple(policy), defense, dp)
    progs = _HOST_PROGRAMS.get(key)  # fedlint: allow[mesh-stale-program] — host oracle programs are unsharded plain jit; there is no mesh identity to key on
    if progs is None:
        stage = (jax.jit(make_stage_fn(defense, dp, mode, n))
                 if (defense is not None or dp is not None) else None)
        fold = jax.jit(make_fold_fn(mode))
        tail = jax.jit(make_tail_fn(tx, opt_idx, out_dtypes))
        progs = (stage, fold, tail)
        _HOST_PROGRAMS[key] = progs
    stage, fold, tail = progs

    chunk = [np.stack([np.asarray(leaves_list[c][j]) for c in range(n)])
             for j in range(len(shapes))]
    w = jnp.asarray(w_all)
    rejected = 0.0
    if stage is not None:
        ids = (np.arange(n, dtype=np.int32) if client_ids is None
               else np.asarray(client_ids, np.int32))
        chunk, w, rej = stage(
            [jnp.asarray(c) for c in chunk], w,
            [jnp.asarray(np.asarray(l)) for l in p_leaves],
            jnp.asarray(int(round_idx), jnp.int32), jnp.asarray(ids),
            jnp.asarray(float(dp_sigma), jnp.float32))
        rejected = float(rej)
    zeros = [jnp.zeros(sh, dt) for sh, dt in zip(shapes, acc_dtypes)]
    acc = fold(zeros, [jnp.asarray(c) for c in chunk], w)
    if tx is not None and opt_state is None:
        opt_state = tx.init([jnp.asarray(np.asarray(p_leaves[i]))
                             .astype(out_dtypes[i]) for i in opt_idx])
    new_leaves, new_opt = tail(
        [jnp.asarray(np.asarray(l)) for l in p_leaves], opt_state, acc)
    return (jax.tree_util.tree_unflatten(
        treedef, [np.asarray(x) for x in new_leaves]), new_opt, rejected)


def reset_host_programs() -> None:
    """Drop the cached host-oracle programs (tests)."""
    _HOST_PROGRAMS.clear()
