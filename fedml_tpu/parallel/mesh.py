"""Device-mesh helpers.

TPU-native successor of the reference's process-group plumbing
(``ml/engine/torch_process_group_manager.py``, NCCL/gloo init in
``simulation/nccl/base_framework/common.py:106-122``): on TPU there is no
process group to boot — a ``jax.sharding.Mesh`` over ``jax.devices()`` is the
communicator, and XLA compiles the collectives onto ICI.

Axis conventions (constants.py): client / dp / fsdp / tp / sp / pp / ep.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


#: Deterministic device-visibility shim: ``None`` = every device the runtime
#: reports; otherwise the ids that survive fault injection (``mesh_shrink`` /
#: ``device_loss``) or precede an elastic restart.  Mesh builders route
#: through :func:`visible_devices` so a topology change is observed the next
#: time a mesh is constructed — no process restart required.
_VISIBLE_IDS: Optional[Tuple[int, ...]] = None


def set_visible_devices(ids: Optional[Sequence[int]] = None) -> None:
    """Restrict (or with ``None`` restore) the device set that
    :func:`visible_devices` reports.  The shim is process-global and
    deterministic — fault injection and tests drive elastic topology
    changes through it instead of needing real chip loss."""
    global _VISIBLE_IDS
    if ids is None:
        _VISIBLE_IDS = None
        return
    ids = tuple(sorted({int(i) for i in ids}))
    if not ids:
        raise ValueError("visible device set must be non-empty (pass None "
                         "to restore full visibility)")
    _VISIBLE_IDS = ids


def visible_devices(
        devices: Optional[Sequence[jax.Device]] = None) -> list:
    """The currently-live devices: ``devices`` (default ``jax.devices()``)
    filtered through :func:`set_visible_devices`.  Falls back to the first
    device when the visible set and the runtime's devices are disjoint —
    a server with one chip left degrades, it does not crash."""
    devices = list(devices if devices is not None else jax.devices())
    if _VISIBLE_IDS is None:
        return devices
    allowed = set(_VISIBLE_IDS)
    vis = [d for d in devices if int(d.id) in allowed]
    return vis if vis else devices[:1]


def create_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(axis_sizes))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    dev_array = mesh_utils.create_device_mesh(tuple(axis_sizes), devices=devices[:n])
    return Mesh(dev_array, tuple(axis_names))


def create_fl_mesh(n_devices: Optional[int] = None,
                   devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over the 'client' axis — the Parrot-XLA simulator's layout."""
    devices = list(devices if devices is not None else jax.devices())
    n = int(n_devices or len(devices))
    return create_mesh((n,), ("client",), devices)


def create_train_mesh(dp: int = 1, tp: int = 1, sp: int = 1,
                      devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """dp x tp x sp mesh for the distributed trainer ("Cheetah" successor)."""
    return create_mesh((dp, tp, sp), ("dp", "tp", "sp"), devices)


def create_round_mesh(clients: int = 1, model: Optional[int] = None,
                      devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """2-D ``(client, model)`` mesh for the sharded round update: client
    deltas reduce along ``client`` while global params and server-optimizer
    state shard along ``model`` (the cross-replica weight-update sharding of
    arxiv 2004.13336).  ``model`` defaults to all remaining devices."""
    devices = list(devices if devices is not None else jax.devices())
    clients = int(clients)
    if clients < 1:
        raise ValueError(f"client axis must be >= 1 (got {clients})")
    if model is None:
        model = max(1, len(devices) // clients)
    return create_mesh((clients, int(model)), ("client", "model"), devices)


def mesh_fingerprint(mesh: Mesh) -> Tuple[Tuple[str, int], ...]:
    """Hashable identity of a mesh: (axis name, size) pairs plus the flat
    device ids.  Two meshes with the same fingerprint compile to the same
    program; caching on anything less lets a rebuilt/changed mesh silently
    reuse programs compiled for the old device set."""
    axes = tuple((str(a), int(mesh.shape[a])) for a in mesh.axis_names)
    ids = tuple(int(d.id) for d in np.asarray(mesh.devices).flat)
    return axes + (("devices",) + ids,)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))
