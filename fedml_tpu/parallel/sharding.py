"""Parameter/batch sharding rules.

The TPU-native successor of DDP wrapping (reference
``ml/engine/ml_engine_adapter.py:273-281`` ``model_ddp``): instead of
wrapping a module, annotate each array with a ``NamedSharding`` and let XLA
insert the collectives.  Heuristic tensor-parallel rule: shard a parameter's
largest axis over ``tp`` when divisible (dense kernels [in, out] split out;
embeddings [vocab, d] split vocab); everything else replicates.  Batches
shard over ``dp``.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any


def param_spec(shape, tp: int, axis: str = "tp") -> P:
    """PartitionSpec for one parameter under the largest-divisible-axis
    heuristic.  ``axis`` names the mesh axis to shard over — ``tp`` for the
    trainer, ``model`` for the sharded round-update plane."""
    if len(shape) < 2 or tp <= 1:
        return P()
    dim = int(np.argmax(shape))
    if shape[dim] % tp != 0:
        return P()
    spec = [None] * len(shape)
    spec[dim] = axis
    return P(*spec)


def param_shardings(params: Pytree, mesh: Mesh) -> Pytree:
    """NamedSharding pytree for params over ``mesh`` (axes dp and/or tp)."""
    tp = int(mesh.shape.get("tp", 1))

    def rule(x):
        return NamedSharding(mesh, param_spec(np.shape(x), tp))

    return jax.tree_util.tree_map(rule, params)


def batch_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard axis 0 (batch) over dp, replicate the rest."""
    if "dp" in mesh.axis_names:
        return NamedSharding(mesh, P("dp", *([None] * (ndim - 1))))
    return NamedSharding(mesh, P())


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
