"""Compiled sharded aggregation plane: one-jit GSPMD reduction over client deltas.

The server's hottest loop — aggregating client updates — was per-client
host-side pytree arithmetic (``core/aggregate.py`` ``weighted_mean``), so its
cost scaled with Python object overhead and never touched the mesh this
package already builds.  This module rebuilds it as ONE compiled,
``NamedSharding``-annotated program over a device mesh, the cross-replica
sharding of the weight update from "Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training" (arxiv 2004.13336): every device
owns a shard of every parameter and reduces only its shard.

Shape of the plane:

* **Stacked deltas** — client updates are stacked on a leading axis
  (``core/aggregate.flatten_checked`` validates structure/shape first, with
  a clear error naming the offending client and leaf).
* **Partition rules** — per-leaf ``PartitionSpec``\\s come from regex rules
  matched against the ``/``-joined flattened param path (the
  ``match_partition_rules`` pattern, SNIPPETS [2]/[3]) with the
  ``parallel/sharding.py:param_spec`` largest-divisible-axis heuristic as
  fallback; scalars always replicate.
* **One jit, donated buffers** — the reduction is a single compiled
  ``lax.scan`` over the client axis folding each delta into a running
  accumulator.  The accumulator and the in-flight delta chunk are DONATED,
  so steady-state HBM is one model-size accumulator plus one chunk.
* **bf16 wire, f32 accumulate** — ``wire_dtype="bf16"`` halves host→device
  traffic; accumulation is always f32 (integer leaves accumulate in their
  own dtype under ``sum`` to mirror the host path).
* **Microbatching** — ``microbatch_clients=K`` folds K clients at a time
  into the accumulator, so 1k–10k deltas aggregate without ever
  materializing the full stack in HBM.

Bit-exactness contract (tier-1, CPU): in f32 mode the scan accumulates
left-to-right — multiply-by-weight then add, exactly the op sequence of the
host ``weighted_mean``/``unweighted_sum`` — so host and compiled paths agree
bitwise, microbatched or not.  (bf16 wire trades that for bandwidth; the
test suite pins its tolerance.)

Observability: the plane emits an ``aggregate.compile`` span per new
(treedef, shapes, K, mode) signature and an ``aggregate.reduce`` span per
aggregation — parented under the caller's ambient span (the server
managers' ``aggregate`` phase) so chaos traces stay single-rooted — plus
``agg.step_seconds`` / ``agg.bytes_reduced`` metrics.
"""

from __future__ import annotations

import logging
import re
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import obs
from ..core.aggregate import flatten_checked, leaf_paths
from ..core.obs.trace import NULL_SPAN
from .mesh import create_mesh
from .sharding import param_spec

logger = logging.getLogger(__name__)

Pytree = Any

AGG_PLANES = ("host", "compiled")
AGG_WIRE_DTYPES = ("f32", "bf16")

_WIRE_JNP = {"f32": jnp.float32, "bf16": jnp.bfloat16}


def default_agg_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D ``tp`` mesh over all devices: each device owns one shard of every
    (divisible) parameter and reduces only that shard — the weight-update
    analogue of data-parallel replicas splitting the update step."""
    devices = list(devices if devices is not None else jax.devices())
    return create_mesh((len(devices),), ("tp",), devices)


def match_partition_rules(rules: Sequence[Tuple[str, Any]], names: Sequence[str],
                          shapes: Sequence[Tuple[int, ...]], mesh: Mesh) -> List[P]:
    """Per-leaf ``PartitionSpec``: first regex in ``rules`` that matches the
    ``/``-joined param path wins; unmatched leaves fall back to the
    ``param_spec`` largest-divisible-axis heuristic; scalars (and size-1
    leaves) always replicate.  A rule naming a mesh axis that does not exist
    (or that does not divide the leaf) degrades to replication rather than
    failing the round — aggregation must work on any mesh."""
    tp = int(mesh.shape.get("tp", 1))
    out: List[P] = []
    for name, shape in zip(names, shapes):
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            out.append(P())
            continue
        spec = None
        for pat, ps in rules:
            if re.search(pat, name):
                spec = P(*ps) if not isinstance(ps, P) else ps
                break
        if spec is None:
            out.append(param_spec(shape, tp))
            continue
        out.append(_sanitize_spec(spec, shape, mesh))
    return out


def _sanitize_spec(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    if len(spec) > len(shape):
        return P()
    for dim, axis in zip(shape, spec):
        if axis is None:
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        size = 1
        for a in axes:
            if a not in mesh.shape:
                return P()
            size *= int(mesh.shape[a])
        if size > 1 and dim % size != 0:
            return P()
    return spec


def stacked_reduce(stacked: Pytree, weights: jnp.ndarray) -> Pytree:
    """Sequential in-mesh weighted reduction: fold ``stacked[i] * w_i`` into
    a f32 accumulator left-to-right via ``lax.scan``.  Pure and traceable —
    the XLA simulator's security tail uses it directly; the plane's compiled
    step is the chunked/donated version of the same loop.  Unlike the
    tensordot form, the fold order is the host path's, so results are
    bit-identical to ``weighted_mean`` given the same f32 weights."""
    zeros = jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape[1:], jnp.float32), stacked)

    def body(acc, xw):
        x, w = xw
        return jax.tree_util.tree_map(
            lambda a, v: a + v.astype(jnp.float32) * w, acc, x), None

    acc, _ = jax.lax.scan(body, zeros, (stacked, weights.astype(jnp.float32)))
    return acc


class _Program:
    """One compiled reduction: the AOT-compiled step plus the leaf plan."""

    __slots__ = ("step", "acc_shardings", "chunk_shardings", "acc_dtypes",
                 "wire_dtypes", "out_dtypes", "shapes", "wire_bytes",
                 "flops_per_step", "bytes_per_step")

    def __init__(self, step, acc_shardings, chunk_shardings, acc_dtypes,
                 wire_dtypes, out_dtypes, shapes, wire_bytes):
        self.step = step
        self.acc_shardings = acc_shardings
        self.chunk_shardings = chunk_shardings
        self.acc_dtypes = acc_dtypes
        self.wire_dtypes = wire_dtypes
        self.out_dtypes = out_dtypes
        self.shapes = shapes
        self.wire_bytes = wire_bytes
        self.flops_per_step = _compiled_cost(step, "flops")
        self.bytes_per_step = _compiled_cost(step, "bytes accessed")


def _compiled_cost(compiled: Any, key: str) -> float:
    """One key of XLA's per-program cost model (``Compiled.cost_analysis``:
    "flops", "bytes accessed", ...), 0.0 when the backend doesn't report it."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return float(ca.get(key, 0.0) or 0.0)
    except Exception:
        return 0.0


class CompiledAggPlane:
    """The compiled aggregation plane.

    ``aggregate(updates, mode)`` mirrors :func:`core.aggregate.weighted_mean`
    (``mode="mean"``) / :func:`core.aggregate.unweighted_sum`
    (``mode="sum"``) over ``[(n_samples, pytree), ...]`` but runs as one
    donated-buffer compiled program per microbatch chunk.

    Programs are cached per (treedef, leaf shapes/dtypes, K, mode): the
    first round at a new signature pays the XLA compile (visible as the
    ``aggregate.compile`` span); every later round reuses it.
    """

    def __init__(self, mesh: Optional[Mesh] = None,
                 rules: Sequence[Tuple[str, Any]] = (),
                 wire_dtype: str = "f32",
                 microbatch_clients: int = 0):
        if wire_dtype not in AGG_WIRE_DTYPES:
            raise ValueError(
                f"agg_wire_dtype must be one of {AGG_WIRE_DTYPES} (got {wire_dtype!r})")
        if int(microbatch_clients) < 0:
            raise ValueError(
                f"agg_microbatch_clients must be >= 0 (got {microbatch_clients})")
        self.mesh = mesh if mesh is not None else default_agg_mesh()
        self.rules = tuple(rules)
        self.wire_dtype = wire_dtype
        self.microbatch_clients = int(microbatch_clients)
        self._programs: Dict[Any, _Program] = {}

    # -- program construction ------------------------------------------------
    def _leaf_plan(self, treedef, shapes, dtypes, mode):
        names = leaf_paths(treedef)
        specs = match_partition_rules(self.rules, names, shapes, self.mesh)
        wire = _WIRE_JNP[self.wire_dtype]
        acc_dtypes, wire_dtypes, out_dtypes = [], [], []
        for dt in dtypes:
            dt = jnp.dtype(dt)
            if jnp.issubdtype(dt, jnp.floating):
                wire_dtypes.append(jnp.dtype(wire))
                acc_dtypes.append(jnp.dtype(jnp.float32))
                # host parity: mean keeps the input float dtype, sum too
                out_dtypes.append(dt)
            else:
                # integer leaves: no lossy wire cast; host sum stays integer
                # while host mean promotes to f32
                wire_dtypes.append(dt)
                if mode == "sum":
                    acc_dtypes.append(dt)
                    out_dtypes.append(dt)
                else:
                    acc_dtypes.append(jnp.dtype(jnp.float32))
                    out_dtypes.append(jnp.dtype(jnp.float32))
        return specs, acc_dtypes, wire_dtypes, out_dtypes

    def _build_program(self, treedef, shapes, dtypes, k, mode) -> _Program:
        specs, acc_dtypes, wire_dtypes, out_dtypes = self._leaf_plan(
            treedef, shapes, dtypes, mode)
        mesh = self.mesh
        acc_sh = [NamedSharding(mesh, s) for s in specs]
        chunk_sh = [NamedSharding(mesh, P(None, *s)) for s in specs]
        w_sh = NamedSharding(mesh, P())

        def step(acc, chunk, w):
            if mode == "mean":
                # scale the whole chunk BEFORE the scan: the product must
                # materialize at the while-loop boundary, so it rounds to
                # f32 exactly like the host path's tree_scale — inside the
                # loop body LLVM would contract a + v*w into an fma and
                # break bit-exactness
                chunk = [c.astype(a.dtype)
                         * w.reshape((-1,) + (1,) * (c.ndim - 1)).astype(a.dtype)
                         for a, c in zip(acc, chunk)]

            def body(carry, x):
                # padding rows are all-zero (rows AND weights), so adding
                # them is exact; host sum mode never multiplies, nor do we
                return [a + v.astype(a.dtype)
                        for a, v in zip(carry, x)], None

            acc, _ = jax.lax.scan(body, acc, chunk)
            return acc

        # acc and the in-flight chunk are donated: steady-state HBM is one
        # accumulator + one chunk regardless of client count
        jitted = jax.jit(step, donate_argnums=(0, 1),
                         in_shardings=(acc_sh, chunk_sh, w_sh),
                         out_shardings=acc_sh)
        acc_sds = [jax.ShapeDtypeStruct(sh, dt, sharding=s)
                   for sh, dt, s in zip(shapes, acc_dtypes, acc_sh)]
        chunk_sds = [jax.ShapeDtypeStruct((k,) + sh, dt, sharding=s)
                     for sh, dt, s in zip(shapes, wire_dtypes, chunk_sh)]
        w_sds = jax.ShapeDtypeStruct((k,), jnp.float32, sharding=w_sh)
        with warnings.catch_warnings():
            # donation is a no-op on CPU backends; the warning is expected
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            compiled = jitted.lower(acc_sds, chunk_sds, w_sds).compile()
        wire_bytes = int(sum(int(np.prod(sh) or 1) * jnp.dtype(dt).itemsize
                             for sh, dt in zip(shapes, wire_dtypes)))
        return _Program(compiled, acc_sh, chunk_sh, acc_dtypes, wire_dtypes,
                        out_dtypes, shapes, wire_bytes)

    def _program_for(self, treedef, shapes, dtypes, k, mode,
                     parent) -> _Program:
        sig = (treedef, shapes, dtypes, k, mode, self.wire_dtype)
        prog = self._programs.get(sig)
        if prog is None:
            sp = (obs.span("aggregate.compile", parent, k=k, mode=mode,
                           n_leaves=len(shapes))
                  if parent is not None else NULL_SPAN)
            with sp:
                t0 = time.perf_counter()
                prog = self._build_program(treedef, shapes, dtypes, k, mode)
                compile_s = time.perf_counter() - t0
                obs.histogram_observe("agg.compile_seconds", compile_s,
                                      labels={"mode": mode})
                # XLA's own cost model for the cached program: what one
                # reduction step costs in flops / memory traffic
                obs.gauge_set("agg.program_flops", prog.flops_per_step,
                              labels={"mode": mode})
                obs.gauge_set("agg.program_bytes", prog.bytes_per_step,
                              labels={"mode": mode})
                # end with attribution attrs; the context-manager re-end is
                # an idempotent no-op
                sp.end(compile_s=round(compile_s, 6),
                       flops_per_step=prog.flops_per_step,
                       bytes_per_step=prog.bytes_per_step)
                logger.info(
                    "agg_plane compiled %s k=%d leaves=%d in %.3fs",
                    mode, k, len(shapes), compile_s)
            self._programs[sig] = prog
        return prog

    # -- the reduction -------------------------------------------------------
    def aggregate(self, updates: Sequence[Tuple[float, Pytree]],
                  mode: str = "mean",
                  obs_parent: Any = None) -> Pytree:
        """Aggregate ``[(n_samples, pytree), ...]`` on the mesh.

        Returns a pytree of device arrays (same structure as the inputs;
        dtypes mirror the host path).  Raises ``ValueError`` on an empty
        update list, a non-positive total sample count (``mean``), or
        structurally mismatched client pytrees.
        """
        if mode not in ("mean", "sum"):
            raise ValueError(f"agg mode must be mean|sum (got {mode!r})")
        if not updates:
            raise ValueError("no updates to aggregate")
        ns = [float(n) for n, _ in updates]
        leaves_list, treedef = flatten_checked([t for _, t in updates])
        n = len(leaves_list)
        if mode == "mean":
            total = float(sum(ns))
            if total <= 0:
                raise ValueError("total sample count must be positive")
            # the same f64 divide the host path feeds tree_scale, rounded to
            # f32 once — the multiply then matches bit-for-bit
            w_all = np.asarray([x / total for x in ns], np.float32)
        else:
            w_all = np.ones(n, np.float32)

        shapes = tuple(tuple(np.shape(l)) for l in leaves_list[0])
        dtypes = tuple(jnp.dtype(jnp.result_type(l)) for l in leaves_list[0])
        k = self.microbatch_clients or n
        parent = obs_parent if obs_parent is not None else obs.active_ctx()
        prog = self._program_for(treedef, shapes, dtypes, k, mode, parent)

        t0 = time.perf_counter()
        sp = (obs.span("aggregate.reduce", parent, n_clients=n, k=k,
                       mode=mode)
              if parent is not None else NULL_SPAN)
        w_sharding = NamedSharding(self.mesh, P())
        with sp:
            acc = jax.device_put(
                [np.zeros(sh, np.dtype(dt))
                 for sh, dt in zip(shapes, prog.acc_dtypes)],
                prog.acc_shardings)
            for lo in range(0, n, k):
                hi = min(lo + k, n)
                chunk = []
                for j, sh in enumerate(shapes):
                    buf = np.zeros((k,) + sh, dtype=np.dtype(prog.wire_dtypes[j]))
                    for row, c in enumerate(range(lo, hi)):
                        buf[row] = np.asarray(leaves_list[c][j])
                    chunk.append(buf)
                # the final chunk is zero-padded (rows AND weights): acc + 0
                # is exact, so padding never perturbs the result
                w = np.zeros(k, np.float32)
                w[: hi - lo] = w_all[lo:hi]
                chunk = jax.device_put(chunk, prog.chunk_shardings)
                acc = prog.step(acc, chunk, jax.device_put(w, w_sharding))
            out = [a.astype(dt) if a.dtype != dt else a
                   for a, dt in zip(acc, prog.out_dtypes)]
            jax.block_until_ready(out)
        dt_s = time.perf_counter() - t0
        obs.histogram_observe("agg.step_seconds", dt_s,
                              labels={"path": "compiled", "mode": mode})
        obs.counter_inc("agg.bytes_reduced", n * prog.wire_bytes,
                        labels={"path": "compiled"})
        return jax.tree_util.tree_unflatten(treedef, out)


# -- args-driven construction ------------------------------------------------

_PLANES: Dict[Any, CompiledAggPlane] = {}


def plane_config(args: Any) -> Tuple[str, int]:
    wire = str(getattr(args, "agg_wire_dtype", "f32") or "f32").lower()
    k = int(getattr(args, "agg_microbatch_clients", 0) or 0)
    return wire, k


def plane_for(args: Any) -> CompiledAggPlane:
    """Process-cached plane for this config (the mesh — hence the compiled
    programs — are per-process resources; every aggregator with the same
    knobs shares one plane and its program cache)."""
    key = plane_config(args)
    plane = _PLANES.get(key)
    if plane is None:
        wire, k = key
        plane = CompiledAggPlane(wire_dtype=wire, microbatch_clients=k)
        _PLANES[key] = plane
    return plane


def reset_planes() -> None:
    """Drop cached planes/programs (tests; device topology changes)."""
    _PLANES.clear()
