"""Compiled sharded aggregation plane: one-jit GSPMD reduction over client deltas.

The server's hottest loop — aggregating client updates — was per-client
host-side pytree arithmetic (``core/aggregate.py`` ``weighted_mean``), so its
cost scaled with Python object overhead and never touched the mesh this
package already builds.  This module rebuilds it as ONE compiled,
``NamedSharding``-annotated program over a device mesh, the cross-replica
sharding of the weight update from "Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training" (arxiv 2004.13336): every device
owns a shard of every parameter and reduces only its shard.

Shape of the plane:

* **Stacked deltas** — client updates are stacked on a leading axis
  (``core/aggregate.flatten_checked`` validates structure/shape first, with
  a clear error naming the offending client and leaf).
* **Partition rules** — per-leaf ``PartitionSpec``\\s come from regex rules
  matched against the ``/``-joined flattened param path (the
  ``match_partition_rules`` pattern, SNIPPETS [2]/[3]) with the
  ``parallel/sharding.py:param_spec`` largest-divisible-axis heuristic as
  fallback; scalars always replicate.
* **One jit, donated buffers** — the reduction is a single compiled
  ``lax.scan`` over the client axis folding each delta into a running
  accumulator.  The accumulator and the in-flight delta chunk are DONATED,
  so steady-state HBM is one model-size accumulator plus one chunk.
* **bf16 wire, f32 accumulate** — ``wire_dtype="bf16"`` halves host→device
  traffic; accumulation is always f32 (integer leaves accumulate in their
  own dtype under ``sum`` to mirror the host path).
* **Microbatching** — ``microbatch_clients=K`` folds K clients at a time
  into the accumulator, so 1k–10k deltas aggregate without ever
  materializing the full stack in HBM.

Bit-exactness contract (tier-1, CPU): in f32 mode the scan accumulates
left-to-right — multiply-by-weight then add, exactly the op sequence of the
host ``weighted_mean``/``unweighted_sum`` — so host and compiled paths agree
bitwise, microbatched or not.  (bf16 wire trades that for bandwidth; the
test suite pins its tolerance.)

Observability: the plane emits an ``aggregate.compile`` span per new
(treedef, shapes, K, mode) signature and an ``aggregate.reduce`` span per
aggregation — parented under the caller's ambient span (the server
managers' ``aggregate`` phase) so chaos traces stay single-rooted — plus
``agg.step_seconds`` / ``agg.bytes_reduced`` metrics.
"""

from __future__ import annotations

import logging
import re
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import obs
from ..core.aggregate import flatten_checked, leaf_paths, opt_leaf_indices
from ..core.obs.trace import NULL_SPAN
from .mesh import (create_mesh, create_round_mesh, mesh_fingerprint,
                   visible_devices)
from .sec_plane import (make_fold_fn, make_stage_fn, make_tail_fn,
                        plane_security)
from .sharding import param_spec

logger = logging.getLogger(__name__)

Pytree = Any

AGG_PLANES = ("host", "compiled")
AGG_WIRE_DTYPES = ("f32", "bf16")
#: where global params + server-optimizer state live between rounds:
#: ``replicated`` = host pytrees (the pre-sharded-plane behaviour),
#: ``sharded`` = NamedSharding device arrays on the round mesh with the
#: whole round tail compiled (:class:`ShardedRoundPlane`).
SERVER_STATES = ("replicated", "sharded")

_WIRE_JNP = {"f32": jnp.float32, "bf16": jnp.bfloat16}


def default_agg_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D ``tp`` mesh over all devices: each device owns one shard of every
    (divisible) parameter and reduces only that shard — the weight-update
    analogue of data-parallel replicas splitting the update step."""
    devices = list(devices if devices is not None else visible_devices())
    return create_mesh((len(devices),), ("tp",), devices)


def default_round_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """2-D ``(client, model)`` mesh for the sharded round update.  On the
    server the client axis is 1 — client deltas arrive over the wire and the
    fold stays sequential for bit-exactness — while every device owns a
    model shard of the global params, the optimizer state, and the update
    step (the XLA simulator widens the client axis for in-mesh cohorts)."""
    devices = list(devices if devices is not None else visible_devices())
    return create_round_mesh(clients=1, model=len(devices), devices=devices)


def match_partition_rules(rules: Sequence[Tuple[str, Any]], names: Sequence[str],
                          shapes: Sequence[Tuple[int, ...]], mesh: Mesh,
                          axis: str = "tp") -> List[P]:
    """Per-leaf ``PartitionSpec``: first regex in ``rules`` that matches the
    ``/``-joined param path wins; unmatched leaves fall back to the
    ``param_spec`` largest-divisible-axis heuristic over ``axis``; scalars
    (and size-1 leaves) always replicate.  A rule naming a mesh axis that
    does not exist (or that does not divide the leaf) degrades to
    replication rather than failing the round — aggregation must work on
    any mesh."""
    size = int(mesh.shape.get(axis, 1))
    out: List[P] = []
    for name, shape in zip(names, shapes):
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            out.append(P())
            continue
        spec = None
        for pat, ps in rules:
            if re.search(pat, name):
                spec = P(*ps) if not isinstance(ps, P) else ps
                break
        if spec is None:
            out.append(param_spec(shape, size, axis=axis))
            continue
        out.append(_sanitize_spec(spec, shape, mesh))
    return out


def _sanitize_spec(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    if len(spec) > len(shape):
        return P()
    for dim, axis in zip(shape, spec):
        if axis is None:
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        size = 1
        for a in axes:
            if a not in mesh.shape:
                return P()
            size *= int(mesh.shape[a])
        if size > 1 and dim % size != 0:
            return P()
    return spec


def stacked_reduce(stacked: Pytree, weights: jnp.ndarray) -> Pytree:
    """Sequential in-mesh weighted reduction: fold ``stacked[i] * w_i`` into
    a f32 accumulator left-to-right via ``lax.scan``.  Pure and traceable —
    the XLA simulator's security tail uses it directly; the plane's compiled
    step is the chunked/donated version of the same loop.  Unlike the
    tensordot form, the fold order is the host path's, so results are
    bit-identical to ``weighted_mean`` given the same f32 weights."""
    zeros = jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape[1:], jnp.float32), stacked)

    def body(acc, xw):
        x, w = xw
        return jax.tree_util.tree_map(
            lambda a, v: a + v.astype(jnp.float32) * w, acc, x), None

    acc, _ = jax.lax.scan(body, zeros, (stacked, weights.astype(jnp.float32)))
    return acc


class _Program:
    """One compiled reduction: the AOT-compiled step plus the leaf plan."""

    __slots__ = ("step", "acc_shardings", "chunk_shardings", "acc_dtypes",
                 "wire_dtypes", "out_dtypes", "shapes", "wire_bytes",
                 "flops_per_step", "bytes_per_step")

    def __init__(self, step, acc_shardings, chunk_shardings, acc_dtypes,
                 wire_dtypes, out_dtypes, shapes, wire_bytes):
        self.step = step
        self.acc_shardings = acc_shardings
        self.chunk_shardings = chunk_shardings
        self.acc_dtypes = acc_dtypes
        self.wire_dtypes = wire_dtypes
        self.out_dtypes = out_dtypes
        self.shapes = shapes
        self.wire_bytes = wire_bytes
        self.flops_per_step = _compiled_cost(step, "flops")
        self.bytes_per_step = _compiled_cost(step, "bytes accessed")


def _compiled_cost(compiled: Any, key: str) -> float:
    """One key of XLA's per-program cost model (``Compiled.cost_analysis``:
    "flops", "bytes accessed", ...), 0.0 when the backend doesn't report it."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return float(ca.get(key, 0.0) or 0.0)
    except Exception:
        return 0.0


class CompiledAggPlane:
    """The compiled aggregation plane.

    ``aggregate(updates, mode)`` mirrors :func:`core.aggregate.weighted_mean`
    (``mode="mean"``) / :func:`core.aggregate.unweighted_sum`
    (``mode="sum"``) over ``[(n_samples, pytree), ...]`` but runs as one
    donated-buffer compiled program per microbatch chunk.

    Programs are cached per (mesh, treedef, leaf shapes/dtypes, K, mode):
    the first round at a new signature pays the XLA compile (visible as the
    ``aggregate.compile`` span); every later round reuses it.  The mesh is
    part of the key — a program compiled for one device set must never be
    replayed on another just because the shapes line up.
    """

    #: mesh axis params shard over; the round plane overrides with "model"
    axis = "tp"

    def __init__(self, mesh: Optional[Mesh] = None,
                 rules: Sequence[Tuple[str, Any]] = (),
                 wire_dtype: str = "f32",
                 microbatch_clients: int = 0):
        if wire_dtype not in AGG_WIRE_DTYPES:
            raise ValueError(
                f"agg_wire_dtype must be one of {AGG_WIRE_DTYPES} (got {wire_dtype!r})")
        if int(microbatch_clients) < 0:
            raise ValueError(
                f"agg_microbatch_clients must be >= 0 (got {microbatch_clients})")
        self.mesh = mesh if mesh is not None else default_agg_mesh()
        self.mesh_key = mesh_fingerprint(self.mesh)
        self.rules = tuple(rules)
        self.wire_dtype = wire_dtype
        self.microbatch_clients = int(microbatch_clients)
        self._programs: Dict[Any, _Program] = {}

    # -- program construction ------------------------------------------------
    def _leaf_plan(self, treedef, shapes, dtypes, mode):
        names = leaf_paths(treedef)
        specs = match_partition_rules(self.rules, names, shapes, self.mesh,
                                      axis=self.axis)
        wire = _WIRE_JNP[self.wire_dtype]
        acc_dtypes, wire_dtypes, out_dtypes = [], [], []
        for dt in dtypes:
            dt = jnp.dtype(dt)
            if jnp.issubdtype(dt, jnp.floating):
                wire_dtypes.append(jnp.dtype(wire))
                acc_dtypes.append(jnp.dtype(jnp.float32))
                # host parity: mean keeps the input float dtype, sum too
                out_dtypes.append(dt)
            else:
                # integer leaves: no lossy wire cast; host sum stays integer
                # while host mean promotes to f32
                wire_dtypes.append(dt)
                if mode == "sum":
                    acc_dtypes.append(dt)
                    out_dtypes.append(dt)
                else:
                    acc_dtypes.append(jnp.dtype(jnp.float32))
                    out_dtypes.append(jnp.dtype(jnp.float32))
        return specs, acc_dtypes, wire_dtypes, out_dtypes

    def _build_program(self, treedef, shapes, dtypes, k, mode) -> _Program:
        specs, acc_dtypes, wire_dtypes, out_dtypes = self._leaf_plan(
            treedef, shapes, dtypes, mode)
        mesh = self.mesh
        acc_sh = [NamedSharding(mesh, s) for s in specs]
        chunk_sh = [NamedSharding(mesh, P(None, *s)) for s in specs]
        w_sh = NamedSharding(mesh, P())

        def step(acc, chunk, w):
            if mode == "mean":
                # scale the whole chunk BEFORE the scan: the product must
                # materialize at the while-loop boundary, so it rounds to
                # f32 exactly like the host path's tree_scale — inside the
                # loop body LLVM would contract a + v*w into an fma and
                # break bit-exactness
                chunk = [c.astype(a.dtype)
                         * w.reshape((-1,) + (1,) * (c.ndim - 1)).astype(a.dtype)
                         for a, c in zip(acc, chunk)]

            def body(carry, x):
                # padding rows are all-zero (rows AND weights), so adding
                # them is exact; host sum mode never multiplies, nor do we
                return [a + v.astype(a.dtype)
                        for a, v in zip(carry, x)], None

            acc, _ = jax.lax.scan(body, acc, chunk)
            return acc

        # acc and the in-flight chunk are donated: steady-state HBM is one
        # accumulator + one chunk regardless of client count
        jitted = jax.jit(step, donate_argnums=(0, 1),
                         in_shardings=(acc_sh, chunk_sh, w_sh),
                         out_shardings=acc_sh)
        acc_sds = [jax.ShapeDtypeStruct(sh, dt, sharding=s)
                   for sh, dt, s in zip(shapes, acc_dtypes, acc_sh)]
        chunk_sds = [jax.ShapeDtypeStruct((k,) + sh, dt, sharding=s)
                     for sh, dt, s in zip(shapes, wire_dtypes, chunk_sh)]
        w_sds = jax.ShapeDtypeStruct((k,), jnp.float32, sharding=w_sh)
        with warnings.catch_warnings():
            # donation is a no-op on CPU backends; the warning is expected
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            compiled = jitted.lower(acc_sds, chunk_sds, w_sds).compile()
        wire_bytes = int(sum(int(np.prod(sh) or 1) * jnp.dtype(dt).itemsize
                             for sh, dt in zip(shapes, wire_dtypes)))
        return _Program(compiled, acc_sh, chunk_sh, acc_dtypes, wire_dtypes,
                        out_dtypes, shapes, wire_bytes)

    def _program_for(self, treedef, shapes, dtypes, k, mode,
                     parent) -> _Program:
        sig = (self.mesh_key, treedef, shapes, dtypes, k, mode,
               self.wire_dtype)
        prog = self._programs.get(sig)
        if prog is None:
            sp = (obs.span("aggregate.compile", parent, k=k, mode=mode,
                           n_leaves=len(shapes))
                  if parent is not None else NULL_SPAN)
            with sp:
                t0 = time.perf_counter()
                prog = self._build_program(treedef, shapes, dtypes, k, mode)
                compile_s = time.perf_counter() - t0
                obs.histogram_observe("agg.compile_seconds", compile_s,
                                      labels={"mode": mode})
                # XLA's own cost model for the cached program: what one
                # reduction step costs in flops / memory traffic
                obs.gauge_set("agg.program_flops", prog.flops_per_step,
                              labels={"mode": mode})
                obs.gauge_set("agg.program_bytes", prog.bytes_per_step,
                              labels={"mode": mode})
                # end with attribution attrs; the context-manager re-end is
                # an idempotent no-op
                sp.end(compile_s=round(compile_s, 6),
                       flops_per_step=prog.flops_per_step,
                       bytes_per_step=prog.bytes_per_step)
                logger.info(
                    "agg_plane compiled %s k=%d leaves=%d in %.3fs",
                    mode, k, len(shapes), compile_s)
            self._programs[sig] = prog
        return prog

    # -- the reduction -------------------------------------------------------
    def aggregate(self, updates: Sequence[Tuple[float, Pytree]],
                  mode: str = "mean",
                  obs_parent: Any = None) -> Pytree:
        """Aggregate ``[(n_samples, pytree), ...]`` on the mesh.

        Returns a pytree of device arrays (same structure as the inputs;
        dtypes mirror the host path).  Raises ``ValueError`` on an empty
        update list, a non-positive total sample count (``mean``), or
        structurally mismatched client pytrees.
        """
        if mode not in ("mean", "sum"):
            raise ValueError(f"agg mode must be mean|sum (got {mode!r})")
        if not updates:
            raise ValueError("no updates to aggregate")
        ns = [float(n) for n, _ in updates]
        leaves_list, treedef = flatten_checked([t for _, t in updates])
        n = len(leaves_list)
        if mode == "mean":
            total = float(sum(ns))
            if total <= 0:
                raise ValueError("total sample count must be positive")
            # the same f64 divide the host path feeds tree_scale, rounded to
            # f32 once — the multiply then matches bit-for-bit
            w_all = np.asarray([x / total for x in ns], np.float32)
        else:
            w_all = np.ones(n, np.float32)

        shapes = tuple(tuple(np.shape(l)) for l in leaves_list[0])
        dtypes = tuple(jnp.dtype(jnp.result_type(l)) for l in leaves_list[0])
        k = self.microbatch_clients or n
        parent = obs_parent if obs_parent is not None else obs.active_ctx()
        prog = self._program_for(treedef, shapes, dtypes, k, mode, parent)

        t0 = time.perf_counter()
        sp = (obs.span("aggregate.reduce", parent, n_clients=n, k=k,
                       mode=mode)
              if parent is not None else NULL_SPAN)
        w_sharding = NamedSharding(self.mesh, P())
        with sp:
            acc = jax.device_put(
                [np.zeros(sh, np.dtype(dt))
                 for sh, dt in zip(shapes, prog.acc_dtypes)],
                prog.acc_shardings)
            for lo in range(0, n, k):
                hi = min(lo + k, n)
                chunk = []
                for j, sh in enumerate(shapes):
                    buf = np.zeros((k,) + sh, dtype=np.dtype(prog.wire_dtypes[j]))
                    for row, c in enumerate(range(lo, hi)):
                        buf[row] = np.asarray(leaves_list[c][j])
                    chunk.append(buf)
                # the final chunk is zero-padded (rows AND weights): acc + 0
                # is exact, so padding never perturbs the result
                w = np.zeros(k, np.float32)
                w[: hi - lo] = w_all[lo:hi]
                chunk = jax.device_put(chunk, prog.chunk_shardings)
                acc = prog.step(acc, chunk, jax.device_put(w, w_sharding))
            out = [a.astype(dt) if a.dtype != dt else a
                   for a, dt in zip(acc, prog.out_dtypes)]
            jax.block_until_ready(out)
        dt_s = time.perf_counter() - t0
        obs.histogram_observe("agg.step_seconds", dt_s,
                              labels={"path": "compiled", "mode": mode})
        obs.counter_inc("agg.bytes_reduced", n * prog.wire_bytes,
                        labels={"path": "compiled"})
        return jax.tree_util.tree_unflatten(treedef, out)

    def partial_reduce(self, updates: Sequence[Tuple[float, Pytree]],
                       total_weight: Optional[float] = None,
                       mode: str = "mean",
                       obs_parent: Any = None) -> Pytree:
        """One hierarchy block's fold — the reduction WITHOUT a server
        tail (the edge-aggregator tier's compiled leg).

        Identical to :meth:`aggregate` except the ``mean`` weights divide
        by the caller-supplied GLOBAL ``total_weight`` instead of the
        block-local sum, so every per-leaf multiply uses the same f32
        operand the flat fold would — block partials then combine (a
        ``sum``-mode fold over the partial pytrees) into the flat result
        bit-for-bit.  ``sum`` mode ignores ``total_weight``.
        """
        if mode not in ("mean", "sum"):
            raise ValueError(f"agg mode must be mean|sum (got {mode!r})")
        if not updates:
            raise ValueError("no updates to fold")
        if mode == "sum":
            return self.aggregate(updates, mode="sum", obs_parent=obs_parent)
        if total_weight is None:
            total_weight = float(sum(float(n) for n, _ in updates))
        total = float(total_weight)
        if total <= 0:
            raise ValueError("total sample count must be positive")
        ns = [float(n) for n, _ in updates]
        leaves_list, treedef = flatten_checked([t for _, t in updates])
        n = len(leaves_list)
        # the same f64 divide the host partial_fold feeds tree_scale,
        # rounded to f32 once — matching the flat plane's weight path
        w_all = np.asarray([x / total for x in ns], np.float32)

        shapes = tuple(tuple(np.shape(l)) for l in leaves_list[0])
        dtypes = tuple(jnp.dtype(jnp.result_type(l)) for l in leaves_list[0])
        k = self.microbatch_clients or n
        parent = obs_parent if obs_parent is not None else obs.active_ctx()
        prog = self._program_for(treedef, shapes, dtypes, k, "mean", parent)

        t0 = time.perf_counter()
        sp = (obs.span("aggregate.partial", parent, n_clients=n, k=k,
                       mode=mode)
              if parent is not None else NULL_SPAN)
        w_sharding = NamedSharding(self.mesh, P())
        with sp:
            acc = jax.device_put(
                [np.zeros(sh, np.dtype(dt))
                 for sh, dt in zip(shapes, prog.acc_dtypes)],
                prog.acc_shardings)
            for lo in range(0, n, k):
                hi = min(lo + k, n)
                chunk = []
                for j, sh in enumerate(shapes):
                    buf = np.zeros((k,) + sh, dtype=np.dtype(prog.wire_dtypes[j]))
                    for row, c in enumerate(range(lo, hi)):
                        buf[row] = np.asarray(leaves_list[c][j])
                    chunk.append(buf)
                w = np.zeros(k, np.float32)
                w[: hi - lo] = w_all[lo:hi]
                chunk = jax.device_put(chunk, prog.chunk_shardings)
                acc = prog.step(acc, chunk, jax.device_put(w, w_sharding))
            out = [a.astype(dt) if a.dtype != dt else a
                   for a, dt in zip(acc, prog.out_dtypes)]
            jax.block_until_ready(out)
        dt_s = time.perf_counter() - t0
        obs.histogram_observe("agg.step_seconds", dt_s,
                              labels={"path": "compiled", "mode": "partial"})
        obs.counter_inc("agg.bytes_reduced", n * prog.wire_bytes,
                        labels={"path": "compiled"})
        return jax.tree_util.tree_unflatten(treedef, out)


# -- the sharded round plane -------------------------------------------------


class _RoundProgram:
    """One compiled round tail: fused fold+optimize+materialize (``fused``)
    or the finishing tail alone (microbatched folds feed it)."""

    __slots__ = ("fn", "leaf_shardings", "chunk_shardings", "opt_shardings",
                 "acc_dtypes", "wire_dtypes", "out_dtypes", "wire_bytes",
                 "fused", "staged")

    def __init__(self, fn, leaf_shardings, chunk_shardings, opt_shardings,
                 acc_dtypes, wire_dtypes, out_dtypes, wire_bytes, fused,
                 staged=False):
        self.fn = fn
        self.leaf_shardings = leaf_shardings
        self.chunk_shardings = chunk_shardings
        self.opt_shardings = opt_shardings
        self.acc_dtypes = acc_dtypes
        self.wire_dtypes = wire_dtypes
        self.out_dtypes = out_dtypes
        self.wire_bytes = wire_bytes
        self.fused = fused
        self.staged = staged


def round_policy(args: Any) -> Tuple:
    """Server-optimizer policy tuple for the round tail, resolved exactly
    like the sp/fedopt host oracle: ``("fedavg",)`` when the federated
    optimizer has no server step, else ``(name, lr, momentum)`` from
    ``server_optimizer`` / ``server_lr`` / ``server_momentum``."""
    opt = str(getattr(args, "federated_optimizer", "FedAvg") or "FedAvg")
    if opt not in ("FedOpt", "FedOpt_seq"):
        return ("fedavg",)
    name = str(getattr(args, "server_optimizer", "adam") or "adam").lower()
    lr = float(getattr(args, "server_lr", 1e-1))
    momentum = float(getattr(args, "server_momentum", 0.9))
    return (name, lr, momentum)


def _policy_tx(policy: Tuple):
    """optax transform for a policy tuple, via the sp/fedopt oracle builder
    (lazy import: fedopt_api imports core.aggregate at module top)."""
    if policy[0] == "fedavg":
        return None
    import types

    from ..simulation.sp.fedopt.fedopt_api import make_server_optimizer
    name, lr, momentum = policy
    return make_server_optimizer(types.SimpleNamespace(
        server_optimizer=name, server_lr=lr, server_momentum=momentum))


class ShardedRoundPlane(CompiledAggPlane):
    """Model-sharded server state + one compiled round update.

    Global params and server-optimizer state live between rounds as
    ``NamedSharding`` device arrays partitioned along the round mesh's
    ``model`` axis.  ``round_update(params, updates)`` runs the whole round
    tail — stacked-delta reduce, FedOpt/FedAdam/FedYogi step (or the FedAvg
    identity), new-params materialization — as ONE donated-buffer compiled
    program per (mesh, treedef, shapes, K, mode, policy) signature; with
    microbatching the chunk fold reuses the inherited step program and only
    the finishing tail is a second program, so microbatched == full
    bitwise.

    Bit-exactness: the fold is the inherited left-to-right scan (bitwise
    the host ``weighted_mean``/``unweighted_sum``), an
    ``optimization_barrier`` pins the reduce→tail materialization boundary
    so XLA cannot contract across it, and the tail traces the same optax
    transform the host oracle jits — so the round update matches
    :func:`fedml_tpu.core.aggregate.host_server_round_update` bit-for-bit
    in f32 mode.
    """

    axis = "model"

    def __init__(self, mesh: Optional[Mesh] = None,
                 rules: Sequence[Tuple[str, Any]] = (),
                 wire_dtype: str = "f32",
                 microbatch_clients: int = 0,
                 policy: Tuple = ("fedavg",),
                 defense: Optional[Tuple] = None,
                 dp: Optional[Tuple] = None):
        mesh = mesh if mesh is not None else default_round_mesh()
        super().__init__(mesh=mesh, rules=rules, wire_dtype=wire_dtype,
                         microbatch_clients=microbatch_clients)
        self.policy = tuple(policy)
        #: hashable sec_plane stage specs; when either is set the round
        #: program grows a pre-reduce (DP → defense) stage and the plane
        #: always folds the FULL stack fused (padding/microbatch rows would
        #: enter a sort/median defense's consensus)
        self.defense = tuple(defense) if defense is not None else None
        self.dp = tuple(dp) if dp is not None else None
        self._tx = _policy_tx(self.policy)
        self._treedef = None
        self._shapes: Optional[Tuple] = None
        self._param_dtypes: Optional[Tuple] = None
        self._leaf_shardings: Optional[List[NamedSharding]] = None
        self._param_leaves: Optional[List[Any]] = None
        self._opt_idx: Tuple[int, ...] = ()
        self._opt_state: Any = ()
        self._last_out: Any = None
        # (upd_dtypes, k, mode, fused) of the most recent round — remesh()
        # pre-warms the same program on the new mesh so the first post-resize
        # round pays device transfer, not a cold compile
        self._last_prog_args: Optional[Tuple] = None

    # -- resident state ------------------------------------------------------
    def install(self, params_tree: Pytree) -> None:
        """Place the global params on the mesh (model-axis NamedShardings)
        and (re)build the server-optimizer state when the structure changed.
        Optimizer state survives a re-install of same-structure params —
        the oracle never resets it mid-run either."""
        leaves, treedef = jax.tree_util.tree_flatten(params_tree)
        names = leaf_paths(treedef)
        shapes = tuple(tuple(np.shape(l)) for l in leaves)
        dtypes = tuple(jnp.dtype(jnp.result_type(l)) for l in leaves)
        specs = match_partition_rules(self.rules, names, shapes, self.mesh,
                                      axis=self.axis)
        changed = (self._treedef is None or treedef != self._treedef
                   or shapes != self._shapes or dtypes != self._param_dtypes)
        self._treedef = treedef
        self._shapes = shapes
        self._param_dtypes = dtypes
        self._leaf_shardings = [NamedSharding(self.mesh, s) for s in specs]
        self._param_leaves = jax.device_put(
            [np.asarray(l) for l in leaves], self._leaf_shardings)
        self._opt_idx = tuple(opt_leaf_indices(names, dtypes)
                              if self._tx is not None else ())
        if self._tx is not None and (changed or self._opt_state == ()):
            self._opt_state = self._tx.init(
                [self._param_leaves[i] for i in self._opt_idx])
        self._last_out = None
        param_bytes = sum(int(np.prod(sh) or 1) * jnp.dtype(dt).itemsize
                          for sh, dt in zip(shapes, dtypes))
        opt_bytes = sum(
            int(np.prod(np.shape(l)) or 1) * jnp.dtype(jnp.result_type(l)).itemsize
            for l in jax.tree_util.tree_leaves(self._opt_state))
        model = int(self.mesh.shape.get(self.axis, 1))
        obs.gauge_set("server_state.shard_bytes",
                      (param_bytes + opt_bytes) / model,
                      labels={"axis": self.axis})

    @property
    def installed(self) -> bool:
        return self._treedef is not None

    # -- round programs ------------------------------------------------------
    def _build_round_program(self, upd_dtypes, k, mode, fused) -> _RoundProgram:
        treedef, shapes = self._treedef, self._shapes
        specs, acc_dtypes, wire_dtypes, out_dtypes = self._leaf_plan(
            treedef, shapes, upd_dtypes, mode)
        mesh = self.mesh
        leaf_sh = [NamedSharding(mesh, s) for s in specs]
        chunk_sh = [NamedSharding(mesh, P(None, *s)) for s in specs]
        w_sh = NamedSharding(mesh, P())
        tx, opt_idx = self._tx, self._opt_idx
        param_dtypes = self._param_dtypes

        if tx is not None:
            opt_sds_in = [jax.ShapeDtypeStruct(shapes[i], param_dtypes[i])
                          for i in opt_idx]
            opt_template = jax.eval_shape(tx.init, opt_sds_in)
            model = int(mesh.shape.get(self.axis, 1))
            opt_sh = jax.tree_util.tree_map(
                lambda l: NamedSharding(
                    mesh, param_spec(l.shape, model, axis=self.axis)),
                opt_template)
            opt_sds = jax.tree_util.tree_map(
                lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
                opt_template, opt_sh)
        else:
            opt_sh, opt_sds = (), ()

        # the fold/tail closures are sec_plane's — the SAME objects the
        # host oracle jits standalone, so parity is by construction
        fold = make_fold_fn(mode)
        tail = make_tail_fn(tx, opt_idx, out_dtypes)
        staged = fused and (self.defense is not None or self.dp is not None)

        if staged:
            stage = make_stage_fn(self.defense, self.dp, mode, k)
            repl = NamedSharding(mesh, P())

            def fn(params, opt_state, chunk, w, round_idx, client_ids,
                   sigma):
                # the security stage runs on a REPLICATED copy of the
                # stack: its cross-coordinate reductions (row norms,
                # Krum's pairwise matmul) must see whole rows, or GSPMD's
                # partial-sum order would break the bitwise contract with
                # the host oracle; the fold below stays model-sharded
                c_r = [jax.lax.with_sharding_constraint(c, repl)
                       for c in chunk]
                p_r = [jax.lax.with_sharding_constraint(p, repl)
                       for p in params]
                c2, w2, rejected = stage(c_r, w, p_r, round_idx,
                                         client_ids, sigma)
                # anchor the stage EXIT replicated too, so the chunk_sh
                # re-shard below cannot propagate backward into the
                # stage's reductions — on meshes where the leaf dims
                # happen to divide, that propagation splits the row-norm
                # sums and drifts the stage off the oracle by an ulp —
                # then pin the stage→fold boundary (where the host oracle
                # has its program boundary) before re-sharding
                c2 = [jax.lax.with_sharding_constraint(c, repl)
                      for c in c2]
                c2, w2 = jax.lax.optimization_barrier((c2, w2))
                c2 = [jax.lax.with_sharding_constraint(c, s)
                      for c, s in zip(c2, chunk_sh)]
                zeros = [jnp.zeros(sh, dt)
                         for sh, dt in zip(shapes, acc_dtypes)]
                acc = fold(zeros, c2, w2)
                acc = jax.lax.optimization_barrier(acc)
                new, new_state = tail(params, opt_state, acc)
                return new, new_state, rejected

            jitted = jax.jit(
                fn, donate_argnums=(0, 1, 2),
                in_shardings=(leaf_sh, opt_sh, chunk_sh, w_sh, repl, repl,
                              repl),
                out_shardings=(leaf_sh, opt_sh, repl))
            param_sds = [jax.ShapeDtypeStruct(sh, dt, sharding=s)
                         for sh, dt, s in zip(shapes, param_dtypes, leaf_sh)]
            chunk_sds = [jax.ShapeDtypeStruct((k,) + sh, dt, sharding=s)
                         for sh, dt, s in zip(shapes, wire_dtypes, chunk_sh)]
            w_sds = jax.ShapeDtypeStruct((k,), jnp.float32, sharding=w_sh)
            lowered_args = (param_sds, opt_sds, chunk_sds, w_sds,
                            jax.ShapeDtypeStruct((), jnp.int32, sharding=repl),
                            jax.ShapeDtypeStruct((k,), jnp.int32,
                                                 sharding=repl),
                            jax.ShapeDtypeStruct((), jnp.float32,
                                                 sharding=repl))
        elif fused:
            def fn(params, opt_state, chunk, w):
                zeros = [jnp.zeros(sh, dt)
                         for sh, dt in zip(shapes, acc_dtypes)]
                acc = fold(zeros, chunk, w)
                # pin the reduce→tail boundary: the accumulator must
                # materialize here exactly as it does at the two-program
                # boundary of the host oracle / microbatched path
                acc = jax.lax.optimization_barrier(acc)
                return tail(params, opt_state, acc)

            jitted = jax.jit(fn, donate_argnums=(0, 1, 2),
                             in_shardings=(leaf_sh, opt_sh, chunk_sh, w_sh),
                             out_shardings=(leaf_sh, opt_sh))
            param_sds = [jax.ShapeDtypeStruct(sh, dt, sharding=s)
                         for sh, dt, s in zip(shapes, param_dtypes, leaf_sh)]
            chunk_sds = [jax.ShapeDtypeStruct((k,) + sh, dt, sharding=s)
                         for sh, dt, s in zip(shapes, wire_dtypes, chunk_sh)]
            w_sds = jax.ShapeDtypeStruct((k,), jnp.float32, sharding=w_sh)
            lowered_args = (param_sds, opt_sds, chunk_sds, w_sds)
        else:
            def fn(params, opt_state, acc):
                return tail(params, opt_state, acc)

            jitted = jax.jit(fn, donate_argnums=(0, 1, 2),
                             in_shardings=(leaf_sh, opt_sh, leaf_sh),
                             out_shardings=(leaf_sh, opt_sh))
            param_sds = [jax.ShapeDtypeStruct(sh, dt, sharding=s)
                         for sh, dt, s in zip(shapes, param_dtypes, leaf_sh)]
            acc_sds = [jax.ShapeDtypeStruct(sh, dt, sharding=s)
                       for sh, dt, s in zip(shapes, acc_dtypes, leaf_sh)]
            lowered_args = (param_sds, opt_sds, acc_sds)
        with warnings.catch_warnings():
            # donation is a no-op on CPU backends; the warning is expected
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            compiled = jitted.lower(*lowered_args).compile()
        wire_bytes = int(sum(int(np.prod(sh) or 1) * jnp.dtype(dt).itemsize
                             for sh, dt in zip(shapes, wire_dtypes)))
        return _RoundProgram(compiled, leaf_sh, chunk_sh, opt_sh, acc_dtypes,
                             wire_dtypes, out_dtypes, wire_bytes, fused,
                             staged)

    def _round_program_for(self, upd_dtypes, k, mode, fused,
                           parent) -> _RoundProgram:
        sig = (self.mesh_key, self._treedef, self._shapes, upd_dtypes,
               self._param_dtypes, self._opt_idx, k, mode, self.wire_dtype,
               self.policy, fused, self.defense, self.dp)
        prog = _ROUND_PROGRAMS.get(sig)
        if prog is None:
            sp = (obs.span("aggregate.compile", parent, k=k, mode=mode,
                           policy=self.policy[0], fused=fused,
                           n_leaves=len(self._shapes))
                  if parent is not None else NULL_SPAN)
            with sp:
                t0 = time.perf_counter()
                prog = self._build_round_program(upd_dtypes, k, mode, fused)
                compile_s = time.perf_counter() - t0
                obs.histogram_observe("agg.compile_seconds", compile_s,
                                      labels={"mode": mode})
                sp.end(compile_s=round(compile_s, 6))
                logger.info(
                    "round plane compiled policy=%s mode=%s k=%d fused=%s "
                    "in %.3fs", self.policy[0], mode, k, fused, compile_s)
            _ROUND_PROGRAMS[sig] = prog
        return prog

    # -- the round update ----------------------------------------------------
    def round_update(self, params_tree: Pytree,
                     updates: Sequence[Tuple[float, Pytree]],
                     mode: str = "mean",
                     obs_parent: Any = None,
                     round_idx: int = 0,
                     client_ids: Optional[Sequence[int]] = None,
                     dp_sigma: float = 0.0) -> Pytree:
        """One full round tail on the mesh: reduce ``updates``, apply the
        server-optimizer policy against the resident global params, and
        materialize the new globals.  Returns the new global pytree (host
        numpy leaves); the sharded device copy stays resident for the next
        round, the broadcast shard slices, and recovery snapshots.

        ``params_tree`` is authoritative: unless it IS the tree the last
        ``round_update`` returned (identity — the aggregate-install round
        trip through the server manager), it is re-installed first.
        Optimizer state always survives same-structure re-installs.

        With a ``defense``/``dp`` stage configured the program grows a
        pre-reduce security stage and ``round_idx`` / ``client_ids`` /
        ``dp_sigma`` feed it as RUNTIME inputs (never cache keys): the DP
        noise is a counter-based function of (seed, round_idx, client_id)
        and ``dp_sigma`` is whatever scale the budget accountant granted
        this round.
        """
        if mode not in ("mean", "sum"):
            raise ValueError(f"agg mode must be mean|sum (got {mode!r})")
        if not updates:
            raise ValueError("no updates to aggregate")
        ns = [float(n) for n, _ in updates]
        leaves_list, treedef = flatten_checked([t for _, t in updates])
        n = len(leaves_list)
        if (params_tree is not self._last_out or self._treedef is None
                or treedef != self._treedef):
            self.install(params_tree)
        if treedef != self._treedef:
            raise ValueError(
                "client update pytree structure differs from the installed "
                "global params")
        upd_shapes = tuple(tuple(np.shape(l)) for l in leaves_list[0])
        if upd_shapes != self._shapes:
            raise ValueError(
                f"client update leaf shapes {upd_shapes} differ from the "
                f"installed global params {self._shapes}")
        if mode == "mean":
            total = float(sum(ns))
            if total <= 0:
                raise ValueError("total sample count must be positive")
            w_all = np.asarray([x / total for x in ns], np.float32)
        else:
            w_all = np.ones(n, np.float32)
        upd_dtypes = tuple(jnp.dtype(jnp.result_type(l))
                           for l in leaves_list[0])
        sec_active = self.defense is not None or self.dp is not None
        # a sort/median defense ranks EVERY row of the stack: zero-padded
        # or microbatched partial stacks would enter the consensus, so the
        # staged program always folds the full stack fused at k == n
        k = n if sec_active else (self.microbatch_clients or n)
        self._last_prog_args = (upd_dtypes, k, mode, k >= n)
        parent = obs_parent if obs_parent is not None else obs.active_ctx()
        sp = (obs.span("round.server_update", parent, n_clients=n, k=k,
                       mode=mode, policy=self.policy[0])
              if parent is not None else NULL_SPAN)
        w_sharding = NamedSharding(self.mesh, P())
        rejected = 0.0
        t0 = time.perf_counter()
        with sp:
            params = jax.device_put(self._param_leaves, self._leaf_shardings)
            if k >= n:
                prog = self._round_program_for(upd_dtypes, k, mode,
                                               fused=True, parent=parent)
                opt_state = (jax.device_put(self._opt_state,
                                            prog.opt_shardings)
                             if self._tx is not None else ())
                chunk = []
                for j, sh in enumerate(self._shapes):
                    buf = np.zeros((k,) + sh,
                                   dtype=np.dtype(prog.wire_dtypes[j]))
                    for row in range(n):
                        buf[row] = np.asarray(leaves_list[row][j])
                    chunk.append(buf)
                w = np.zeros(k, np.float32)
                w[:n] = w_all
                chunk = jax.device_put(chunk, prog.chunk_shardings)
                if prog.staged:
                    ids = (np.arange(n, dtype=np.int32) if client_ids is None
                           else np.asarray(client_ids, np.int32))
                    if ids.shape != (n,):
                        raise ValueError(
                            f"client_ids must have one id per update "
                            f"({ids.shape} vs {n} updates)")
                    dsp = (obs.span(
                        "round.defense", sp if parent is not None else None,
                        defense=(self.defense[0] if self.defense else "none"),
                        dp=(self.dp[0] if self.dp else "none"), n_clients=n)
                        if parent is not None else NULL_SPAN)
                    with dsp:
                        t_def = time.perf_counter()
                        new_leaves, new_opt, rej = prog.fn(
                            params, opt_state, chunk,
                            jax.device_put(w, w_sharding),
                            jax.device_put(np.int32(round_idx), w_sharding),
                            jax.device_put(ids, w_sharding),
                            jax.device_put(np.float32(dp_sigma), w_sharding))
                        jax.block_until_ready(new_leaves)
                        rejected = float(rej)
                        def_s = time.perf_counter() - t_def
                        dsp.end(rejected=int(rejected),
                                seconds=round(def_s, 6))
                    # staged-round time: the stage is fused with the fold/
                    # tail, so this is the whole staged program's latency
                    obs.histogram_observe(
                        "agg.defense_seconds", def_s,
                        labels={"defense": (self.defense[0] if self.defense
                                            else "none")})
                    if self.defense is not None:
                        obs.counter_inc(
                            "defense.clients_rejected_total", int(rejected),
                            labels={"defense": self.defense[0]})
                    if self.dp is not None:
                        obs.gauge_set("dp.noise_scale", float(dp_sigma),
                                      labels={"mechanism": self.dp[0]})
                else:
                    new_leaves, new_opt = prog.fn(
                        params, opt_state, chunk,
                        jax.device_put(w, w_sharding))
            else:
                fold_prog = self._program_for(treedef, self._shapes,
                                              upd_dtypes, k, mode, parent)
                acc = jax.device_put(
                    [np.zeros(sh, np.dtype(dt))
                     for sh, dt in zip(self._shapes, fold_prog.acc_dtypes)],
                    fold_prog.acc_shardings)
                for lo in range(0, n, k):
                    hi = min(lo + k, n)
                    chunk = []
                    for j, sh in enumerate(self._shapes):
                        buf = np.zeros(
                            (k,) + sh, dtype=np.dtype(fold_prog.wire_dtypes[j]))
                        for row, c in enumerate(range(lo, hi)):
                            buf[row] = np.asarray(leaves_list[c][j])
                        chunk.append(buf)
                    w = np.zeros(k, np.float32)
                    w[: hi - lo] = w_all[lo:hi]
                    chunk = jax.device_put(chunk, fold_prog.chunk_shardings)
                    acc = fold_prog.step(
                        acc, chunk, jax.device_put(w, w_sharding))
                prog = self._round_program_for(upd_dtypes, k, mode,
                                               fused=False, parent=parent)
                opt_state = (jax.device_put(self._opt_state,
                                            prog.opt_shardings)
                             if self._tx is not None else ())
                new_leaves, new_opt = prog.fn(params, opt_state, acc)
            jax.block_until_ready(new_leaves)
        dt_s = time.perf_counter() - t0
        obs.histogram_observe("server_opt.step_seconds", dt_s,
                              labels={"policy": self.policy[0], "mode": mode})
        obs.histogram_observe("agg.step_seconds", dt_s,
                              labels={"path": "sharded", "mode": mode})
        obs.counter_inc("agg.bytes_reduced", n * prog.wire_bytes,
                        labels={"path": "sharded"})
        self._param_leaves = list(new_leaves)
        self._param_dtypes = tuple(jnp.dtype(x.dtype) for x in new_leaves)
        if self._tx is not None:
            self._opt_state = new_opt
        self._last_out = jax.tree_util.tree_unflatten(
            treedef, [np.asarray(x) for x in new_leaves])
        return self._last_out

    # -- snapshot / restore --------------------------------------------------
    def export_state(self) -> Optional[Dict[str, Any]]:
        """Mesh-portable numpy snapshot of the resident server state (None
        before install): param leaves host-gathered in flatten order (the
        canonical layout — no mesh shape survives into the snapshot), the
        optimizer state rendered through flax's state-dict codec, and a
        ``manifest`` (leaf paths / shapes / dtypes plus the source mesh
        fingerprint, informational) so :meth:`load_state` can validate the
        snapshot against ANY target mesh before touching devices."""
        if not self.installed:
            return None
        from flax import serialization
        return {
            "policy": list(self.policy),
            "leaves": [np.asarray(x) for x in self._param_leaves],
            "opt": serialization.to_state_dict(jax.tree_util.tree_map(
                np.asarray, self._opt_state)),
            "manifest": {
                "version": 1,
                "mesh": [list(part) for part in self.mesh_key],
                "names": list(leaf_paths(self._treedef)),
                "shapes": [list(int(d) for d in sh) for sh in self._shapes],
                "dtypes": [str(jnp.dtype(np.asarray(x).dtype))
                           for x in self._param_leaves],
            },
        }

    def _check_manifest(self, manifest: Dict[str, Any]) -> None:
        """Snapshot/installed-params compatibility: same leaf paths, same
        shapes.  The manifest's mesh fingerprint is deliberately NOT
        checked — mesh portability is the point — and dtypes are carried
        for diagnostics only (``load_state`` adopts the snapshot's)."""
        names = tuple(leaf_paths(self._treedef))
        m_names = tuple(str(x) for x in manifest.get("names", ()))
        if m_names and m_names != names:
            diff = next((f"{a!r} vs {b!r}" for a, b in zip(m_names, names)
                         if a != b), f"{len(m_names)} vs {len(names)} leaves")
            raise ValueError(
                f"snapshot param tree differs from installed params ({diff})")
        m_shapes = tuple(tuple(int(d) for d in sh)
                         for sh in manifest.get("shapes", ()))
        if m_shapes and m_shapes != tuple(self._shapes):
            bad = next((i for i, (a, b) in enumerate(
                zip(m_shapes, self._shapes)) if a != b), None)
            where = (f"leaf {names[bad]!r}: {m_shapes[bad]} vs "
                     f"{tuple(self._shapes[bad])}" if bad is not None
                     else f"{len(m_shapes)} vs {len(self._shapes)} leaves")
            raise ValueError(
                f"snapshot leaf shapes differ from installed params ({where})")

    def load_state(self, state: Dict[str, Any]) -> None:
        """Inverse of :meth:`export_state`, onto ANY mesh: requires
        ``install`` first (the treedef and the param shardings come from the
        installed params on the CURRENT mesh), validates the manifest when
        the snapshot carries one, then overwrites the resident leaves and
        optimizer state bit-identically — the optimizer state is re-sharded
        with the same per-leaf model-axis layout the round programs commit
        to, so a snapshot taken on mesh A resumes on mesh B without a
        relayout inside the first round."""
        if not self.installed:
            raise ValueError("install() the global params before load_state")
        from flax import serialization
        manifest = state.get("manifest")
        if manifest:
            self._check_manifest(manifest)
        leaves = [np.asarray(l) for l in state["leaves"]]
        if len(leaves) != len(self._param_leaves):
            raise ValueError(
                f"snapshot has {len(leaves)} leaves, installed params have "
                f"{len(self._param_leaves)}")
        self._param_dtypes = tuple(jnp.dtype(l.dtype) for l in leaves)
        self._param_leaves = jax.device_put(leaves, self._leaf_shardings)
        if self._tx is not None:
            restored = serialization.from_state_dict(
                self._opt_state, state["opt"])
            restored = jax.tree_util.tree_map(np.asarray, restored)
            model = int(self.mesh.shape.get(self.axis, 1))
            opt_sh = jax.tree_util.tree_map(
                lambda l: NamedSharding(
                    self.mesh,
                    param_spec(tuple(np.shape(l)), model, axis=self.axis)),
                restored)
            self._opt_state = jax.device_put(restored, opt_sh)
        self._last_out = None

    # -- elastic resize ------------------------------------------------------
    def remesh(self, new_mesh: Mesh, warm: bool = True) -> Dict[str, Any]:
        """Move the resident server state onto ``new_mesh`` (grow, shrink,
        or relayout) through the portable snapshot codec: host-gather,
        rebuild the shardings on the new mesh, re-place bit-identically.
        ``mesh_key`` is updated first thing after the gather, so every
        program-cache signature re-keys and a program compiled for the old
        topology can never execute against the resharded buffers.  With
        ``warm`` the most recent round program is recompiled eagerly so the
        first post-resize round does not pay a cold compile.  Returns a
        stats dict (``changed``/``old``/``new``/``reshard_bytes``/
        ``recompile_s``/``seconds``)."""
        new_key = mesh_fingerprint(new_mesh)
        if new_key == self.mesh_key:
            return {"changed": False, "old": self.mesh_key,
                    "new": new_key, "reshard_bytes": 0,
                    "recompile_s": 0.0, "seconds": 0.0}
        old_key = self.mesh_key
        snap = self.export_state()
        params_tree = (jax.tree_util.tree_unflatten(
            self._treedef, [np.asarray(x) for x in self._param_leaves])
            if self.installed else None)
        parent = obs.active_ctx()
        sp = (obs.span("remesh", parent, old_mesh=str(old_key),
                       new_mesh=str(new_key), policy=self.policy[0])
              if parent is not None else NULL_SPAN)
        t0 = time.perf_counter()
        reshard_bytes = 0
        recompile_s = 0.0
        with sp:
            self.mesh = new_mesh
            self.mesh_key = new_key
            self._programs.clear()
            if snap is not None:
                self.install(params_tree)
                self.load_state(snap)
                reshard_bytes = int(
                    sum(np.asarray(x).nbytes for x in snap["leaves"])
                    + sum(np.asarray(l).nbytes for l in
                          jax.tree_util.tree_leaves(snap["opt"])))
                if warm and self._last_prog_args is not None:
                    upd_dtypes, k, mode, fused = self._last_prog_args
                    t1 = time.perf_counter()
                    self._round_program_for(upd_dtypes, k, mode, fused,
                                            parent)
                    recompile_s = time.perf_counter() - t1
            seconds = time.perf_counter() - t0
            sp.end(reshard_bytes=reshard_bytes,
                   recompile_s=round(recompile_s, 6),
                   seconds=round(seconds, 6))
        obs.counter_inc("mesh.resizes_total")
        obs.histogram_observe("mesh.resize_seconds", seconds)
        logger.info(
            "remeshed round plane %s -> %s (%d bytes resharded, "
            "recompile %.3fs, total %.3fs)", old_key, new_key,
            reshard_bytes, recompile_s, seconds)
        return {"changed": True, "old": old_key, "new": new_key,
                "reshard_bytes": reshard_bytes,
                "recompile_s": recompile_s, "seconds": seconds}


# -- shard-addressable broadcast ----------------------------------------------


def broadcast_shards(tree: Pytree, num_shards: int) -> List[Dict[str, Any]]:
    """Split a global-params pytree into ``num_shards`` addressable slices.

    Leaves whose leading dim divides evenly are split along it (the model
    axis of the round mesh); the rest round-robin whole.  Each shard is a
    self-describing dict (``shard``, ``num_shards``, ``parts`` =
    ``[(leaf_index, split_axis_or_-1, ndarray), ...]``) so a client — or a
    future edge aggregator — can fetch exactly the slices it needs and
    :func:`assemble_shards` can reassemble the tree exactly."""
    num_shards = int(num_shards)
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1 (got {num_shards})")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shards: List[List[Tuple[int, int, np.ndarray]]] = [
        [] for _ in range(num_shards)]
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        if (num_shards > 1 and arr.ndim >= 1
                and arr.shape[0] >= num_shards
                and arr.shape[0] % num_shards == 0):
            for s, part in enumerate(np.split(arr, num_shards, axis=0)):
                shards[s].append((i, 0, part))
        else:
            shards[i % num_shards].append((i, -1, arr))
    return [{"shard": s, "num_shards": num_shards, "parts": parts}
            for s, parts in enumerate(shards)]


def assemble_shards(shards: Sequence[Dict[str, Any]], treedef) -> Pytree:
    """Reassemble :func:`broadcast_shards` output (any order) into the
    original pytree; raises when a shard is missing or duplicated."""
    if not shards:
        raise ValueError("no shards to assemble")
    num = int(shards[0]["num_shards"])
    seen = sorted(int(s["shard"]) for s in shards)
    if seen != list(range(num)):
        raise ValueError(f"need shards 0..{num - 1}, got {seen}")
    pieces: Dict[int, List[Tuple[int, int, np.ndarray]]] = {}
    for sh in shards:
        for idx, axis, part in sh["parts"]:
            pieces.setdefault(int(idx), []).append(
                (int(sh["shard"]), int(axis), part))
    leaves = []
    for i in range(treedef.num_leaves):
        plist = sorted(pieces[i], key=lambda t: t[0])
        if plist[0][1] == -1:
            leaves.append(plist[0][2])
        else:
            leaves.append(np.concatenate([p for _, _, p in plist], axis=0))
    return jax.tree_util.tree_unflatten(treedef, leaves)


# -- args-driven construction ------------------------------------------------

_PLANES: Dict[Any, CompiledAggPlane] = {}
_ROUND_PROGRAMS: Dict[Any, _RoundProgram] = {}


def plane_config(args: Any) -> Tuple[str, int]:
    wire = str(getattr(args, "agg_wire_dtype", "f32") or "f32").lower()
    k = int(getattr(args, "agg_microbatch_clients", 0) or 0)
    return wire, k


def plane_for(args: Any) -> CompiledAggPlane:
    """Process-cached plane for this config + the CURRENT device topology
    (the mesh fingerprint is part of the key: after a topology change a
    fresh plane compiles fresh programs instead of silently replaying ones
    built for the old device set)."""
    wire, k = plane_config(args)
    key = (wire, k, mesh_fingerprint(default_agg_mesh()))
    plane = _PLANES.get(key)
    if plane is None:
        plane = CompiledAggPlane(wire_dtype=wire, microbatch_clients=k)
        _PLANES[key] = plane
    return plane


def round_mesh_for(args: Any,
                   devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Round mesh over the currently-LIVE devices, honoring
    ``server_model_parallel`` with degrade-to-replicate: when the surviving
    device count cannot satisfy the requested model axis, the mesh falls
    back to a single-device model axis (fully replicated params) instead of
    refusing to serve — an elastic server keeps taking rounds on whatever
    hardware is left and re-shards when capacity returns."""
    devices = list(devices if devices is not None else visible_devices())
    smp = int(getattr(args, "server_model_parallel", 0) or 0)
    if smp <= 0:
        model = len(devices)
    elif smp <= len(devices):
        model = smp
    else:
        logger.warning(
            "server_model_parallel=%d exceeds the %d live device(s); "
            "degrading to a replicated (model=1) round mesh", smp,
            len(devices))
        obs.counter_inc("mesh.degraded_total")
        model = 1
    return create_round_mesh(clients=1, model=model, devices=devices)


def make_round_plane(args: Any, mesh: Optional[Mesh] = None) -> ShardedRoundPlane:
    """Per-aggregator sharded round plane (NOT process-cached: it holds the
    resident server state, which must never bleed across aggregators; the
    compiled round programs DO share the process-wide cache).  Without an
    explicit mesh the plane is built over the live device set via
    :func:`round_mesh_for` — a restart after device loss comes up on the
    shrunken topology and the portable snapshot codec re-shards onto it."""
    wire, k = plane_config(args)
    mesh = mesh if mesh is not None else round_mesh_for(args)
    defense, dp = plane_security(args)
    return ShardedRoundPlane(mesh=mesh, wire_dtype=wire,
                             microbatch_clients=k, policy=round_policy(args),
                             defense=defense, dp=dp)


def reset_planes() -> None:
    """Drop cached planes/programs (tests; device topology changes)."""
    _PLANES.clear()
    _ROUND_PROGRAMS.clear()
