"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context capability the reference lacks entirely (SURVEY.md §5
"long-context/sequence parallelism: absent") but this framework treats as
first-class: each device in the ``sp`` ring holds one sequence shard of
Q/K/V; K/V blocks rotate around the ring via ``jax.lax.ppermute`` (ICI
neighbor traffic, no all-gather), and softmax is accumulated online
(flash-attention style running max / denominator), so the full [L, L] score
matrix never materializes and memory per chip stays O(L/sp · L/sp).

Two entry points:

* :func:`ring_attention_inner` — use inside an existing ``shard_map`` (this
  is what the sequence-parallel transformer binds as its ``attention_fn``);
* :func:`ring_attention` — standalone: shard_maps itself over ``axis_name``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.7
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )


def _block_attend(q, k, v, q_pos, k_pos, causal, m, l, o):
    """One K/V block's contribution under online softmax.

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; q_pos/k_pos: [Lq]/[Lk] global
    positions; (m, l, o): running (max [B,H,Lq], denom [B,H,Lq],
    out [B,Lq,H,D]) accumulators, all float32.
    """
    d = q.shape[-1]
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(d))
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]  # [Lq, Lk]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    block_max = jnp.max(scores, axis=-1)  # [B, H, Lq]
    new_m = jnp.maximum(m, block_max)
    # guard: rows with every position masked keep -inf max; exp(-inf - -inf)
    # would be nan, so shift by a finite max in that case
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    p = jnp.exp(scores - safe_m[..., None])  # [B, H, Lq, Lk]
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    correction = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
    correction = jnp.where(jnp.isfinite(m), correction, 0.0)  # first block: no history
    new_l = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhlm,bmhd->blhd", p, v.astype(jnp.float32))
    new_o = o * correction.transpose(0, 2, 1)[..., None] + pv
    return new_m, new_l, new_o


def ring_attention_inner(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    causal: bool = True,
) -> jnp.ndarray:
    """Exact attention where q/k/v are the LOCAL sequence shards [B, Ls, H, D]
    of a ring over ``axis_name``.  Must run inside shard_map."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Ls, H, D = q.shape
    m = jnp.full((B, H, Ls), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Ls), jnp.float32)
    o = jnp.zeros((B, Ls, H, D), jnp.float32)
    q_pos = my * Ls + jnp.arange(Ls)

    perm = [(i, (i + 1) % n) for i in range(n)]
    cur_k, cur_v = k, v
    for r in range(n):
        src = (my - r) % n  # ring shift r: the block originated on device my-r
        k_pos = src * Ls + jnp.arange(cur_k.shape[1])
        m, l, o = _block_attend(q, cur_k, cur_v, q_pos, k_pos, causal, m, l, o)
        if r < n - 1:
            # one collective for both operands (pytree ppermute)
            cur_k, cur_v = jax.lax.ppermute((cur_k, cur_v), axis_name, perm)
    denom = jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]  # [B, Lq, H, 1]
    return (o / denom).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
) -> jnp.ndarray:
    """Standalone ring attention: q/k/v are FULL [B, L, H, D] arrays; the
    sequence axis is sharded over ``axis_name`` and the result gathered."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        partial(ring_attention_inner, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
