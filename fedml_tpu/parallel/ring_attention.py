"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context capability the reference lacks entirely (SURVEY.md §5
"long-context/sequence parallelism: absent") but this framework treats as
first-class: each device in the ``sp`` ring holds one sequence shard of
Q/K/V; K/V blocks rotate around the ring via ``jax.lax.ppermute`` (ICI
neighbor traffic, no all-gather), and softmax is accumulated online
(flash-attention style running max / denominator), so the full [L, L] score
matrix never materializes and memory per chip stays O(L/sp · L/sp).

Two entry points:

* :func:`ring_attention_inner` — use inside an existing ``shard_map`` (this
  is what the sequence-parallel transformer binds as its ``attention_fn``);
* :func:`ring_attention` — standalone: shard_maps itself over ``axis_name``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.7
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )


# one canonical definition of the per-shard online-softmax math, shared
# with the pallas kernel's backward (ops/flash_attention.py)
from ..ops.flash_attention import shard_update_reference


def _block_attend(q, k, v, q_pos, k_pos, causal, m, l, o):
    """One K/V block's contribution under online softmax (the fused-XLA
    default block_fn; see :func:`shard_update_reference`)."""
    return shard_update_reference(q, k, v, q_pos, k_pos, causal, m, l, o)


def pallas_block_attend(q, k, v, q_pos, k_pos, causal, m, l, o,
                        block_q: int = 128, block_k: int = 128,
                        interpret: bool = False):
    """Drop-in for :func:`_block_attend` that folds the K/V shard through
    the pallas block-update kernel (ops/flash_attention.flash_shard_update):
    the ring moves shards over ICI via ppermute, the kernel does the
    per-chip block math in VMEM — the composed ring+flash design."""
    from ..ops.flash_attention import flash_shard_update

    return flash_shard_update(q, k, v, q_pos, k_pos, m, l, o, causal=causal,
                              block_q=block_q, block_k=block_k,
                              interpret=interpret)


def ring_attention_inner(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    causal: bool = True,
    block_fn=None,
) -> jnp.ndarray:
    """Exact attention where q/k/v are the LOCAL sequence shards [B, Ls, H, D]
    of a ring over ``axis_name``.  Must run inside shard_map.  ``block_fn``
    selects the per-shard update: the fused-XLA :func:`_block_attend`
    (default) or :func:`pallas_block_attend` (the flash kernel per chip)."""
    if block_fn is None:
        block_fn = _block_attend
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Ls, H, D = q.shape
    m = jnp.full((B, H, Ls), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Ls), jnp.float32)
    o = jnp.zeros((B, Ls, H, D), jnp.float32)
    q_pos = my * Ls + jnp.arange(Ls)

    perm = [(i, (i + 1) % n) for i in range(n)]
    cur_k, cur_v = k, v
    for r in range(n):
        src = (my - r) % n  # ring shift r: the block originated on device my-r
        k_pos = src * Ls + jnp.arange(cur_k.shape[1])
        m, l, o = block_fn(q, cur_k, cur_v, q_pos, k_pos, causal, m, l, o)
        if r < n - 1:
            # one collective for both operands (pytree ppermute)
            cur_k, cur_v = jax.lax.ppermute((cur_k, cur_v), axis_name, perm)
    denom = jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]  # [B, Lq, H, 1]
    return (o / denom).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
    block_fn=None,
) -> jnp.ndarray:
    """Standalone ring attention: q/k/v are FULL [B, L, H, D] arrays; the
    sequence axis is sharded over ``axis_name`` and the result gathered."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        partial(ring_attention_inner, axis_name=axis_name, causal=causal,
                block_fn=block_fn),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # only the pallas block_fn needs the relaxation (pallas_call outputs
        # can't declare vma); the default XLA path keeps strict checking
        check_vma=block_fn is None,
    )
    return fn(q, k, v)
