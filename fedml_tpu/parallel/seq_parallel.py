"""Sequence-parallel transformer execution.

Runs :class:`fedml_tpu.models.transformer.TransformerLM` with tokens sharded
over the mesh's ``sp`` axis: activations stay sequence-sharded through every
layer, attention is exact ring attention over ICI
(:mod:`.ring_attention`), and parameters are replicated (compose with a
``dp``/``tp`` axis for weight sharding).  RoPE uses absolute positions, so
each shard computes its rotary phases from its global offsets and no
cross-shard position fixup is needed.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.transformer import TransformerConfig, TransformerLM
from .ring_attention import ring_attention_inner, shard_map

Pytree = Any


def make_sp_model(cfg: TransformerConfig, axis_name: str = "sp") -> TransformerLM:
    """A TransformerLM whose attention is ring attention over ``axis_name``
    (only valid inside shard_map — use :func:`sp_apply` / :func:`sp_loss_fn`)."""
    return TransformerLM(
        cfg, attention_fn=partial(ring_attention_inner, axis_name=axis_name, causal=True)
    )


def sp_apply(
    cfg: TransformerConfig,
    params: Pytree,
    tokens: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
) -> jnp.ndarray:
    """Sequence-parallel forward: tokens [B, L] (L divisible by the axis
    size) -> logits [B, L, vocab], numerically equal to the single-device
    forward."""
    model = make_sp_model(cfg, axis_name)
    n = mesh.shape[axis_name]
    L = tokens.shape[1]
    assert L % n == 0, f"seq len {L} not divisible by sp={n}"

    def fwd(params, tok_shard):
        # global positions for this shard (RoPE needs absolute indices)
        idx = jax.lax.axis_index(axis_name)
        Ls = tok_shard.shape[1]
        positions = jnp.broadcast_to(idx * Ls + jnp.arange(Ls), tok_shard.shape)
        return model.apply(params, tok_shard, positions=positions)

    return shard_map(
        fwd,
        mesh=mesh,
        in_specs=(P(), P(None, axis_name)),
        out_specs=P(None, axis_name, None),
    )(params, tokens)


def sp_init(cfg: TransformerConfig, seed: int = 0, batch: int = 1) -> Pytree:
    """Initialize params for the sp model (init runs unsharded — shapes are
    identical; only the forward is sequence-parallel)."""
    model = TransformerLM(cfg)
    # no parameter shape depends on L (RoPE is stateless) — any short dummy
    # length initializes identical shapes
    tokens = jnp.zeros((batch, 8), jnp.int32)
    return model.init(jax.random.PRNGKey(seed), tokens)


def sp_loss_fn(
    cfg: TransformerConfig,
    mesh: Mesh,
    axis_name: str = "sp",
):
    """Next-token CE loss over the sequence-sharded forward; mean over all
    tokens (psum across shards).  Returns ``loss(params, tokens) -> scalar``
    — differentiable, so ``jax.grad`` gives sequence-parallel training."""
    model = make_sp_model(cfg, axis_name)

    def local_loss(params, tok_shard, tgt_shard):
        idx = jax.lax.axis_index(axis_name)
        Ls = tok_shard.shape[1]
        positions = jnp.broadcast_to(idx * Ls + jnp.arange(Ls), tok_shard.shape)
        logits = model.apply(params, tok_shard, positions=positions)
        import optax

        per = optax.softmax_cross_entropy_with_integer_labels(logits, tgt_shard)
        total = jax.lax.psum(jnp.sum(per), axis_name)
        count = jax.lax.psum(jnp.float32(per.size), axis_name)
        return total / count

    def loss(params, tokens, targets):
        fn = shard_map(
            local_loss,
            mesh=mesh,
            in_specs=(P(), P(None, axis_name), P(None, axis_name)),
            out_specs=P(),
        )
        return fn(params, tokens, targets)

    return loss
