"""Parallelism layer: device meshes, ring attention, sequence parallelism.

The TPU-native successor of the reference's process-group/NCCL plumbing
(SURVEY.md §2.10): scale axes are mesh axes, communication is XLA
collectives over ICI.
"""

from .mesh import create_fl_mesh, create_mesh, create_train_mesh, replicated, sharded
from .ring_attention import ring_attention, ring_attention_inner

__all__ = [
    "create_mesh",
    "create_fl_mesh",
    "create_train_mesh",
    "replicated",
    "sharded",
    "ring_attention",
    "ring_attention_inner",
]
