"""fedml_tpu — a TPU-native federated / distributed learning framework.

Brand-new implementation of the capability surface of FedML (reference
``python/fedml/__init__.py``), designed for JAX/XLA/pjit/pallas on TPU:

* **Simulation ("Parrot")**: in-process loop (sp) or the XLA in-mesh
  simulator — clients sharded over a ``jax.sharding.Mesh``, aggregation via
  ``lax.psum`` over ICI (successor of the reference's MPI/NCCL simulators).
* **Cross-silo ("Octopus")**: host-side gRPC/loopback message plane driving
  the same round protocol; intra-silo parallelism is a pjit mesh, not DDP.
* **Cross-device ("Beehive")**: server runtime + device protocol harness.
* core/: comm kernel, DP, security (attacks/defenses), MPC (SecAgg), topology,
  scheduling, MLOps-style observability.

Public API parity: ``fedml_tpu.init``, ``fedml_tpu.run_simulation``,
``fedml_tpu.run_cross_silo_server/client``, ``fedml_tpu.FedMLRunner``,
``fedml_tpu.data.load``, ``fedml_tpu.model.create``, ``device.get_device``.
"""

from __future__ import annotations

import logging
import os
import random as _random

import numpy as _np

__version__ = "0.1.0"

from . import constants  # noqa: F401
from .arguments import Arguments, load_arguments
from .runner import FedMLRunner  # noqa: F401
from . import data, device, models  # noqa: E402,F401  (public parity: fedml.data/.model/.device)

_logger = logging.getLogger(__name__)


def init(args: Arguments | None = None, should_init_logs: bool = True) -> Arguments:
    """Bootstrap (reference ``__init__.py:27-93``): load config, seed RNGs,
    init security/DP singletons, per-platform setup."""
    if args is None:
        args = load_arguments()
    if hasattr(args, "validate"):
        # validation is part of init, not an optional extra step: config
        # errors must surface HERE, and validate() also injects
        # cross-backend defaults (e.g. FedProx's mu) that every launch
        # path must see.  Idempotent, so pre-validated args are fine.
        args.validate(for_training=bool(getattr(args, "training_type", None)))
    if should_init_logs:
        logging.basicConfig(
            level=logging.INFO, format="[%(asctime)s %(name)s] %(message)s"
        )

    from .core import mlops as _mlops

    _mlops.pre_setup(args)
    if getattr(args, "using_mlops", False):
        _mlops.init(args)

    # multi-host mesh bootstrap (role of reference init_simulation_mpi /
    # torchrun env parsing + NCCL pg init, __init__.py:96,228-246): when a
    # coordinator is configured, join the jax.distributed cluster so
    # jax.devices() spans every host's chips and the same Mesh/shard_map
    # code runs pod-scale — collectives ride ICI within a slice and DCN
    # across hosts, inserted by XLA from the sharding annotations.
    coord = getattr(args, "jax_coordinator_address", None) or os.environ.get(
        "FEDML_JAX_COORDINATOR"
    )
    if coord:
        import jax as _jax

        # explicit args keys win over env (same convention as the cross-silo
        # env parse below) — and 0 is a VALID process id, so test `is None`
        n_proc = getattr(args, "jax_num_processes", None)
        if n_proc is None:
            n_proc = int(os.environ.get("FEDML_JAX_NUM_PROCESSES", 0) or 0)
        n_proc = int(n_proc)
        pid = getattr(args, "jax_process_id", None)
        if pid is None:
            pid = int(os.environ.get("FEDML_JAX_PROCESS_ID", 0) or 0)
        pid = int(pid)
        # idempotent: a process calling init() again (new Arguments, second
        # simulator) must not re-bootstrap the cluster
        if not _jax.distributed.is_initialized():
            _jax.distributed.initialize(
                coordinator_address=str(coord),
                num_processes=n_proc or None,
                process_id=pid if n_proc else None,
            )
            _logger.info("jax.distributed up: proc %d/%s via %s", pid, n_proc, coord)

    # multi-process-silo cross-silo: a launcher (torchrun-style or the
    # example main.py spawner) places each silo process by env — parse it
    # HERE so one config file serves every process of the silo (reference
    # init_cross_silo_hierarchical reads the torchrun env the same way,
    # __init__.py:217,228-246).  Gated on the platform, NOT on
    # scenario=='hierarchical': the adapter's pg plane activates on
    # n_proc_in_silo > 1 for any scenario, and n_proc itself may arrive by
    # env.  Explicit args keys win over env; empty env values are ignored.
    if str(getattr(args, "training_type", "")) == "cross_silo":
        for attr, envs in (
            ("proc_rank_in_silo", ("FEDML_PROC_RANK_IN_SILO", "LOCAL_RANK")),
            ("n_proc_in_silo", ("FEDML_N_PROC_IN_SILO", "LOCAL_WORLD_SIZE")),
        ):
            if getattr(args, attr, None) is None:
                for e in envs:
                    if os.environ.get(e):
                        setattr(args, attr, int(os.environ[e]))
                        break
        if getattr(args, "pg_master_address", None) is None and os.environ.get("MASTER_ADDR"):
            args.pg_master_address = os.environ["MASTER_ADDR"]
        if getattr(args, "pg_master_port", None) is None and os.environ.get("MASTER_PORT"):
            args.pg_master_port = int(os.environ["MASTER_PORT"])

    seed = int(getattr(args, "random_seed", 0))
    _random.seed(seed)
    # run-entry global seeding is the ONE approved global-RNG seam (the
    # reference does the same in fedml.init); library code must use local
    # generators — tools/lint_rng.py enforces this
    _np.random.seed(seed)  # lint_rng: allow

    from .core.dp.fedml_differential_privacy import FedMLDifferentialPrivacy
    from .core.security.fedml_attacker import FedMLAttacker
    from .core.security.fedml_defender import FedMLDefender

    FedMLAttacker.get_instance().init(args)
    FedMLDefender.get_instance().init(args)
    FedMLDifferentialPrivacy.get_instance().init(args)

    if not hasattr(args, "client_id_list"):
        # reference update_client_id_list (:265): synthesize [1..N]
        n = int(getattr(args, "client_num_in_total", 0) or 0)
        args.client_id_list = list(range(1, n + 1))
    _logger.info("fedml_tpu %s initialized (training_type=%s backend=%s)",
                 __version__, getattr(args, "training_type", None), getattr(args, "backend", None))
    return args


def run_simulation(backend: str = "sp") -> None:
    """One-liner (reference ``launch_simulation.py:9``)."""
    from . import data as _data_mod
    from . import device as _device_mod
    from . import models as _models_mod
    from .constants import FEDML_TRAINING_PLATFORM_SIMULATION

    args = load_arguments(FEDML_TRAINING_PLATFORM_SIMULATION, backend)
    args.training_type = FEDML_TRAINING_PLATFORM_SIMULATION
    args.backend = getattr(args, "backend", None) or backend
    args = init(args)
    device = _device_mod.get_device(args)
    dataset, output_dim = _data_mod.data_loader.load(args)
    model = _models_mod.hub.create(args, output_dim)
    runner = FedMLRunner(args, device, dataset, model)
    runner.run()


def run_mpi_simulation(config, world_size: int, port: int = 0,
                       deadline_s: float = 3600.0, retries: int = 2):
    """``mpirun -np N`` replacement (reference MPI simulator workflow): spawn
    ``world_size`` rank processes over the host-plane ProcessGroup and return
    rank 0's metrics.  ``config``: nested args dict (the YAML shape).

    Call from under ``if __name__ == "__main__":`` — ranks are spawned
    multiprocessing children, which re-import the caller's main module (the
    standard Python spawn contract; an unguarded top-level call would
    recursively re-launch itself in every child)."""
    from .simulation.mpi_proc import run_mpi_simulation as _run

    return _run(config, world_size, port=port, deadline_s=deadline_s,
                retries=retries)


def run_cross_silo_server() -> None:
    from .launch_cross_silo import run_cross_silo

    run_cross_silo(role="server")


def run_cross_silo_client() -> None:
    from .launch_cross_silo import run_cross_silo

    run_cross_silo(role="client")


def run_device_server():
    """Cross-device (Beehive) server one-liner (reference ``run_mnn_server``)."""
    from .launch_cross_device import run_device_server as _run

    return _run()


run_mnn_server = run_device_server
