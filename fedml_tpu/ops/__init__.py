"""TPU kernels (pallas) + fused-XLA fallbacks for the hot ops."""

from .flash_attention import attention, flash_attention, reference_attention

__all__ = ["attention", "flash_attention", "reference_attention"]
