"""Pallas flash attention (TPU kernel) with an XLA fallback.

The single-chip hot path of the transformer stack: blockwise attention with
online softmax.  Grid is (batch·heads, L/block_q, L/block_k) — TPU executes
the innermost grid dimension sequentially per core, so the running
(max, denom, out) accumulators live in VMEM scratch across k-steps and only
[block_q, D] / [block_k, D] tiles are VMEM-resident (never the full K/V, so
long contexts aren't VMEM-capped).  Composes with ring attention
(parallel/ring_attention.py): the ring moves K/V shards across chips via
ppermute and :func:`flash_shard_update` folds each shard into the running
online-softmax state per chip (wired as
``ring_attention(..., block_fn=pallas_block_attend)``).

Differentiation: a ``jax.custom_vjp`` over dedicated pallas backward
kernels — the forward additionally emits the per-row log-sum-exp, and the
backward re-materializes P blockwise from (q, k, lse) in two passes (a dQ
pass with k innermost, a dK/dV pass with q innermost), so backward memory
is O(block²) per core like the forward, never the O(L²) probs matrix.

``interpret=True`` runs the same kernel on CPU (how tests exercise it);
:func:`attention` picks the kernel on TPU and the fused-XLA reference
elsewhere, padding ragged sequence lengths to block multiples.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas imports fine everywhere; Mosaic lowering needs TPU
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def reference_attention(q, k, v, causal: bool = True):
    """Fused-XLA attention, [B, L, H, D] layout (fallback, test oracle, and
    the single fused-attention definition — models/transformer.py delegates
    here)."""
    d = q.shape[-1]
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) / jnp.sqrt(
        jnp.float32(d)
    )
    if causal:
        L, M = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((L, M), dtype=bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref, *,
                  block_q, block_k, n_kb, causal, scale, valid_len):
    """Grid cell (bh, qi, kj): fold K/V block kj into q block qi's online
    softmax state (scratch persists across the sequential kj dimension)."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # a causal block whose keys all lie after this q block's last row (or an
    # entirely-padded key block) contributes nothing — skip its FLOPs
    block_live = kj * block_k < valid_len
    if causal:
        block_live = jnp.logical_and(block_live, kj * block_k <= (qi + 1) * block_q - 1)

    @pl.when(block_live)
    def _attend():
        # matmuls stay in the input dtype (bf16 rides the MXU at full rate)
        # with f32 accumulation; softmax state is f32 throughout
        q = q_ref[0]  # [bq, D]
        k = k_ref[0]  # [bk, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        live = k_pos < valid_len  # padded tail keys never contribute
        if causal:
            live = live & (q_pos >= k_pos)
        s = jnp.where(live, s, -jnp.inf)

        m = m_ref[:]
        l = l_ref[:]
        block_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, block_max)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(s - safe_m[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        m_ref[:] = new_m
        l_ref[:] = l * corr + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * corr[:, None] + pv

    @pl.when(kj == n_kb - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-20)[:, None]).astype(o_ref.dtype)
        # per-row log-sum-exp of the SCALED scores — the softmax statistic
        # the backward kernels re-materialize P from (-inf for dead rows)
        l = l_ref[:]
        m = m_ref[:]
        lse_ref[0] = jnp.where(
            l > 0, jnp.where(jnp.isfinite(m), m, 0.0) + jnp.log(jnp.maximum(l, 1e-38)),
            -jnp.inf,
        )


def _pad_geometry(q, block_q, block_k):
    import math

    B, L, H, D = q.shape
    block_q = min(block_q, L)
    block_k = min(block_k, L)
    # pad to a common multiple of BOTH blocks: the grid is (Lp//block_q,
    # Lp//block_k), so a padded length only one block divides would silently
    # truncate the other axis (keys never folded in / rows never written)
    m = math.lcm(block_q, block_k)
    Lp = -(-L // m) * m
    return B, L, H, D, block_q, block_k, Lp


def _to_bh(x, B, L, H, D, Lp):  # [B, L, H, D] -> [B*H, Lp, D]
    x = x.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    if Lp != L:
        x = jnp.pad(x, ((0, 0), (0, Lp - L), (0, 0)))
    return x


def _from_bh(x, B, L, H, D):  # [B*H, Lp, D] -> [B, L, H, D]
    return x[:, :L].reshape(B, H, L, D).transpose(0, 2, 1, 3)


def _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                   with_lse: bool = False):
    if not _HAS_PALLAS:
        raise RuntimeError("pallas is unavailable in this jax build; use reference_attention")
    B, L, H, D, block_q, block_k, Lp = _pad_geometry(q, block_q, block_k)
    qb = _to_bh(q, B, L, H, D, Lp)
    kb = _to_bh(k, B, L, H, D, Lp)
    vb = _to_bh(v, B, L, H, D, Lp)
    scale = float(1.0 / (D**0.5))  # python float: traced scalars can't be closed over
    n_kb = Lp // block_k
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, n_kb=n_kb,
        causal=causal, scale=scale, valid_len=L,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Lp // block_q, n_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lp, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Lp), jnp.float32),
        ],
        scratch_shapes=_scratch(block_q, D),
        interpret=interpret,
    )(qb, kb, vb)
    out = _from_bh(out, B, L, H, D)
    return (out, lse) if with_lse else out


def _block_grads(q, k, v, do, lse, delta, qi, kj, *, block_q, block_k, causal,
                 scale, valid_len):
    """Shared backward block math: re-materialize this (qi, kj) block's probs
    P from (q, k, lse) and form dS — used identically by the dQ and dK/dV
    kernels so the two gradients cannot desynchronize.

    ``lse`` is finite for any q row that attends >=1 live key — which
    includes padded q-tail rows (the live mask constrains keys, not
    queries).  Padded-tail GRADIENT correctness therefore rests on dO (and
    hence delta) being zero-padded by _to_bh, not on lse masking; the
    isfinite guard only covers rows with no live keys at all (e.g. the
    first rows of a fully-masked causal block)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    live = (k_pos < valid_len) & jnp.isfinite(lse)[:, None]
    if causal:
        live = live & (q_pos >= k_pos)
    p = jnp.where(live, jnp.exp(s - jnp.where(jnp.isfinite(lse), lse, 0.0)[:, None]), 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [bq, bk]
    ds = p * (dp - delta[:, None]) * scale
    return p, ds


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, acc_ref, *, block_q, block_k, n_kb, causal,
                         scale, valid_len):
    """Grid cell (bh, qi, kj): accumulate q block qi's gradient over k blocks
    (sequential innermost kj; acc persists in VMEM scratch)."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    block_live = kj * block_k < valid_len
    if causal:
        block_live = jnp.logical_and(block_live, kj * block_k <= (qi + 1) * block_q - 1)

    @pl.when(block_live)
    def _accum():
        k = k_ref[0]
        _, ds = _block_grads(
            q_ref[0], k, v_ref[0], do_ref[0], lse_ref[0], delta_ref[0], qi, kj,
            block_q=block_q, block_k=block_k, causal=causal, scale=scale,
            valid_len=valid_len,
        )
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == n_kb - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, block_q, block_k,
                          n_qb, causal, scale, valid_len):
    """Grid cell (bh, kj, qi): accumulate k/v block kj's gradients over q
    blocks (sequential innermost qi)."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    block_live = qi * block_q < valid_len
    if causal:
        # p is zero wherever q_pos < k_pos: skip q blocks entirely above kj
        block_live = jnp.logical_and(block_live, (qi + 1) * block_q - 1 >= kj * block_k)

    @pl.when(block_live)
    def _accum():
        q = q_ref[0]
        do = do_ref[0]
        p, ds = _block_grads(
            q, k_ref[0], v_ref[0], do, lse_ref[0], delta_ref[0], qi, kj,
            block_q=block_q, block_k=block_k, causal=causal, scale=scale,
            valid_len=valid_len,
        )
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, D]
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == n_qb - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k, interpret):
    """Pallas flash backward: same blockwise structure as the forward — P is
    re-materialized per block from (q, k, lse), so backward memory is
    O(block² ) per core instead of the O(L²) probs matrix."""
    B, L, H, D, block_q, block_k, Lp = _pad_geometry(q, block_q, block_k)
    qb = _to_bh(q, B, L, H, D, Lp)
    kb = _to_bh(k, B, L, H, D, Lp)
    vb = _to_bh(v, B, L, H, D, Lp)
    dob = _to_bh(g.astype(q.dtype), B, L, H, D, Lp)
    ob = _to_bh(out, B, L, H, D, Lp)
    # delta_i = rowsum(dO * O): tiny elementwise pass, fused by XLA
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32), axis=-1)
    scale = float(1.0 / (D**0.5))
    n_qb, n_kb = Lp // block_q, Lp // block_k
    row_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),  # q
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),  # k
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),  # v
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),  # dO
        pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),        # lse
        pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),        # delta
    ]
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_q=block_q, block_k=block_k, n_kb=n_kb,
            causal=causal, scale=scale, valid_len=L,
        ),
        grid=(B * H, n_qb, n_kb),
        in_specs=row_specs,
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Lp, D), q.dtype),
        scratch_shapes=[_vmem((block_q, D), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)
    col_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),  # q
        pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),  # k
        pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),  # v
        pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),  # dO
        pl.BlockSpec((1, block_q), lambda b, j, i: (b, i)),        # lse
        pl.BlockSpec((1, block_q), lambda b, j, i: (b, i)),        # delta
    ]
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, block_q=block_q, block_k=block_k, n_qb=n_qb,
            causal=causal, scale=scale, valid_len=L,
        ),
        grid=(B * H, n_kb, n_qb),
        in_specs=col_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lp, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Lp, D), q.dtype),
        ],
        scratch_shapes=[_vmem((block_k, D), jnp.float32),
                        _vmem((block_k, D), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)
    return (_from_bh(dq, B, L, H, D), _from_bh(dk, B, L, H, D),
            _from_bh(dv, B, L, H, D))


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


def _scratch(block_q, D):
    return [
        _vmem((block_q,), jnp.float32),
        _vmem((block_q,), jnp.float32),
        _vmem((block_q, D), jnp.float32),
    ]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas blockwise attention. q/k/v: [B, L, H, D] -> [B, L, H, D].
    Ragged L is padded to a block multiple internally."""
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret,
                              with_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k,
                           interpret)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def shard_update_reference(q, k, v, q_pos, k_pos, causal, m, l, o):
    """Fused-XLA online-softmax shard update — the SINGLE canonical
    definition of ring attention's per-shard math (parallel/ring_attention
    aliases this as ``_block_attend``), and the recompute path for
    :func:`flash_shard_update`'s backward.

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; q_pos/k_pos: [Lq]/[Lk] global
    positions; (m, l, o): running (max [B,H,Lq], denom [B,H,Lq],
    UNNORMALIZED out [B,Lq,H,D]) accumulators, all float32."""
    d = q.shape[-1]
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(d))
    live = (k_pos >= 0)[None, :]  # k_pos < 0 marks padding
    if causal:
        live = live & (q_pos[:, None] >= k_pos[None, :])
    scores = jnp.where(live[None, None], scores, -jnp.inf)
    block_max = jnp.max(scores, axis=-1)  # [B, H, Lq]
    new_m = jnp.maximum(m, block_max)
    # guard: rows with every position masked keep -inf max; exp(-inf - -inf)
    # would be nan, so shift by a finite max in that case
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    p = jnp.exp(scores - safe_m[..., None])  # [B, H, Lq, Lk]
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    correction = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
    correction = jnp.where(jnp.isfinite(m), correction, 0.0)  # first block: no history
    new_l = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhlm,bmhd->blhd", p, v.astype(jnp.float32))
    new_o = o * correction.transpose(0, 2, 1)[..., None] + pv
    return new_m, new_l, new_o


def _flash_update_kernel(q_ref, k_ref, v_ref, qp_ref, kp_ref, mi_ref, li_ref,
                         oi_ref, mo_ref, lo_ref, oo_ref, m_s, l_s, acc_s, *,
                         block_q, block_k, n_kb, causal, scale):
    """Grid cell (bh, qi, kj): fold K/V block kj into the RUNNING online-
    softmax state (m, l, unnormalized o) carried in from outside — the
    per-chip block update of ring attention.  Positions come from the
    q_pos/k_pos arrays (global ring offsets), not program ids; k_pos < 0
    marks padding and is always dead."""
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _seed():
        m_s[:] = mi_ref[0]
        l_s[:] = li_ref[0]
        acc_s[:] = oi_ref[0].astype(jnp.float32)

    q_pos = qp_ref[0]  # [bq] i32
    k_pos = kp_ref[0]  # [bk] i32
    # dead-block skip (mirrors _flash_kernel's block_live): an all-padded
    # key block, or a causal block whose earliest live key lies after this
    # q block's last row, contributes nothing — skip both matmuls
    any_live_key = jnp.any(k_pos >= 0)
    block_live = any_live_key
    if causal:
        first_live_k = jnp.min(jnp.where(k_pos >= 0, k_pos, 2**30))
        block_live = jnp.logical_and(block_live, jnp.max(q_pos) >= first_live_k)

    @pl.when(block_live)
    def _attend():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        live = (k_pos >= 0)[None, :]
        if causal:
            live = live & (q_pos[:, None] >= k_pos[None, :])
        s = jnp.where(live, s, -jnp.inf)
        m = m_s[:]
        l = l_s[:]
        block_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, block_max)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(s - safe_m[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        m_s[:] = new_m
        l_s[:] = l * corr + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_s[:] = acc_s[:] * corr[:, None] + pv

    @pl.when(kj == n_kb - 1)
    def _finish():
        mo_ref[0] = m_s[:]
        lo_ref[0] = l_s[:]
        oo_ref[0] = acc_s[:].astype(oo_ref.dtype)


def _flash_shard_update_impl(q, k, v, q_pos, k_pos, m, l, o, causal,
                            block_q, block_k, interpret):
    """Pallas block update for ring attention: fold ONE K/V shard into the
    running (m, l, unnormalized o) online-softmax state.

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; q_pos/k_pos: [Lq]/[Lk] global
    positions (i32); m, l: [B, H, Lq] f32; o: [B, Lq, H, D] f32
    (UNNORMALIZED accumulator).  Returns updated (m, l, o) — the exact
    math of :func:`fedml_tpu.parallel.ring_attention._block_attend`, block
    by block in VMEM.  Pallas-kernel side of the ring+flash composition:
    the ring moves K/V shards over ICI, this folds each shard locally."""
    if not _HAS_PALLAS:
        raise RuntimeError("pallas is unavailable in this jax build")
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    block_q = min(block_q, Lq)
    block_k = min(block_k, Lk)
    # q and k pad independently here: the grid axes are separate, so no
    # common-multiple constraint (unlike _pad_geometry's shared L)
    Lqp = -(-Lq // block_q) * block_q
    Lkp = -(-Lk // block_k) * block_k

    qb = _to_bh(q, B, Lq, H, D, Lqp)
    kb = _to_bh(k, B, Lk, H, D, Lkp)
    vb = _to_bh(v, B, Lk, H, D, Lkp)
    qp = jnp.pad(q_pos.astype(jnp.int32), (0, Lqp - Lq))[None, :]
    kp = jnp.pad(k_pos.astype(jnp.int32), (0, Lkp - Lk),
                 constant_values=-1)[None, :]  # padded keys: always dead
    mb = jnp.pad(m.reshape(B * H, Lq), ((0, 0), (0, Lqp - Lq)),
                 constant_values=-jnp.inf)
    lb = jnp.pad(l.reshape(B * H, Lq), ((0, 0), (0, Lqp - Lq)))
    ob = _to_bh(o, B, Lq, H, D, Lqp)
    scale = float(1.0 / (D**0.5))
    n_kb = Lkp // block_k
    kernel = functools.partial(
        _flash_update_kernel, block_q=block_q, block_k=block_k, n_kb=n_kb,
        causal=causal, scale=scale,
    )
    mo, lo, oo = pl.pallas_call(
        kernel,
        grid=(B * H, Lqp // block_q, n_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),   # q
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),   # k
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),   # v
            pl.BlockSpec((1, block_q), lambda b, i, j: (0, i)),         # q_pos
            pl.BlockSpec((1, block_k), lambda b, i, j: (0, j)),         # k_pos
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),         # m in
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),         # l in
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),   # o in
        ],
        out_specs=[
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lqp), jnp.float32),
            jax.ShapeDtypeStruct((B * H, Lqp), jnp.float32),
            jax.ShapeDtypeStruct((B * H, Lqp, D), jnp.float32),
        ],
        scratch_shapes=_scratch(block_q, D),
        interpret=interpret,
    )(qb, kb, vb, qp, kp, mb, lb, ob)
    m_out = mo[:, :Lq].reshape(B, H, Lq)
    l_out = lo[:, :Lq].reshape(B, H, Lq)
    o_out = _from_bh(oo, B, Lq, H, D)
    return m_out, l_out, o_out


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11))
def _flash_shard_update_vjp(q, k, v, q_pos, k_pos, m, l, o, causal, block_q,
                            block_k, interpret):
    return _flash_shard_update_impl(q, k, v, q_pos, k_pos, m, l, o, causal,
                                    block_q, block_k, interpret)


def _shard_update_fwd(q, k, v, q_pos, k_pos, m, l, o, causal, block_q,
                      block_k, interpret):
    out = _flash_shard_update_impl(q, k, v, q_pos, k_pos, m, l, o, causal,
                                   block_q, block_k, interpret)
    return out, (q, k, v, q_pos, k_pos, m, l, o)


def _shard_update_bwd(causal, block_q, block_k, interpret, res, g):
    # exact gradients by recomputing through the canonical XLA update (the
    # same trade the main kernel made before its dedicated backward): the
    # composed ring+pallas path stays trainable
    import numpy as np

    q, k, v, q_pos, k_pos, m, l, o = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_, m_, l_, o_: shard_update_reference(
            q_, k_, v_, q_pos, k_pos, causal, m_, l_, o_
        ),
        q, k, v, m, l, o,
    )
    dq, dk, dv, dm, dl, do = vjp(g)
    zq = np.zeros(q_pos.shape, dtype=jax.dtypes.float0)  # int positions
    zk = np.zeros(k_pos.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, zq, zk, dm, dl, do


_flash_shard_update_vjp.defvjp(_shard_update_fwd, _shard_update_bwd)


def flash_shard_update(q, k, v, q_pos, k_pos, m, l, o, causal: bool = True,
                       block_q: int = 128, block_k: int = 128,
                       interpret: bool = False):
    """Differentiable pallas shard update (see _flash_shard_update_impl for
    the kernel): forward in VMEM blocks, backward by exact recompute through
    :func:`shard_update_reference`."""
    return _flash_shard_update_vjp(q, k, v, q_pos, k_pos, m, l, o, causal,
                                   block_q, block_k, interpret)


def _on_tpu() -> bool:
    """True when the default backend is TPU hardware — including tunneled
    PJRT plugins whose *platform name* is not literally 'tpu' (the axon
    backend reports its own name; the device kind still says TPU)."""
    if jax.default_backend() == "tpu":
        return True
    try:
        d = jax.devices()[0]
    except Exception:  # pragma: no cover - no backend at all
        return False
    return "tpu" in (getattr(d, "platform", "") or "").lower() or (
        "tpu" in (getattr(d, "device_kind", "") or "").lower()
    )


def attention(q, k, v, causal: bool = True):
    """Dispatch: pallas kernel on TPU, XLA reference elsewhere."""
    if _HAS_PALLAS and _on_tpu():
        return flash_attention(q, k, v, causal=causal)
    return reference_attention(q, k, v, causal=causal)
