"""Pallas flash attention (TPU kernel) with an XLA fallback.

The single-chip hot path of the transformer stack: blockwise attention with
online softmax.  Grid is (batch·heads, L/block_q, L/block_k) — TPU executes
the innermost grid dimension sequentially per core, so the running
(max, denom, out) accumulators live in VMEM scratch across k-steps and only
[block_q, D] / [block_k, D] tiles are VMEM-resident (never the full K/V, so
long contexts aren't VMEM-capped).  Composes with ring attention
(parallel/ring_attention.py): ring moves K/V shards across chips, this
kernel does the per-chip block math.

Differentiation: a ``jax.custom_vjp`` whose backward recomputes through the
fused-XLA reference — exact gradients, O(L²) memory on the backward only (a
dedicated pallas backward kernel is the planned upgrade).

``interpret=True`` runs the same kernel on CPU (how tests exercise it);
:func:`attention` picks the kernel on TPU and the fused-XLA reference
elsewhere, padding ragged sequence lengths to block multiples.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas imports fine everywhere; Mosaic lowering needs TPU
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def reference_attention(q, k, v, causal: bool = True):
    """Fused-XLA attention, [B, L, H, D] layout (fallback, test oracle, and
    the single fused-attention definition — models/transformer.py delegates
    here)."""
    d = q.shape[-1]
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) / jnp.sqrt(
        jnp.float32(d)
    )
    if causal:
        L, M = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((L, M), dtype=bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  block_q, block_k, n_kb, causal, scale, valid_len):
    """Grid cell (bh, qi, kj): fold K/V block kj into q block qi's online
    softmax state (scratch persists across the sequential kj dimension)."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # a causal block whose keys all lie after this q block's last row (or an
    # entirely-padded key block) contributes nothing — skip its FLOPs
    block_live = kj * block_k < valid_len
    if causal:
        block_live = jnp.logical_and(block_live, kj * block_k <= (qi + 1) * block_q - 1)

    @pl.when(block_live)
    def _attend():
        # matmuls stay in the input dtype (bf16 rides the MXU at full rate)
        # with f32 accumulation; softmax state is f32 throughout
        q = q_ref[0]  # [bq, D]
        k = k_ref[0]  # [bk, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        live = k_pos < valid_len  # padded tail keys never contribute
        if causal:
            live = live & (q_pos >= k_pos)
        s = jnp.where(live, s, -jnp.inf)

        m = m_ref[:]
        l = l_ref[:]
        block_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, block_max)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(s - safe_m[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        m_ref[:] = new_m
        l_ref[:] = l * corr + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * corr[:, None] + pv

    @pl.when(kj == n_kb - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-20)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    if not _HAS_PALLAS:
        raise RuntimeError("pallas is unavailable in this jax build; use reference_attention")
    B, L, H, D = q.shape
    block_q = min(block_q, L)
    block_k = min(block_k, L)
    Lp = -(-L // max(block_q, block_k)) * max(block_q, block_k)

    def to_bh(x):  # [B, L, H, D] -> [B*H, Lp, D]
        x = x.transpose(0, 2, 1, 3).reshape(B * H, L, D)
        if Lp != L:
            x = jnp.pad(x, ((0, 0), (0, Lp - L), (0, 0)))
        return x

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    scale = float(1.0 / (D**0.5))  # python float: traced scalars can't be closed over
    n_kb = Lp // block_k
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, n_kb=n_kb,
        causal=causal, scale=scale, valid_len=L,
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Lp // block_q, n_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Lp, D), q.dtype),
        scratch_shapes=_scratch(block_q, D),
        interpret=interpret,
    )(qb, kb, vb)
    out = out[:, :L]
    return out.reshape(B, H, L, D).transpose(0, 2, 1, 3)


def _scratch(block_q, D):
    from jax.experimental.pallas import tpu as pltpu

    return [
        pltpu.VMEM((block_q,), jnp.float32),
        pltpu.VMEM((block_q,), jnp.float32),
        pltpu.VMEM((block_q, D), jnp.float32),
    ]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas blockwise attention. q/k/v: [B, L, H, D] -> [B, L, H, D].
    Ragged L is padded to a block multiple internally."""
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    # exact gradients via the fused-XLA reference (recompute; O(L^2) memory
    # on the backward pass only — pallas backward kernel is the upgrade path)
    _, vjp = jax.vjp(lambda q, k, v: reference_attention(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def attention(q, k, v, causal: bool = True):
    """Dispatch: pallas kernel on TPU, XLA reference elsewhere."""
    if _HAS_PALLAS and jax.default_backend() == "tpu":
        return flash_attention(q, k, v, causal=causal)
    return reference_attention(q, k, v, causal=causal)
