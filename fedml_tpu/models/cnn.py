"""CNN zoo slice (flax.linen).

Counterparts of reference ``model/cv/cnn.py``:
* ``CNN_DropOut`` — the FedAvg-paper 2conv+2fc CNN used for (Fed)EMNIST
  (``only_digits`` switches 10 vs 62 classes), reference ``cnn.py:6-76``.
* ``CNN_WEB`` — small MNIST CNN.
NHWC layout + channels-last convs (TPU-native; XLA tiles these onto the MXU).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CNN_DropOut(nn.Module):
    only_digits: bool = True
    num_classes: int = 0  # 0 -> derive from only_digits (10/62)

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:  # [B, H, W] -> [B, H, W, 1]
            x = x[..., None]
        x = nn.Conv(32, (3, 3), padding="VALID", name="conv2d_1")(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), padding="VALID", name="conv2d_2")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, name="dense_1")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        head = self.num_classes or (10 if self.only_digits else 62)
        return nn.Dense(head, name="dense_2")(x)


class CNN_WEB(nn.Module):
    """Compact MNIST CNN (reference cnn.py:79-119 analog)."""

    output_dim: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.relu(nn.Conv(32, (5, 5), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (5, 5), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512)(x))
        return nn.Dense(self.output_dim)(x)
