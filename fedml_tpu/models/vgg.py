"""VGG (flax.linen) — counterpart of reference ``model/cv/vgg.py``."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

_CFG = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512, "M"],
}


class VGG(nn.Module):
    num_classes: int = 10
    depth: int = 16

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        for i, v in enumerate(_CFG[self.depth]):
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.relu(nn.Conv(int(v), (3, 3), padding="SAME", name=f"conv{i}")(x))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.relu(nn.Dense(512, name="fc1")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, name="classifier")(x)
