"""U-Net for federated semantic segmentation (FedSeg).

Role of reference ``simulation/mpi/fedseg``'s DeepLab/backbone models
(``model/cv/``): an encoder-decoder with skip connections producing per-pixel
class logits.  Group norm, compact widths — sized so 100-client FL rounds fit
comfortably in HBM next to the data."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def _gn(c: int):
    return nn.GroupNorm(num_groups=min(8, c))


class _ConvBlock(nn.Module):
    width: int

    @nn.compact
    def __call__(self, x):
        x = nn.relu(_gn(self.width)(nn.Conv(self.width, (3, 3), padding="SAME")(x)))
        x = nn.relu(_gn(self.width)(nn.Conv(self.width, (3, 3), padding="SAME")(x)))
        return x


class UNet(nn.Module):
    """Input [B, H, W, C] -> logits [B, H, W, num_classes] (H, W div by 4)."""

    num_classes: int
    width: int = 16

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = self.width
        e1 = _ConvBlock(w)(x)                                       # H
        e2 = _ConvBlock(w * 2)(nn.max_pool(e1, (2, 2), strides=(2, 2)))  # H/2
        b = _ConvBlock(w * 4)(nn.max_pool(e2, (2, 2), strides=(2, 2)))   # H/4
        u2 = nn.ConvTranspose(w * 2, (2, 2), strides=(2, 2))(b)     # H/2
        d2 = _ConvBlock(w * 2)(jnp.concatenate([u2, e2], axis=-1))
        u1 = nn.ConvTranspose(w, (2, 2), strides=(2, 2))(d2)        # H
        d1 = _ConvBlock(w)(jnp.concatenate([u1, e1], axis=-1))
        return nn.Conv(self.num_classes, (1, 1))(d1)


def iou_counts(logits: jnp.ndarray, masks: jnp.ndarray, num_classes: int):
    """Per-class (intersection, union) pixel counts — accumulate these across
    batches and divide once for dataset-level mIoU (batch-mean mIoU is biased
    when classes are sparse)."""
    pred = jnp.argmax(logits, axis=-1)
    inter = jnp.stack([jnp.sum((pred == c) & (masks == c)) for c in range(num_classes)])
    union = jnp.stack([jnp.sum((pred == c) | (masks == c)) for c in range(num_classes)])
    return inter, union


def mean_iou(logits: jnp.ndarray, masks: jnp.ndarray, num_classes: int) -> jnp.ndarray:
    """Mean intersection-over-union over classes present in target or pred."""
    pred = jnp.argmax(logits, axis=-1)
    ious = []
    for c in range(num_classes):
        p = pred == c
        t = masks == c
        inter = jnp.sum(p & t)
        union = jnp.sum(p | t)
        ious.append(jnp.where(union > 0, inter / jnp.maximum(union, 1), jnp.nan))
    ious = jnp.stack(ious)
    return jnp.nanmean(ious)
