"""MobileNet family (flax.linen).

Counterparts of reference ``model/cv/mobilenet.py`` (MobileNetV1, the
CIFAR benchmark rows BENCHMARK_MPI.md:104-106) and ``mobilenet_v3.py``.
Depthwise convs via ``feature_group_count`` — XLA lowers these to efficient
TPU convolutions.  GroupNorm default for FL friendliness (see resnet.py).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class _DWSeparable(nn.Module):
    filters: int
    stride: int = 1
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        x = nn.Conv(in_ch, (3, 3), strides=(self.stride, self.stride), padding="SAME",
                    feature_group_count=in_ch, use_bias=False, name="dw")(x)
        x = _norm_layer(self.norm, "dw_norm", train)(x)
        x = nn.relu(x)
        x = nn.Conv(self.filters, (1, 1), use_bias=False, name="pw")(x)
        x = _norm_layer(self.norm, "pw_norm", train)(x)
        return nn.relu(x)


def _norm_layer(norm: str, name: str, train: bool):
    if norm == "bn":
        return nn.BatchNorm(use_running_average=not train, momentum=0.9, name=name)
    return nn.GroupNorm(num_groups=None, group_size=8, name=name)


class MobileNetV1(nn.Module):
    num_classes: int = 10
    width: float = 1.0
    norm: str = "gn"
    small_images: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        w = lambda c: max(8, int(c * self.width))
        stride0 = 1 if self.small_images else 2
        x = nn.Conv(w(32), (3, 3), strides=(stride0, stride0), padding="SAME",
                    use_bias=False, name="conv_init")(x)
        x = _norm_layer(self.norm, "norm_init", train)(x)
        x = nn.relu(x)
        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2), (1024, 1)]
        for i, (c, s) in enumerate(cfg):
            x = _DWSeparable(w(c), s, self.norm, name=f"block{i}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, name="classifier")(x)


class _SEBlock(nn.Module):
    reduce: int = 4

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(max(c // self.reduce, 8))(s))
        s = nn.hard_sigmoid(nn.Dense(c)(s))
        return x * s[:, None, None, :]


class _MBV3Block(nn.Module):
    expand: int
    filters: int
    kernel: int
    stride: int
    use_se: bool
    act: str  # "relu" | "hswish"
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        act = nn.relu if self.act == "relu" else nn.hard_swish
        inp = x
        c_in = x.shape[-1]
        x = nn.Conv(self.expand, (1, 1), use_bias=False)(x)
        x = _norm_layer(self.norm, "expand_norm", train)(x)
        x = act(x)
        x = nn.Conv(self.expand, (self.kernel, self.kernel), strides=(self.stride, self.stride),
                    padding="SAME", feature_group_count=self.expand, use_bias=False)(x)
        x = _norm_layer(self.norm, "dw_norm", train)(x)
        x = act(x)
        if self.use_se:
            x = _SEBlock()(x)
        x = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        x = _norm_layer(self.norm, "project_norm", train)(x)
        if self.stride == 1 and c_in == self.filters:
            x = x + inp
        return x


class MobileNetV3Small(nn.Module):
    num_classes: int = 10
    norm: str = "gn"
    small_images: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        stride0 = 1 if self.small_images else 2
        x = nn.Conv(16, (3, 3), strides=(stride0, stride0), padding="SAME", use_bias=False)(x)
        x = _norm_layer(self.norm, "norm_init", train)(x)
        x = nn.hard_swish(x)
        cfg = [  # expand, filters, kernel, stride, se, act
            (16, 16, 3, 2, True, "relu"),
            (72, 24, 3, 2, False, "relu"),
            (88, 24, 3, 1, False, "relu"),
            (96, 40, 5, 2, True, "hswish"),
            (240, 40, 5, 1, True, "hswish"),
            (240, 40, 5, 1, True, "hswish"),
            (120, 48, 5, 1, True, "hswish"),
            (144, 48, 5, 1, True, "hswish"),
            (288, 96, 5, 2, True, "hswish"),
            (576, 96, 5, 1, True, "hswish"),
            (576, 96, 5, 1, True, "hswish"),
        ]
        for i, (e, f, k, s, se, act) in enumerate(cfg):
            x = _MBV3Block(e, f, k, s, se, act, self.norm, name=f"block{i}")(x, train=train)
        x = nn.Conv(576, (1, 1), use_bias=False)(x)
        x = _norm_layer(self.norm, "norm_head", train)(x)
        x = nn.hard_swish(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.hard_swish(nn.Dense(1024)(x))
        return nn.Dense(self.num_classes, name="classifier")(x)
