"""CIFAR-style ResNets (flax.linen).

Counterparts of reference ``model/cv/resnet.py`` (ResNet-20/32/44/56 for
CIFAR, used by the headline benchmark CIFAR-10 ResNet-56 93.19 IID,
BENCHMARK_MPI.md:101) and ``model/cv/resnet_gn.py`` (ResNet-18 + GroupNorm
for fed_cifar100, BENCHMARK_MPI.md:51).

TPU-first notes: NHWC layout, 3x3 convs XLA maps straight onto the MXU;
``norm='gn'`` keeps the model purely functional (no mutable batch stats),
which is also the FL-correct choice (BN running stats average badly across
non-IID clients — the reason the reference ships a GN variant).  ``norm='bn'``
is supported for strict parity; its ``batch_stats`` collection is carried in
the model state and sample-weight-averaged like parameters.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


def _norm(norm: str, name: str, train: bool, dtype=jnp.float32):
    if norm == "bn":
        return nn.BatchNorm(use_running_average=not train, momentum=0.9, name=name,
                            dtype=dtype)
    if norm == "gn":
        return nn.GroupNorm(num_groups=None, group_size=16, name=name, dtype=dtype)
    raise ValueError(f"unknown norm {norm!r}")


class BasicBlock(nn.Module):
    filters: int
    stride: int = 1
    norm: str = "gn"
    dtype: Any = jnp.float32  # compute dtype; params stay fp32 (mixed precision)

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.stride, self.stride),
                    padding="SAME", use_bias=False, name="conv1", dtype=self.dtype)(x)
        y = _norm(self.norm, "norm1", train, self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False, name="conv2",
                    dtype=self.dtype)(y)
        y = _norm(self.norm, "norm2", train, self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), strides=(self.stride, self.stride),
                               use_bias=False, name="proj", dtype=self.dtype)(residual)
            residual = _norm(self.norm, "norm_proj", train, self.dtype)(residual)
        return nn.relu(y + residual)


class CifarResNet(nn.Module):
    """3-stage CIFAR ResNet: depth = 6n+2 (n blocks/stage, 16/32/64 filters)."""

    num_blocks: int  # n: 3 -> ResNet-20, 9 -> ResNet-56
    num_classes: int = 10
    norm: str = "gn"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False, name="conv_init",
                    dtype=self.dtype)(x)
        x = _norm(self.norm, "norm_init", train, self.dtype)(x)
        x = nn.relu(x)
        for stage, filters in enumerate((16, 32, 64)):
            for block in range(self.num_blocks):
                stride = 2 if (stage > 0 and block == 0) else 1
                x = BasicBlock(filters, stride, self.norm, self.dtype,
                               name=f"stage{stage}_block{block}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, name="classifier", dtype=self.dtype)(x)


class ResNet18(nn.Module):
    """ImageNet-style ResNet-18, GroupNorm default (fed_cifar100 row)."""

    num_classes: int = 100
    norm: str = "gn"
    small_images: bool = True  # CIFAR: 3x3 stem, no max-pool
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        if self.small_images:
            x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False, name="conv_init",
                        dtype=self.dtype)(x)
        else:
            x = nn.Conv(64, (7, 7), strides=(2, 2), padding="SAME", use_bias=False,
                        name="conv_init", dtype=self.dtype)(x)
        x = _norm(self.norm, "norm_init", train, self.dtype)(x)
        x = nn.relu(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, filters in enumerate((64, 128, 256, 512)):
            for block in range(2):
                stride = 2 if (stage > 0 and block == 0) else 1
                x = BasicBlock(filters, stride, self.norm, self.dtype,
                               name=f"stage{stage}_block{block}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, name="classifier", dtype=self.dtype)(x)


def resnet20(num_classes: int = 10, norm: str = "gn", dtype=jnp.float32) -> CifarResNet:
    return CifarResNet(num_blocks=3, num_classes=num_classes, norm=norm, dtype=dtype)


def resnet56(num_classes: int = 10, norm: str = "gn", dtype=jnp.float32) -> CifarResNet:
    return CifarResNet(num_blocks=9, num_classes=num_classes, norm=norm, dtype=dtype)


def resnet18_gn(num_classes: int = 100, dtype=jnp.float32) -> ResNet18:
    return ResNet18(num_classes=num_classes, norm="gn", dtype=dtype)
