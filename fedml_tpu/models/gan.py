"""MNIST GAN pair — counterpart of reference ``model/cv/generator.py`` /
``discriminator.py`` (used by the FedGAN algorithm, simulation/mpi/fedgan/)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MNISTGenerator(nn.Module):
    latent_dim: int = 100

    @nn.compact
    def __call__(self, z, train: bool = False):
        x = nn.relu(nn.Dense(7 * 7 * 128, name="fc")(z))
        x = x.reshape((z.shape[0], 7, 7, 128))
        x = nn.ConvTranspose(64, (4, 4), strides=(2, 2), padding="SAME", name="deconv1")(x)
        x = nn.relu(x)
        x = nn.ConvTranspose(1, (4, 4), strides=(2, 2), padding="SAME", name="deconv2")(x)
        return nn.tanh(x)


class MNISTDiscriminator(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.leaky_relu(nn.Conv(64, (4, 4), strides=(2, 2), padding="SAME", name="conv1")(x), 0.2)
        x = nn.leaky_relu(nn.Conv(128, (4, 4), strides=(2, 2), padding="SAME", name="conv2")(x), 0.2)
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(1, name="head")(x)
