"""FedGKT model pair (reference ``simulation/mpi/fedgkt/model_hub.py:49-52``:
ResNet-8 edge model + ResNet-55 server model).

The client net is a small conv feature extractor + auxiliary classifier head
that runs on the edge; the server net is the large residual tower that
resumes from the client's feature maps.  Group norm throughout (no batch
stats to aggregate — the FL-friendly choice)."""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp


def _gn(c: int):
    return nn.GroupNorm(num_groups=min(8, c))


class GKTClientNet(nn.Module):
    """Edge-side extractor: stem + one residual block; returns
    (features [B, H/2, W/2, width], logits [B, classes])."""

    num_classes: int = 10
    width: int = 32

    @nn.compact
    def __call__(self, x, train: bool = False) -> Tuple[jnp.ndarray, jnp.ndarray]:
        h = nn.Conv(self.width, (3, 3), padding="SAME")(x)
        h = nn.relu(_gn(self.width)(h))
        h = nn.Conv(self.width, (3, 3), strides=(2, 2), padding="SAME")(h)
        h = nn.relu(_gn(self.width)(h))
        r = nn.Conv(self.width, (3, 3), padding="SAME")(h)
        r = nn.relu(_gn(self.width)(r))
        r = nn.Conv(self.width, (3, 3), padding="SAME")(r)
        features = nn.relu(_gn(self.width)(r) + h)
        pooled = features.mean(axis=(1, 2))
        logits = nn.Dense(self.num_classes)(pooled)
        return features, logits


class GKTServerNet(nn.Module):
    """Server-side tower consuming client feature maps."""

    num_classes: int = 10
    width: int = 64
    blocks: int = 3

    @nn.compact
    def __call__(self, features, train: bool = False) -> jnp.ndarray:
        h = nn.Conv(self.width, (3, 3), padding="SAME")(features)
        h = nn.relu(_gn(self.width)(h))
        for _ in range(self.blocks):
            r = nn.Conv(self.width, (3, 3), padding="SAME")(h)
            r = nn.relu(_gn(self.width)(r))
            r = nn.Conv(self.width, (3, 3), padding="SAME")(r)
            h = nn.relu(_gn(self.width)(r) + h)
        h = h.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(h)
