"""Model factory keyed on (model, dataset).

Parity with reference ``model/model_hub.py:20-85`` (``fedml.model.create``):
same model-name keys, flax modules instead of torch.  Returns an
uninitialized ``nn.Module``; parameter init happens in the trainer via
``ml.engine.train.init_variables`` (functional — no eager weights here).
"""

from __future__ import annotations

import logging
from typing import Any

import flax.linen as nn

logger = logging.getLogger(__name__)


_BF16_MODELS = {"resnet20", "resnet56", "resnet18", "resnet18_gn"}


def create(args: Any, output_dim: int) -> nn.Module:
    name = str(getattr(args, "model", "lr")).lower()
    dataset = str(getattr(args, "dataset", "")).lower()

    import jax.numpy as jnp

    if _dtype(args) is not jnp.float32 and name not in _BF16_MODELS:
        logger.warning(
            "compute_dtype=%s is only plumbed into %s; model %r runs fp32",
            getattr(args, "compute_dtype", None), sorted(_BF16_MODELS), name,
        )

    if name in ("lr", "logistic_regression"):
        from .linear import LogisticRegression

        return LogisticRegression(output_dim=output_dim)
    if name in ("cnn", "cnn_dropout"):
        from .cnn import CNN_DropOut

        return CNN_DropOut(only_digits=(output_dim <= 10), num_classes=output_dim)
    if name in ("cnn_web",):
        from .cnn import CNN_WEB

        return CNN_WEB(output_dim=output_dim)
    if name in ("resnet20",):
        from .resnet import resnet20

        return resnet20(num_classes=output_dim, norm=_norm(args), dtype=_dtype(args))
    if name in ("resnet56",):
        from .resnet import resnet56

        return resnet56(num_classes=output_dim, norm=_norm(args), dtype=_dtype(args))
    if name in ("resnet18", "resnet18_gn"):
        from .resnet import resnet18_gn

        return resnet18_gn(num_classes=output_dim, dtype=_dtype(args))
    if name in ("mobilenet", "mobilenet_v1"):
        from .mobilenet import MobileNetV1

        return MobileNetV1(num_classes=output_dim)
    if name in ("mobilenet_v3",):
        from .mobilenet import MobileNetV3Small

        return MobileNetV3Small(num_classes=output_dim)
    if name in ("rnn", "rnn_fedavg", "rnn_originalfedavg"):
        from .rnn import RNN_OriginalFedAvg

        return RNN_OriginalFedAvg(vocab_size=max(output_dim, 90))
    if name in ("rnn_fedshakespeare",):
        from .rnn import RNN_FedShakespeare

        return RNN_FedShakespeare(vocab_size=max(output_dim, 90))
    if name in ("rnn_stackoverflow", "rnn_nwp"):
        from .rnn import RNN_StackOverFlow

        return RNN_StackOverFlow(vocab_size=output_dim)
    if name in ("lstm", "lstm_tagpred"):
        from .rnn import RNN_OriginalFedAvg

        return RNN_OriginalFedAvg(vocab_size=max(output_dim, 90))
    if name in ("transformer", "fedtransformer"):
        from .transformer import TransformerLM, TransformerConfig

        return TransformerLM(TransformerConfig(vocab_size=max(output_dim, 256)))
    if name in ("vgg11", "vgg16"):
        from .vgg import VGG

        return VGG(num_classes=output_dim, depth=int(name[3:]))
    if name in ("gan", "mnist_gan"):
        from .gan import MNISTGenerator

        return MNISTGenerator()
    if name in ("unet", "deeplabv3", "deeplabv3_plus"):
        from .unet import UNet

        return UNet(num_classes=output_dim)
    if name in ("gkt_client", "resnet8_gkt"):
        from .gkt import GKTClientNet

        return GKTClientNet(num_classes=output_dim)
    if name in ("gkt_server", "resnet55_gkt"):
        from .gkt import GKTServerNet

        return GKTServerNet(num_classes=output_dim)
    if name in ("darts", "darts_network"):
        from .darts import DARTSNetwork

        return DARTSNetwork(num_classes=output_dim)
    if name in ("transformer_cls", "bert_cls", "distilbert"):
        from ..data.data_loader import DATASET_SPECS
        from .nlp import TransformerClassifier

        vocab = int(DATASET_SPECS.get(dataset, {}).get("vocab", 2000))
        return TransformerClassifier(num_classes=output_dim, vocab_size=vocab)
    if name in ("transformer_tagger", "bert_tagger"):
        from ..data.data_loader import DATASET_SPECS
        from .nlp import TransformerTagger

        vocab = int(DATASET_SPECS.get(dataset, {}).get("vocab", 2000))
        return TransformerTagger(num_tags=output_dim, vocab_size=vocab)
    if name in ("transformer_span", "bert_qa"):
        from ..data.data_loader import DATASET_SPECS
        from .nlp import TransformerSpanExtractor

        vocab = int(DATASET_SPECS.get(dataset, {}).get("vocab", 200))
        # compact head: at CI data scales a wide encoder memorizes spans
        # instead of learning the extraction rule
        return TransformerSpanExtractor(vocab_size=vocab, d_model=48, d_ff=96)
    if name in ("tiny_detector", "yolo_lite"):
        from .detection import TinyDetector

        return TinyDetector(num_classes=output_dim)
    if name in ("gcn", "graphsage", "gat"):
        from ..data.data_loader import DATASET_SPECS

        from .gcn import GCN

        feat_dim = int(DATASET_SPECS.get(dataset, {}).get("feat_dim", 8))
        return GCN(num_classes=output_dim, feat_dim=feat_dim)
    if name in ("gcn_linkpred", "gcn_link_pred"):
        from ..data.data_loader import DATASET_SPECS
        from .gcn import GCNLinkPred

        feat_dim = int(DATASET_SPECS.get(dataset, {}).get("feat_dim", 8))
        return GCNLinkPred(feat_dim=feat_dim)
    if name in ("gcn_nodeclf", "gcn_node"):
        from ..data.data_loader import DATASET_SPECS
        from .gcn import GCNNodeClassifier

        feat_dim = int(DATASET_SPECS.get(dataset, {}).get("feat_dim", 8))
        return GCNNodeClassifier(num_classes=output_dim, feat_dim=feat_dim)
    if name in ("gcn_reg", "gcn_regressor"):
        from ..data.data_loader import DATASET_SPECS
        from .gcn import GCNRegressor

        feat_dim = int(DATASET_SPECS.get(dataset, {}).get("feat_dim", 8))
        return GCNRegressor(feat_dim=feat_dim)
    if name in ("gcn_mtl", "gcn_multitask"):
        from ..data.data_loader import DATASET_SPECS
        from .gcn import GCN

        spec = DATASET_SPECS.get(dataset, {})
        feat_dim = int(spec.get("feat_dim", 8))
        return GCN(num_classes=int(spec.get("num_tasks", output_dim)), feat_dim=feat_dim)
    if name in ("autoencoder", "ae", "anomaly_ae"):
        from ..data.data_loader import DATASET_SPECS
        from .autoencoder import AutoEncoder

        feat = int(DATASET_SPECS.get(dataset, {}).get("shape", (24,))[0])
        return AutoEncoder(feat_dim=feat)
    if name in ("transformer_s2s", "bart_s2s", "seq2seq"):
        from ..data.data_loader import DATASET_SPECS
        from .transformer import TransformerConfig, TransformerLM

        vocab = int(DATASET_SPECS.get(dataset, {}).get("vocab", max(output_dim, 64)))
        # causal decoder-only over [src ‖ SEP ‖ tgt] — the TPU-first seq2seq
        # (reference app/fednlp/seq2seq uses encoder-decoder BART; the task
        # contract is identical with loss masked to target positions)
        return TransformerLM(TransformerConfig(
            vocab_size=vocab, d_model=128, n_heads=4, n_layers=2, d_ff=256,
        ))
    if name in ("mlp",):
        from .linear import MLP

        return MLP(output_dim=output_dim)
    if name in ("efficientnet", "efficientnet_b0"):
        from .efficientnet import EfficientNet

        return EfficientNet(num_classes=output_dim)
    raise ValueError(f"unknown model {name!r} for dataset {dataset!r}")


def _norm(args: Any) -> str:
    return str(getattr(args, "model_norm", "gn")).lower()


def _parse_dtype(name: str, arg_name: str):
    """One dtype-string table for every dtype knob (compute/storage)."""
    import jax.numpy as jnp

    if name in ("fp32", "float32"):
        return jnp.float32
    if name in ("bf16", "bfloat16"):
        return jnp.bfloat16
    raise ValueError(f"unknown {arg_name} {name!r} (use fp32 or bf16)")


def _dtype(args: Any):
    """Compute dtype from ``args.compute_dtype`` — 'bf16' runs activations
    and MXU passes in bfloat16 while parameters stay fp32 (mixed precision:
    halves HBM traffic on the usual bandwidth-bound TPU regime)."""
    return _parse_dtype(
        str(getattr(args, "compute_dtype", "fp32") or "fp32").lower(), "compute_dtype"
    )


def data_storage_dtype(args: Any, module: Any = None):
    """HBM storage dtype for the simulator's packed dataset (fed_sim
    _pack_data).  The per-step row gather from the HBM-resident dataset is
    the measured #1 cost of the compiled round (PERF.md term 1) and it is
    bandwidth-bound, so the stored element width IS the gather cost.  When
    the model's entry cast sends the batch to bf16 anyway (compute_dtype
    bf16 + a model that plumbs it), storing bf16 halves that traffic with
    bitwise-identical model input: bf16(gather(fp32_x)) == gather(bf16_x).
    ``args.xla_data_dtype`` in {auto, fp32, bf16} overrides; 'auto' (default)
    applies exactly the condition under which the numerics cannot change."""
    import jax.numpy as jnp

    req = str(getattr(args, "xla_data_dtype", "auto") or "auto").lower()
    if req != "auto":
        return _parse_dtype(req, "xla_data_dtype")
    name = str(getattr(args, "model", "lr")).lower()
    if _dtype(args) is not jnp.bfloat16 or name not in _BF16_MODELS:
        return jnp.float32
    # key the guarantee off the ACTUAL module in use, not just the config
    # name: a user-supplied custom module (FedMLRunner accepts any flax
    # module) has no hub-made entry-cast promise — only downcast when the
    # module itself declares bf16 compute (the hub models' dtype field)
    if module is not None and getattr(module, "dtype", None) is not jnp.bfloat16:
        return jnp.float32
    return jnp.bfloat16
