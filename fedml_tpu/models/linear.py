"""Linear model zoo slice (flax.linen).

Counterpart of reference ``model/linear/lr.py`` (LogisticRegression used by
the MNIST+LR benchmark row, BENCHMARK_simulation.md:5).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LogisticRegression(nn.Module):
    """Flattens input and applies one Dense layer; softmax lives in the loss."""

    output_dim: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.output_dim, name="linear")(x)


class MLP(nn.Module):
    """Two-hidden-layer perceptron for tabular tasks (healthcare/UCI rows of
    the reference data layer)."""

    output_dim: int
    hidden: int = 128

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.output_dim)(x)
