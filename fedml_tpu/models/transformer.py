"""Decoder-only Transformer LM (flax.linen), the flagship model for the
distributed/long-context path.

The reference has no transformer of its own (its NLP apps use stock
HuggingFace models, ``python/app/fednlp/``); this module provides the
equivalent capability TPU-first:

* RoPE positions (stateless — compatible with sequence-sharded ring
  attention, see fedml_tpu/parallel/ring_attention.py);
* an injectable ``attention_fn`` so the same module runs with plain fused
  attention on one chip or ring attention over an ``sp`` mesh axis;
* parameter shapes chosen to shard cleanly over a ``tp`` axis (head dim and
  mlp dim are the partitioned axes — see parallel/sharding.py rules).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 1024
    max_seq_len: int = 2048
    dropout: float = 0.0
    dtype: Any = jnp.float32  # set bfloat16 for TPU runs
    remat: bool = False  # jax.checkpoint each block (HBM <-> FLOPs trade)


def rope(x: jnp.ndarray, positions: jnp.ndarray, base: float = 10000.0) -> jnp.ndarray:
    """Rotary embedding. x: [B, L, H, D], positions: [B, L] absolute indices
    (absolute so sequence-sharded blocks stay correct)."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, L, half]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Default attention: [B, L, H, D] -> [B, L, H, D], causal — the pallas
    flash kernel on TPU (fwd + bwd, ops/flash_attention.py), the fused XLA
    reference elsewhere.
    Single definition lives in ops (also the pallas kernel's oracle)."""
    from ..ops.flash_attention import attention

    return attention(q, k, v, causal=True)


AttentionFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


class Block(nn.Module):
    cfg: TransformerConfig
    attention_fn: AttentionFn = causal_attention

    @nn.compact
    def __call__(self, x, positions, train: bool = False):
        cfg = self.cfg
        h = nn.RMSNorm(dtype=cfg.dtype, name="attn_norm")(x)
        d_head = cfg.d_model // cfg.n_heads
        qkv = nn.DenseGeneral((3, cfg.n_heads, d_head), axis=-1, use_bias=False,
                              dtype=cfg.dtype, name="qkv")(h)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = rope(q, positions)
        k = rope(k, positions)
        attn = self.attention_fn(q, k, v)
        attn = nn.DenseGeneral(cfg.d_model, axis=(-2, -1), use_bias=False,
                               dtype=cfg.dtype, name="out_proj")(attn)
        x = x + attn
        h = nn.RMSNorm(dtype=cfg.dtype, name="mlp_norm")(x)
        gate = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype, name="wi_gate")(h)
        up = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype, name="wi_up")(h)
        h = nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype, name="wo")(
            nn.silu(gate) * up
        )
        return x + h


class TransformerLM(nn.Module):
    cfg: TransformerConfig
    attention_fn: AttentionFn = causal_attention

    @nn.compact
    def __call__(self, tokens, positions: Optional[jnp.ndarray] = None, train: bool = False):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1]), tokens.shape
            )
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype, name="embed")(tokens)
        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(Block, static_argnums=(3,))
        for i in range(cfg.n_layers):
            x = block_cls(cfg, self.attention_fn, name=f"layer{i}")(x, positions, train)
        x = nn.RMSNorm(dtype=cfg.dtype, name="final_norm")(x)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype, name="lm_head")(x)
