from . import hub
from .hub import create
