"""NLP task heads (the fednlp app's model family).

Role of reference ``python/app/fednlp`` models (stock HuggingFace encoders +
task heads): a compact transformer encoder classifier, TPU-first (static
shapes, bf16-ready, GAP pooling)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from .transformer import Block, TransformerConfig


class TransformerClassifier(nn.Module):
    """Token ids [B, L] -> class logits [B, num_classes] (mean-pooled
    bidirectional encoder: attention is non-causal for classification)."""

    num_classes: int
    vocab_size: int = 32000
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        from ..ops.flash_attention import reference_attention

        cfg = TransformerConfig(
            vocab_size=self.vocab_size, d_model=self.d_model, n_heads=self.n_heads,
            n_layers=self.n_layers, d_ff=self.d_ff,
        )
        positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
        x = nn.Embed(cfg.vocab_size, cfg.d_model, name="embed")(tokens)
        attn = lambda q, k, v: reference_attention(q, k, v, causal=False)
        for i in range(cfg.n_layers):
            x = Block(cfg, attention_fn=attn, name=f"layer{i}")(x, positions, train)
        x = nn.RMSNorm(name="final_norm")(x)
        return nn.Dense(self.num_classes, name="cls_head")(x.mean(axis=1))


class TransformerTagger(nn.Module):
    """Token ids [B, L] -> per-token tag logits [B, L, num_tags] (reference
    app/fednlp/seq_tagging task heads).  Same bidirectional encoder as the
    classifier; the engine's per-token masked CE consumes [B, L] labels."""

    num_tags: int
    vocab_size: int = 32000
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        from ..ops.flash_attention import reference_attention

        cfg = TransformerConfig(
            vocab_size=self.vocab_size, d_model=self.d_model, n_heads=self.n_heads,
            n_layers=self.n_layers, d_ff=self.d_ff,
        )
        positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
        x = nn.Embed(cfg.vocab_size, cfg.d_model, name="embed")(tokens)
        attn = lambda q, k, v: reference_attention(q, k, v, causal=False)
        for i in range(cfg.n_layers):
            x = Block(cfg, attention_fn=attn, name=f"layer{i}")(x, positions, train)
        x = nn.RMSNorm(name="final_norm")(x)
        return nn.Dense(self.num_tags, name="tag_head")(x)


class TransformerSpanExtractor(nn.Module):
    """Token ids [B, L] -> span logits [B, L, 2] (start, end) — reference
    app/fednlp/span_extraction (SQuAD-style QA) head."""

    vocab_size: int = 32000
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        from ..ops.flash_attention import reference_attention

        cfg = TransformerConfig(
            vocab_size=self.vocab_size, d_model=self.d_model, n_heads=self.n_heads,
            n_layers=self.n_layers, d_ff=self.d_ff,
        )
        positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
        x = nn.Embed(cfg.vocab_size, cfg.d_model, name="embed")(tokens)
        attn = lambda q, k, v: reference_attention(q, k, v, causal=False)
        for i in range(cfg.n_layers):
            x = Block(cfg, attention_fn=attn, name=f"layer{i}")(x, positions, train)
        x = nn.RMSNorm(name="final_norm")(x)
        return nn.Dense(2, name="span_head")(x)
