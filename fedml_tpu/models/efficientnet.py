"""EfficientNet (flax.linen) — reference ``model/cv/efficientnet*``
(model hub key ``efficientnet``, model_hub.py:20-85).

Compact B0-style: MBConv (expand → depthwise → squeeze-excite → project)
with GroupNorm (FL-correct: no running stats to average) and stride pattern
scaled for CIFAR-sized inputs."""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


def _gn(c: int):
    return nn.GroupNorm(num_groups=min(8, c))


class SqueezeExcite(nn.Module):
    channels: int
    ratio: int = 4

    @nn.compact
    def __call__(self, x):
        s = x.mean(axis=(1, 2))
        s = nn.relu(nn.Dense(max(self.channels // self.ratio, 4))(s))
        s = nn.sigmoid(nn.Dense(self.channels)(s))
        return x * s[:, None, None, :]


class MBConv(nn.Module):
    out_ch: int
    expand: int = 4
    stride: int = 1
    kernel: int = 3

    @nn.compact
    def __call__(self, x):
        in_ch = x.shape[-1]
        mid = in_ch * self.expand
        h = x
        if self.expand != 1:
            h = nn.relu(_gn(mid)(nn.Conv(mid, (1, 1), use_bias=False)(h)))
        h = nn.Conv(mid, (self.kernel, self.kernel), strides=(self.stride, self.stride),
                    padding="SAME", feature_group_count=mid, use_bias=False)(h)
        h = nn.relu(_gn(mid)(h))
        h = SqueezeExcite(mid)(h)
        h = _gn(self.out_ch)(nn.Conv(self.out_ch, (1, 1), use_bias=False)(h))
        if self.stride == 1 and in_ch == self.out_ch:
            h = h + x
        return h


class EfficientNet(nn.Module):
    """(out_ch, expand, stride, repeats) stages; default ~B0-lite."""

    num_classes: int
    stages: Sequence[Tuple[int, int, int, int]] = (
        (16, 1, 1, 1),
        (24, 4, 2, 2),
        (40, 4, 2, 2),
        (80, 4, 2, 2),
        (112, 4, 1, 1),
    )
    stem: int = 32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        h = nn.relu(_gn(self.stem)(nn.Conv(self.stem, (3, 3), padding="SAME",
                                           use_bias=False)(x)))
        for out_ch, expand, stride, repeats in self.stages:
            for r in range(repeats):
                h = MBConv(out_ch, expand, stride if r == 0 else 1)(h)
        h = nn.relu(_gn(192)(nn.Conv(192, (1, 1), use_bias=False)(h)))
        return nn.Dense(self.num_classes)(h.mean(axis=(1, 2)))
