"""RNN zoo (flax.linen LSTMs).

Counterparts of reference ``model/nlp/rnn.py``:
* ``RNN_OriginalFedAvg`` — 2-layer LSTM char model (shakespeare LEAF,
  BENCHMARK_simulation.md:8)
* ``RNN_FedShakespeare`` — Google fed_shakespeare variant (:9)
* ``RNN_StackOverFlow`` — 1-LSTM + 2-FC next-word-prediction model (:10)

Sequences are scanned with ``nn.RNN`` (lax.scan under jit — static shapes,
TPU-friendly).  Input [B, L] int tokens -> logits [B, L, vocab].
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class RNN_OriginalFedAvg(nn.Module):
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Embed(self.vocab_size, self.embedding_dim, name="embed")(x)
        x = nn.RNN(nn.LSTMCell(self.hidden_size), name="lstm1")(x)
        x = nn.RNN(nn.LSTMCell(self.hidden_size), name="lstm2")(x)
        return nn.Dense(self.vocab_size, name="head")(x)


class RNN_FedShakespeare(nn.Module):
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Embed(self.vocab_size, self.embedding_dim, name="embed")(x)
        x = nn.RNN(nn.LSTMCell(self.hidden_size), name="lstm1")(x)
        x = nn.RNN(nn.LSTMCell(self.hidden_size), name="lstm2")(x)
        return nn.Dense(self.vocab_size, name="head")(x)


class RNN_StackOverFlow(nn.Module):
    """1 LSTM + 2 FC (reference rnn.py StackOverflow NWP model)."""

    vocab_size: int = 10004
    embedding_dim: int = 96
    hidden_size: int = 670

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Embed(self.vocab_size, self.embedding_dim, name="embed")(x)
        x = nn.RNN(nn.LSTMCell(self.hidden_size), name="lstm")(x)
        x = nn.Dense(self.embedding_dim, name="fc1")(x)
        return nn.Dense(self.vocab_size, name="fc2")(x)
