"""Single-object detector (reference ``python/app/fedcv/object_detection``
family, YOLO-lite scale): conv backbone -> class logits + normalized box.

Output layout [B, num_classes + 4]: class logits then sigmoid (cx, cy, w, h).
TPU-first: NHWC convs, static shapes, GAP head."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class TinyDetector(nn.Module):
    num_classes: int = 6

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i, feats in enumerate((16, 32, 64)):
            x = nn.Conv(feats, (3, 3), strides=(2, 2), padding="SAME",
                        use_bias=False, name=f"conv{i}")(x)
            x = nn.GroupNorm(num_groups=None, group_size=8, name=f"norm{i}")(x)
            x = nn.relu(x)
        # FLATTEN, not GAP: box regression needs spatial position information
        # (global pooling would make cx/cy unrecoverable)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(64, name="neck")(x))
        cls = nn.Dense(self.num_classes, name="cls_head")(x)
        box = nn.sigmoid(nn.Dense(4, name="box_head")(x))
        return jnp.concatenate([cls, box], axis=-1)
