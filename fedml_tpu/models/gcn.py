"""Graph neural networks (the fedgraphnn app's model family).

Role of reference ``python/app/fedgraphnn`` models (GCN/GAT/GraphSAGE over
moleculenet): a dense-adjacency GCN — TPU-first means fixed-size padded
graphs and adjacency matmuls on the MXU instead of sparse gather/scatter.

Graph batch packing (matches data kind "graph"): each sample is
``[N, F + N]`` — node features [N, F] concatenated with the dense adjacency
[N, N] (self-loops added by the model).  Padding nodes have all-zero rows.

One shared encoder (``gcn_encode``, called inside each module's compact
scope so layer names stay flat: ``gc0``, ``gc1``, ...) feeds four heads:
graph classification (``GCN``), link prediction (``GCNLinkPred``), per-node
classification (``GCNNodeClassifier``), and property regression
(``GCNRegressor``).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def unpack_graph(x, feat_dim: int):
    """[B, N, F+N] -> (features [B, N, F], adjacency [B, N, N])."""
    return x[..., :feat_dim], x[..., feat_dim:]


def gcn_encode(x, feat_dim: int, hidden: int, n_layers: int):
    """Shared GCN encoder: normalized-adjacency message passing.

    Must be called inside an ``nn.compact`` ``__call__`` (it creates the
    ``gc<i>`` Dense layers on the calling module).  Returns
    (node_states [B, N, H], node_mask [B, N]); padding nodes (all-zero
    feature rows) stay silent."""
    feats, adj = unpack_graph(x, feat_dim)
    n = adj.shape[-1]
    # normalized adjacency with self loops: D^-1/2 (A + I) D^-1/2
    a = adj + jnp.eye(n)
    deg = jnp.clip(a.sum(-1), 1e-6, None)
    dinv = 1.0 / jnp.sqrt(deg)
    a_norm = a * dinv[..., :, None] * dinv[..., None, :]
    node_mask = (jnp.abs(feats).sum(-1) > 0).astype(feats.dtype)  # [B, N]

    h = feats
    for i in range(n_layers):
        h = a_norm @ nn.Dense(hidden, name=f"gc{i}")(h)
        h = nn.relu(h) * node_mask[..., None]  # keep padding nodes silent
    return h, node_mask


def masked_mean_pool(h, node_mask):
    """[B, N, H] -> [B, H] mean over real nodes."""
    return h.sum(axis=-2) / jnp.clip(node_mask.sum(-1, keepdims=True), 1.0, None)


class GCN(nn.Module):
    """Graph-level classifier: GCN layers + masked mean pooling."""

    num_classes: int
    feat_dim: int
    hidden: int = 64
    n_layers: int = 2

    @nn.compact
    def __call__(self, x, train: bool = False):
        h, node_mask = gcn_encode(x, self.feat_dim, self.hidden, self.n_layers)
        return nn.Dense(self.num_classes, name="readout")(masked_mean_pool(h, node_mask))


class GCNLinkPred(nn.Module):
    """Link predictor (reference ``app/fedgraphnn/ego_networks_link_pred`` +
    ``recsys_subgraph_link_pred`` GCNLinkPred): GCN encoder over the observed
    adjacency -> node embeddings -> dense pairwise score matrix [B, N, N] via
    one embedding-gram matmul (TPU-first: all candidate pairs scored in a
    single MXU pass instead of per-edge gathers)."""

    feat_dim: int
    hidden: int = 64
    n_layers: int = 2

    @nn.compact
    def __call__(self, x, train: bool = False):
        h, node_mask = gcn_encode(x, self.feat_dim, self.hidden, self.n_layers)
        z = nn.Dense(self.hidden, name="embed")(h) * node_mask[..., None]
        scores = jnp.einsum("...ih,...jh->...ij", z, z) / jnp.sqrt(float(self.hidden))
        bias = self.param("score_bias", nn.initializers.zeros, ())
        return scores + bias


class GCNNodeClassifier(nn.Module):
    """Per-node classifier (reference ``app/fedgraphnn/ego_networks_node_clf``):
    GCN layers WITHOUT pooling -> node logits [B, N, C].  The engine's
    per-token masked CE consumes [B, N] node labels (same path as sequence
    tagging)."""

    num_classes: int
    feat_dim: int
    hidden: int = 64
    n_layers: int = 2

    @nn.compact
    def __call__(self, x, train: bool = False):
        h, _ = gcn_encode(x, self.feat_dim, self.hidden, self.n_layers)
        return nn.Dense(self.num_classes, name="node_head")(h)


class GCNRegressor(nn.Module):
    """Graph-level regressor (reference
    ``app/fedgraphnn/moleculenet_graph_reg``: freesolv/esol/lipophilicity
    property regression) — GCN + masked mean pooling + scalar head; trains
    on the engine's "mse" loss."""

    feat_dim: int
    hidden: int = 64
    n_layers: int = 2
    out_dim: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        h, node_mask = gcn_encode(x, self.feat_dim, self.hidden, self.n_layers)
        return nn.Dense(self.out_dim, name="reg_head")(masked_mean_pool(h, node_mask))
