"""Dense autoencoder (the IoT anomaly-detection model family).

Role of reference ``iot/anomaly_detection_for_cybersecurity``'s
autoencoder (benign-traffic reconstruction; anomalies flagged by
reconstruction error): a symmetric dense stack with a bottleneck.
TPU-first: every layer is one MXU matmul, static shapes throughout.
"""

from __future__ import annotations

import flax.linen as nn


class AutoEncoder(nn.Module):
    """x [B, D] -> reconstruction [B, D]."""

    feat_dim: int
    hidden: int = 32
    bottleneck: int = 8

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x.reshape((x.shape[0], -1))
        h = nn.relu(nn.Dense(self.hidden, name="enc1")(h))
        z = nn.relu(nn.Dense(self.bottleneck, name="enc2")(h))
        h = nn.relu(nn.Dense(self.hidden, name="dec1")(z))
        return nn.Dense(self.feat_dim, name="dec2")(h)
