"""DARTS search space for FedNAS (reference ``simulation/mpi/fednas`` +
``model/cv/darts/``): a differentiable cell whose edges are softmax-weighted
mixtures over a candidate op set; architecture parameters (alphas) are a
separate pytree trained alongside the weights and FedAvg-aggregated by the
FedNAS server, exactly like weights.

Kept deliberately compact (one cell type, ``STEPS`` intermediate nodes, each
connected to the 2 previous states) — the search mechanics, aggregation
semantics, and discrete-architecture derivation match the reference; the op
set is sized for TPU-friendly static shapes."""

from __future__ import annotations

from typing import Any, Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp

OPS = ("skip", "conv3", "conv1", "avgpool", "zero")
STEPS = 2  # intermediate nodes per cell
PREV = 2  # each node sees the 2 previous states


def num_edges() -> int:
    return STEPS * PREV


def _gn(c: int):
    return nn.GroupNorm(num_groups=min(8, c))


class MixedOp(nn.Module):
    """Softmax(alpha)-weighted sum of the candidate ops on one edge."""

    width: int

    @nn.compact
    def __call__(self, x, weights):
        outs = [
            x,  # skip
            nn.relu(_gn(self.width)(nn.Conv(self.width, (3, 3), padding="SAME")(x))),
            nn.relu(_gn(self.width)(nn.Conv(self.width, (1, 1))(x))),
            nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME"),
            jnp.zeros_like(x),  # zero
        ]
        return sum(w * o for w, o in zip(weights, outs))


class DARTSNetwork(nn.Module):
    """Stem -> one searched cell -> GAP -> classifier.  ``alphas``:
    [num_edges, len(OPS)] logits passed at call time (a separate pytree)."""

    num_classes: int = 10
    width: int = 16

    @nn.compact
    def __call__(self, x, alphas, train: bool = False):
        weights = jax.nn.softmax(alphas, axis=-1)
        s0 = nn.relu(_gn(self.width)(nn.Conv(self.width, (3, 3), padding="SAME")(x)))
        s1 = nn.relu(_gn(self.width)(nn.Conv(self.width, (3, 3), strides=(2, 2), padding="SAME")(s0)))
        s0 = nn.avg_pool(s0, (2, 2), strides=(2, 2))  # align spatial dims
        states = [s0, s1]
        edge = 0
        for _ in range(STEPS):
            acc = 0.0
            for j in range(PREV):
                acc = acc + MixedOp(self.width)(states[-1 - j], weights[edge])
                edge += 1
            states.append(acc)
        h = states[-1].mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(h)


def init_alphas(seed: int = 0) -> jnp.ndarray:
    """Near-uniform architecture logits (reference initializes 1e-3 randn)."""
    return 1e-3 * jax.random.normal(jax.random.PRNGKey(seed), (num_edges(), len(OPS)))


def derive_architecture(alphas) -> List[Dict[str, Any]]:
    """Discrete genotype: argmax op per edge, 'zero' excluded (reference
    genotype derivation)."""
    a = jnp.asarray(alphas)
    zero_idx = OPS.index("zero")
    masked = a.at[:, zero_idx].set(-jnp.inf)
    choices = jnp.argmax(masked, axis=-1)
    genotype = []
    edge = 0
    for node in range(STEPS):
        for j in range(PREV):
            genotype.append({"node": node, "input": -1 - j, "op": OPS[int(choices[edge])]})
            edge += 1
    return genotype
