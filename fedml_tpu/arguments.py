"""Argument / configuration system.

Capability parity with the reference's ``python/fedml/arguments.py`` (argparse
flags ``--cf --run_id --rank --local_rank --node_rank --role`` + a YAML config
whose sections are flattened onto a single ``Arguments`` object,
reference ``arguments.py:34-196``), with two native improvements:

* ``Arguments`` can be constructed programmatically from a plain dict
  (``Arguments.from_dict``) — no YAML file required, which is what the
  in-process test harness uses.
* A light validation pass (`validate()`) that checks type/enum constraints the
  reference only probes with ``hasattr`` at use sites.

The canonical YAML shape is unchanged::

    common_args:   { training_type, random_seed, ... }
    data_args:     { dataset, data_cache_dir, partition_method, partition_alpha, ... }
    model_args:    { model, ... }
    train_args:    { federated_optimizer, client_num_in_total, client_num_per_round,
                     comm_round, epochs, batch_size, client_optimizer, learning_rate, ... }
    validation_args: { frequency_of_the_test }
    device_args:   { using_gpu, device_type, ... }
    comm_args:     { backend, ... }
    tracking_args: { enable_wandb, log_file_dir, ... }
    fault_args:    { fault_plan, ... }

Transport-reliability knobs (``train_args`` or ``comm_args``; consumed by
``core/distributed/comm_manager.py``):

* ``comm_reliability`` (default True) — stamp every outbound message with a
  monotonic ``msg_id``, ack stamped inbound messages, and drop re-deliveries
  (idempotent receive).  Turning it off restores the raw reference wire.
* ``comm_max_retries`` (default 0) — send-side retry budget.  0 keeps the
  reference's synchronous-raise semantics; > 0 retries failed sends with
  exponential backoff + jitter AND runs a background retransmitter that
  re-sends unacked messages until acked or the budget is spent.
* ``comm_backoff_base_s`` (default 0.2) / ``comm_backoff_max_s`` (default
  2.0) / ``comm_backoff_jitter`` (default 0.25) — backoff schedule:
  ``min(base * 2^attempt, max) * (1 + jitter * U[0,1))``.
* ``comm_dedup_window`` (default 8192) — LRU size of the receive-side
  message-id dedup window.
* ``comm_backoff_seed`` (int, default = ``random_seed``, unset = legacy
  per-incarnation nonce) — seeds the retransmit jitter stream per
  ``(seed, rank)`` so schedules are deterministic ACROSS incarnations
  (a restarted cohort must not re-draw identical fresh-nonce schedules
  and synchronize its retry storm) yet distinct per rank.
* ``fault_plan`` (default None; ``fault_args`` section) — a deterministic
  chaos plan injected at the transport seam; schema in
  ``core/distributed/faults.py``.

Chunked resumable-upload knobs (``train_args`` or ``comm_args``; consumed
by ``core/distributed/chunking.py``, wire format + resume protocol in
``docs/INGEST.md``):

* ``upload_chunk_bytes`` (int >= 0, default 0 = whole-message sends) —
  split payload-bearing messages larger than this into crc32-framed
  chunks, each acked/deduped/retransmitted individually by the
  reliability layer, so a reconnecting sender resumes from its last
  acked chunk instead of restarting the upload.  Requires
  ``comm_max_retries > 0`` for the resume semantics to engage.
* ``chunk_window`` (int >= 1, default 8) — max unacked chunks in flight
  per stream; bounds both sender memory and the bytes a mid-stream link
  cut can waste.
* ``chunk_resume`` (bool, default True) — journal each accepted chunk
  before its transport ack (journal-before-ack one level down) so a
  server/edge kill mid-upload replays partial streams from disk; off
  keeps reassembly memory-only (a receiver crash re-collects from
  retransmits).
* ``chunk_buffer_bytes`` (int >= 1, default 64 MiB) — receiver-side
  reassembly budget; over it the OLDEST incomplete stream is shed (its
  sender told to restart via ``comm_chunk_reset``, the over-budget
  chunk's ack withheld).
* ``chunk_receive`` (bool, default True) — advertise chunk-receive
  capability on outbound messages.  Chunking negotiates DOWN per link:
  senders only chunk toward peers seen advertising, so legacy peers keep
  whole-message uploads in both directions.

Backend-specific resilience knobs: ``trpc_connect_retries`` /
``trpc_retry_interval_s`` (TCP), ``grpc_send_retries`` /
``grpc_send_backoff_base_s`` (gRPC), ``mqtt_reconnect_retries`` /
``mqtt_reconnect_base_s`` (broker client auto-reconnect).

Population / pacing knobs (``train_args`` or ``population_args``; consumed
by ``core/population``, semantics in ``docs/POPULATION.md``):

* ``selection_policy`` (default ``uniform``) — per-round cohort policy:
  ``uniform`` (bit-identical to the legacy schedules) | ``stratified``
  (speed strata) | ``importance`` (sample-count/staleness weighted).
* ``pacing_overcommit`` (float >= 1.0, default 1.0) — invite
  ``ceil(K * overcommit)`` clients per round.
* ``pacing_quorum`` (int >= 0, default 0 = the target ``K``) — reports
  needed to close the round when pacing is on; the deadline is the
  existing ``round_timeout_s`` timer.
* ``population_blocklist`` (list of client ids, default none) — never
  selected; must leave >= ``client_num_per_round`` clients eligible.
* ``population_strata`` (int >= 1, default 4) — stratified policy's
  stratum count.
* ``importance_alpha`` / ``importance_staleness`` (floats) — importance
  policy weights.
* ``population_stacked`` (bool, default False) — XLA simulator only:
  draw the whole run's cohorts in one vectorized call (a different,
  single-seed schedule — NOT parity with the per-round draw).

Checkpoint / crash-recovery knobs (``train_args``; consumed by
``core/checkpoint.py``, recovery semantics in ``docs/FAULT_TOLERANCE.md``):

* ``checkpoint_dir`` (default unset = disabled) — simulator round
  checkpoint/resume directory (``sp`` / ``xla``).
* ``checkpoint_keep`` (int >= 1, default 3) — keep-last-N retention for
  both simulator checkpoints and server state snapshots.
* ``checkpoint_frequency`` (int >= 1, default 1) — simulator rounds
  between saves.  The message-plane server snapshots every round open
  regardless: journal replay is only correct against that round's
  snapshot.
* ``server_checkpoint_dir`` (default unset = disabled) — enables
  message-plane server crash recovery: a per-round state snapshot plus an
  update journal of accepted uploads; a restarted server resumes the
  in-flight round instead of restarting the run.
* ``server_journal_fsync`` (``always`` | ``never``, default ``always``) —
  whether each journal append fsyncs before the upload is acked.
  ``never`` trades the power-loss guarantee for upload-path latency
  (process crashes are still covered by the OS page cache).
* ``journal_group_commit_ms`` (float >= 0, default 0 = per-record
  commits) — group-commit window for the update journal: concurrent
  appends within the window coalesce into ONE write+fsync batch and
  their transport acks are released together once the batch is durable
  ("ack implies journaled" amortized, see ``docs/INGEST.md``).
* ``journal_group_commit_max`` (int >= 1, default 32) — records per
  group-commit batch before the committer stops waiting out the window.

Server ingest-pipeline knobs (``train_args`` or ``comm_args``; consumed
by ``core/distributed/comm_manager.py`` + ``core/ingest.py``, stage
anatomy in ``docs/INGEST.md``):

* ``ingest_pipeline`` (bool, default False) — stage the server receive
  path: framing/crc/dedup stay on the transport (io) thread, handler
  dispatch moves to a bounded-queue worker, and upload acks are released
  by the journal's group-commit thread.  Off keeps the synchronous
  receive loop bit-identically.
* ``ingest_queue_depth`` (int >= 1, default 64) — bound of the io→
  dispatch queue; a full queue backpressures the transport thread
  instead of growing an unbounded handler backlog.

Hierarchical fan-in knobs (``train_args`` or ``comm_args``; consumed by
``core/hierarchy``, topology contract in ``docs/HIERARCHY.md``):

* ``fan_in_tree`` (1 | 2 | 3, default 1 = flat) — aggregation tree
  depth: 2 inserts an edge-aggregator tier between leaf clients and the
  root, 3 adds a mid tier above the edges.  The BLOCKED fold the tree
  evaluates is the canonical arithmetic — a flat deployment of the same
  plan computes the identical bits at the root.
* ``edge_fanout`` (int >= 0, default 0 = one block of everything) —
  children per tree node: leaves per edge block, and edges per mid in a
  3-level tree.
* ``edge_flush`` (``all`` | seconds > 0, default ``all``) — when an edge
  flushes its block upward.  ``all`` is the bit-exactness barrier (wait
  for every child); a number flushes whatever arrived after that many
  seconds, trading bit-identity against the full-cohort plan for
  liveness under lost leaves.
* ``edge_checkpoint_dir`` (path, default unset; falls back to
  ``server_checkpoint_dir``) — root for per-edge update journals.  With
  neither set, edges keep no durable state and a killed edge's uploads
  must be retransmitted by its leaves.
* ``edge_codec_offers`` / ``edge_codec_accept`` (comma-separated scheme
  lists from ``none|topk|eftopk|quantize|qsgd``, default ``none``) — the
  per-link codec negotiation inputs: a child offers what it can encode
  (with honest byte estimates), a parent picks the cheapest scheme it
  accepts.  Lossy schemes trade the bit-identity contract for bytes.
* ``edge_codec_ratio`` / ``edge_codec_bits`` (defaults 0.05 / 8) —
  parameters for the negotiated scheme, when one applies.

Observability knobs (``tracking_args`` or ``obs_args``; consumed by
``core/obs``, semantics in ``docs/OBSERVABILITY.md``):

* ``obs_trace`` (bool, default False) — emit the per-round span tree
  (deterministic ids, cross-process ``traceparent`` propagation) through
  the mlops sink fan.  Off keeps the wire and the sink stream
  bit-identical to the pre-obs build.
* ``obs_metrics_export_interval`` (float seconds >= 0, default 0) —
  rate limit for periodic MetricsRegistry exports at round close; 0
  exports only the final snapshot at ``mlops.finish()``.
* ``obs_slow_round_factor`` (float >= 1.0, default 2.0) — a round slower
  than ``factor * median(previous rounds)`` gets a ``slow_round`` span
  event (straggler flagging in ``tools/trace_report.py`` uses the same
  factor).
* ``obs_flight_capacity`` (int >= 0, default 2048) — size of the flight
  recorder's in-memory ring of recent telemetry records; 0 disables the
  recorder entirely.
* ``obs_flight_dir`` (path, default unset) — where crc-framed flight
  dumps land on ``server_kill`` / ``server_restore`` / ``slow_round`` /
  unhandled handler exceptions.  Unset keeps the ring (inspectable via
  ``obs.flight_recorder()``) but writes no dumps.
* ``obs_export_port`` (int 0..65535, default 0) — localhost port for the
  OpenMetrics pull endpoint (``GET /metrics``); 0 disables HTTP.
* ``obs_export_path`` (path, default unset) — file that receives atomic
  OpenMetrics snapshots on each rate-limited export and at shutdown.
* ``obs_telemetry`` (bool, default False) — the cross-host telemetry
  plane: clients buffer span/metric records into a bounded ring and
  piggyback one msgpack blob per upload/report (strictly best-effort:
  duplicates dedup by sequence number, gaps are counted, nothing is ever
  retried, and training stays bit-identical on or off).  Requires
  ``obs_trace``.
* ``obs_telemetry_ring`` (int >= 1, default 512) — per-client telemetry
  ring capacity; overflow drops the oldest records (surfacing as
  sequence gaps at the server).
* ``obs_telemetry_flush_s`` (float seconds >= 0, default 0) — minimum
  interval between standalone ``telemetry`` flush messages in async
  mode; 0 restricts telemetry to piggybacked blobs only.
* ``obs_health`` (bool, default False) — the live health & SLO plane
  (``core/obs/health.py``): watchdogs over every long-lived worker,
  EWMA/z-score anomaly windows over the SLO series, a ``/healthz``
  status state machine, and health-triggered flight dumps.  Telemetry
  only: rounds are bit-identical on or off.
* ``obs_health_watchdog_s`` (float > 0, default 30) — default heartbeat
  deadline: an armed watchdog with no beat for this long raises
  ``health.watchdog_expired`` (subsystems may register tighter or looser
  per-worker deadlines).
* ``obs_health_z`` (float > 0, default 4.0) — z-score firing threshold
  for the rolling anomaly windows.
* ``obs_health_ewma_alpha`` (float in (0, 1], default 0.3) — EWMA decay
  for the window mean/variance estimates.
* ``obs_health_warmup`` (int >= 2, default 8) — samples a window must
  see before it may fire (cold distributions would z-fire on noise).

Async / buffered-FL knobs (``train_args`` or ``async_args``; consumed by
``core/async_fl``, execution model in ``docs/ASYNC.md``):

* ``fl_mode`` (``sync`` | ``async``, default ``sync``) — ``async`` turns
  off quorum-gated rounds: the server buffers client deltas (tagged with
  the global-model version they trained against) and flushes the buffer
  through the aggregation plane; ``comm_round`` then counts flushes.
* ``async_buffer_size`` (int >= 1, default = ``client_num_per_round``) —
  deltas per flush.  Must not exceed ``client_num_per_round`` (a buffer
  the active cohort can never fill would only flush by deadline).
  ``async_buffer_size == client_num_per_round`` with the ``constant``
  policy reproduces synchronous FedAvg bit-exactly.
* ``async_staleness_policy`` (``constant`` | ``polynomial`` | ``hinge``,
  default ``constant``) — per-delta aggregation-weight discount as a
  function of staleness (closed forms in ``core/async_fl/staleness.py``).
* ``async_staleness_alpha`` (float > 0, default 0.5) — decay exponent /
  slope of the polynomial and hinge policies.
* ``async_hinge_b`` (int >= 0, default 4) — the hinge policy's no-decay
  grace window.
* ``async_max_staleness`` (int >= 0, default 0) — inclusive staleness
  bound: a delta staler than this is dropped (``async.dropped_stale``)
  and its client immediately re-dispatched on the current global.  0
  accepts only same-version deltas (the sync-equivalence setting); >= 1
  also unlocks the scheduler's immediate re-dispatch of fast clients.
* ``async_flush_deadline_s`` (float >= 0, default 0 = none) — flush a
  non-empty buffer after this many seconds even below capacity (the
  relative-delay timer seam from ``round_timeout_s``; no wall-clock math).

Aggregation-plane knobs (``train_args``; consumed by
``parallel/agg_plane``, semantics in ``docs/AGGREGATION.md``):

* ``agg_plane`` (``host`` | ``compiled``, default ``host``) — where the
  server reduces client updates.  ``compiled`` runs ONE donated-buffer
  GSPMD program over the device mesh; in f32 mode it is bit-exact vs.
  the host path.
* ``agg_wire_dtype`` (``f32`` | ``bf16``, default ``f32``) — dtype for
  staging float client deltas onto the mesh.  ``bf16`` halves wire
  traffic; accumulation stays f32 either way.
* ``agg_microbatch_clients`` (int >= 0, default 0 = all at once) — fold
  K clients at a time into the running accumulator so huge cohorts
  aggregate without materializing the full client stack in HBM.
* ``server_state`` (``replicated`` | ``sharded``, default ``replicated``)
  — where global params + server-optimizer state live between rounds.
  ``sharded`` keeps them as model-axis ``NamedSharding`` device arrays on
  the 2-D (client x model) round mesh and runs the whole round tail
  (reduce -> FedOpt/FedAdam/FedYogi step -> new-params materialization)
  as one donated-buffer compiled program; bit-exact vs. the replicated
  host path in f32 mode.
* ``server_model_parallel`` (int >= 1, default 0 = all devices) — size of
  the round mesh's model axis (the XLA simulator splits its device set
  into client x model with this).  When the live device count can no
  longer satisfy the request (device loss, shrunken restart) the mesh
  degrades to a replicated model=1 layout instead of refusing to serve
  (docs/ELASTICITY.md).
* ``remesh_max_retries`` (int >= 1, default 3) / ``remesh_backoff_s``
  (float >= 0, default 0.05) — retry/backoff for the elastic resume
  handshake: each attempt re-enumerates the live devices before
  re-sharding, so a topology change racing the remesh settles instead of
  failing the round.
* ``broadcast_shards`` (int >= 1, default 1) — number of addressable
  slices the new global params are split into for shard-addressable
  broadcast; each slice is memoized per round as its own
  ``CachedPayload``.

Security/privacy plane knobs (``train_args``; consumed by
``parallel/sec_plane`` and ``core/mpc``, semantics in
``docs/SECURITY.md``):

* ``defense_plane`` (``host`` | ``compiled``, default ``host``) — where
  Byzantine-robust filtering runs when ``enable_defense`` is set.
  ``compiled`` fuses norm-clipping / coordinate-wise trimmed-mean /
  (multi-)Krum into the sharded round program as a pre-reduce stage
  (one program per (mesh, treedef, policy, defense) key); bit-exact
  vs. the retained host defender.
* ``dp_plane`` (``host`` | ``compiled``, default ``host``) — where
  per-client clipping + DP noise runs when ``enable_dp`` is set.
  ``compiled`` draws counter-based noise keyed on (round, client id)
  inside the round program — seed-deterministic and replay/remesh
  stable; the ``core/dp`` budget accountant still drives the noise
  scale (a runtime scalar, never part of the program cache key).
* ``secagg_plane`` (``host`` | ``compiled``, default ``host``) — where
  the secure-aggregation finite-field fold runs.  ``compiled`` sums
  masked residues as sharded uint32 lane ops (``core/mpc/inmesh``);
  exact field math makes any reduction order bit-identical, so the
  knob is a pure perf choice.
"""

from __future__ import annotations

import argparse
import os
from os import path
from typing import Any, Dict, List, Optional

import yaml

from .constants import (
    FEDML_SIMULATION_TYPE_SP,
    FEDML_TRAINING_PLATFORM_SIMULATION,
)

_CONFIG_SECTIONS = (
    "common_args",
    "data_args",
    "model_args",
    "train_args",
    "validation_args",
    "device_args",
    "comm_args",
    "tracking_args",
    "attack_args",
    "defense_args",
    "dp_args",
    "parallel_args",
    # algorithm-family knob sections used by the example configs — an
    # unlisted section would be kept as a dict attr and its knobs silently
    # ignored (the value would quietly fall back to the in-code default)
    "ta_args",
    "vfl_args",
    "fault_args",
    "population_args",
    "obs_args",
    "async_args",
)


def add_args(parser: Optional[argparse.ArgumentParser] = None) -> argparse.Namespace:
    """CLI surface of the reference (``arguments.py:34-60``): five flags."""
    parser = parser or argparse.ArgumentParser(description="fedml_tpu")
    parser.add_argument(
        "--yaml_config_file", "--cf", help="yaml configuration file", type=str, default=""
    )
    parser.add_argument("--run_id", type=str, default="0")
    parser.add_argument("--rank", type=int, default=0)
    parser.add_argument("--local_rank", type=int, default=0)
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--role", type=str, default="client")
    args, _ = parser.parse_known_args()
    return args


class Arguments:
    """Flat attribute bag loaded from YAML sections (reference ``arguments.py:63-171``).

    Every key of every section becomes a top-level attribute; section names are
    conventional.  Unknown sections/keys are preserved verbatim.
    """

    def __init__(
        self,
        cmd_args: Optional[argparse.Namespace] = None,
        training_type: Optional[str] = None,
        comm_backend: Optional[str] = None,
    ):
        if cmd_args is not None:
            for k, v in cmd_args.__dict__.items():
                setattr(self, k, v)
        self.training_type = getattr(self, "training_type", None) or training_type
        self.backend = getattr(self, "backend", None) or comm_backend
        config_file = getattr(self, "yaml_config_file", "")
        if config_file:
            self.load_yaml_config(config_file)

    # -- construction -------------------------------------------------------
    @classmethod
    def from_dict(cls, config: Dict[str, Any]) -> "Arguments":
        """Build from a nested (sectioned) or already-flat dict."""
        args = cls()
        args.set_attr_from_config(config)
        return args

    def load_yaml_config(self, yaml_path: str) -> None:
        with open(yaml_path, "r") as f:
            config = yaml.safe_load(f)
        self.set_attr_from_config(config or {})
        self.yaml_paths = [yaml_path]

    def set_attr_from_config(self, configuration: Dict[str, Any]) -> None:
        """Flatten sections onto self (reference ``arguments.py:168-171``)."""
        for section, content in configuration.items():
            if section in _CONFIG_SECTIONS and isinstance(content, dict):
                for k, v in content.items():
                    setattr(self, k, v)
            else:
                setattr(self, section, content)

    # -- access -------------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return getattr(self, key, default)

    def __contains__(self, key: str) -> bool:
        return hasattr(self, key)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}

    def __repr__(self) -> str:  # pragma: no cover
        return f"Arguments({self.to_dict()!r})"

    # -- validation ---------------------------------------------------------
    REQUIRED_FOR_TRAINING: List[str] = [
        "training_type",
        "dataset",
        "model",
        "federated_optimizer",
        "client_num_in_total",
        "client_num_per_round",
        "comm_round",
    ]

    def validate(self, for_training: bool = True) -> "Arguments":
        if for_training:
            missing = [k for k in self.REQUIRED_FOR_TRAINING if not hasattr(self, k)]
            if missing:
                raise ValueError(f"missing required config keys: {missing}")
            if int(self.client_num_per_round) > int(self.client_num_in_total):
                raise ValueError(
                    "client_num_per_round must be <= client_num_in_total "
                    f"({self.client_num_per_round} > {self.client_num_in_total})"
                )
            bl = getattr(self, "population_blocklist", None)
            if bl:
                eligible = int(self.client_num_in_total) - len(set(int(c) for c in bl))
                if eligible < int(self.client_num_per_round):
                    raise ValueError(
                        "population_blocklist leaves only "
                        f"{eligible} eligible clients (< client_num_per_round="
                        f"{self.client_num_per_round})"
                    )
            # selecting FedProx without a mu means "use the default", on
            # EVERY backend — the engine's proximal hook only installs when
            # mu > 0, so injecting here (the one chokepoint all backends
            # pass through) keeps sp/XLA/MPI_PROC training the same objective
            opt = str(getattr(self, "federated_optimizer", "")).lower()
            if opt == "fedprox" and not float(getattr(self, "proximal_mu", 0) or 0):
                from .constants import FEDPROX_DEFAULT_MU

                self.proximal_mu = FEDPROX_DEFAULT_MU
        # population / pacing knobs fail at config time, not as a traceback
        # mid-run when the first round opens (core/population semantics)
        oc = getattr(self, "pacing_overcommit", None)
        if oc is not None and float(oc) < 1.0:
            raise ValueError(f"pacing_overcommit must be >= 1.0 (got {oc})")
        q = getattr(self, "pacing_quorum", None)
        if q is not None and int(q) < 0:
            raise ValueError(f"pacing_quorum must be >= 0 (got {q})")
        pol = str(getattr(self, "selection_policy", "uniform") or "uniform").lower()
        if pol not in ("uniform", "stratified", "importance"):
            raise ValueError(
                f"unknown selection_policy {pol!r} "
                "(expected uniform|stratified|importance)"
            )
        strata = getattr(self, "population_strata", None)
        if strata is not None and int(strata) < 1:
            raise ValueError(f"population_strata must be >= 1 (got {strata})")
        # checkpoint / server-recovery knobs (core/checkpoint.py) — a typo'd
        # value must fail here, not be silently ignored by the bare getattr
        # defaults at the use sites
        for knob in ("checkpoint_dir", "server_checkpoint_dir"):
            d = getattr(self, knob, None)
            if d is not None and not isinstance(d, (str, os.PathLike)):
                raise ValueError(
                    f"{knob} must be a path string (got {type(d).__name__}); "
                    "empty/unset disables checkpointing")
        for knob, floor in (("checkpoint_keep", 1), ("checkpoint_frequency", 1)):
            v = getattr(self, knob, None)
            if v is None:
                continue
            try:
                iv = int(v)
            except (TypeError, ValueError):
                raise ValueError(f"{knob} must be an integer >= {floor} (got {v!r})")
            if iv < floor:
                raise ValueError(f"{knob} must be >= {floor} (got {iv})")
        fsync = getattr(self, "server_journal_fsync", None)
        if fsync is not None:
            from .core.checkpoint import JOURNAL_FSYNC_POLICIES

            if str(fsync).lower() not in JOURNAL_FSYNC_POLICIES:
                raise ValueError(
                    "server_journal_fsync must be one of "
                    f"{JOURNAL_FSYNC_POLICIES} (got {fsync!r})")
        # ingest-pipeline knobs (core/ingest + comm_manager staged path)
        pipe = getattr(self, "ingest_pipeline", None)
        if pipe is not None and not isinstance(pipe, bool):
            if (not isinstance(pipe, str) or pipe.strip().lower() not in
                    ("1", "true", "on", "yes", "0", "false", "off", "no")):
                raise ValueError(
                    "ingest_pipeline must be a bool or on/off string "
                    f"(got {pipe!r})")
        gc_ms = getattr(self, "journal_group_commit_ms", None)
        if gc_ms is not None:
            try:
                gv = float(gc_ms)
            except (TypeError, ValueError):
                raise ValueError(
                    "journal_group_commit_ms must be a number >= 0 "
                    f"(got {gc_ms!r})")
            if gv < 0:
                raise ValueError(
                    f"journal_group_commit_ms must be >= 0 (got {gv})")
        for knob in ("journal_group_commit_max", "ingest_queue_depth"):
            v = getattr(self, knob, None)
            if v is None:
                continue
            try:
                iv = int(v)
            except (TypeError, ValueError):
                raise ValueError(
                    f"{knob} must be an integer >= 1 (got {v!r})")
            if iv < 1:
                raise ValueError(f"{knob} must be >= 1 (got {iv})")
        # chunked resumable-upload knobs (core/distributed/chunking)
        chunk_bytes = getattr(self, "upload_chunk_bytes", None)
        if chunk_bytes is not None:
            try:
                cb = int(chunk_bytes)
            except (TypeError, ValueError):
                raise ValueError(
                    "upload_chunk_bytes must be an integer >= 0 "
                    f"(got {chunk_bytes!r})")
            if cb < 0:
                raise ValueError(
                    f"upload_chunk_bytes must be >= 0 (got {cb})")
        for knob in ("chunk_window", "chunk_buffer_bytes"):
            v = getattr(self, knob, None)
            if v is None:
                continue
            try:
                iv = int(v)
            except (TypeError, ValueError):
                raise ValueError(
                    f"{knob} must be an integer >= 1 (got {v!r})")
            if iv < 1:
                raise ValueError(f"{knob} must be >= 1 (got {iv})")
        # hierarchical fan-in knobs (core/hierarchy) — the plan derives the
        # tree shape from these, so a bad value must fail before any node
        # is built with a different grouping than its peers
        tree = getattr(self, "fan_in_tree", None)
        if tree is not None:
            from .core.hierarchy.plan import FAN_IN_TREE_LEVELS

            try:
                tv = int(tree)
            except (TypeError, ValueError):
                raise ValueError(
                    f"fan_in_tree must be one of {FAN_IN_TREE_LEVELS} "
                    f"(got {tree!r})")
            if tv not in FAN_IN_TREE_LEVELS:
                raise ValueError(
                    f"fan_in_tree must be one of {FAN_IN_TREE_LEVELS} "
                    f"(got {tv})")
        fanout = getattr(self, "edge_fanout", None)
        if fanout is not None:
            try:
                fo = int(fanout)
            except (TypeError, ValueError):
                raise ValueError(
                    "edge_fanout must be an integer >= 0 "
                    f"(got {fanout!r})")
            if fo < 0:
                raise ValueError(f"edge_fanout must be >= 0 (got {fo})")
        flush_k = getattr(self, "edge_flush", None)
        if flush_k is not None:
            ok = (isinstance(flush_k, str)
                  and flush_k.strip().lower() == "all")
            if not ok:
                try:
                    fs = float(flush_k)
                    ok = fs > 0
                except (TypeError, ValueError):
                    ok = False
            if not ok:
                raise ValueError(
                    "edge_flush must be 'all' or a positive number of "
                    f"seconds (got {flush_k!r})")
        # observability knobs (core/obs) — bad values fail here so a typo'd
        # interval doesn't silently disable the periodic metrics export
        interval = getattr(self, "obs_metrics_export_interval", None)
        if interval is not None:
            try:
                fv = float(interval)
            except (TypeError, ValueError):
                raise ValueError(
                    f"obs_metrics_export_interval must be a number >= 0 "
                    f"(got {interval!r})")
            if fv < 0:
                raise ValueError(
                    f"obs_metrics_export_interval must be >= 0 (got {fv})")
        slow = getattr(self, "obs_slow_round_factor", None)
        if slow is not None:
            try:
                sv = float(slow)
            except (TypeError, ValueError):
                raise ValueError(
                    f"obs_slow_round_factor must be a number >= 1.0 "
                    f"(got {slow!r})")
            if sv < 1.0:
                raise ValueError(
                    f"obs_slow_round_factor must be >= 1.0 (got {sv})")
        cap = getattr(self, "obs_flight_capacity", None)
        if cap is not None:
            try:
                cv = int(cap)
            except (TypeError, ValueError):
                raise ValueError(
                    f"obs_flight_capacity must be an integer >= 0 "
                    f"(got {cap!r})")
            if cv < 0:
                raise ValueError(
                    f"obs_flight_capacity must be >= 0 (got {cv})")
        port = getattr(self, "obs_export_port", None)
        if port is not None:
            try:
                pv = int(port)
            except (TypeError, ValueError):
                raise ValueError(
                    f"obs_export_port must be an integer in 0..65535 "
                    f"(got {port!r})")
            if not 0 <= pv <= 65535:
                raise ValueError(
                    f"obs_export_port must be in 0..65535 (got {pv})")
        ring = getattr(self, "obs_telemetry_ring", None)
        if ring is not None:
            try:
                rv = int(ring)
            except (TypeError, ValueError):
                raise ValueError(
                    f"obs_telemetry_ring must be an integer >= 1 "
                    f"(got {ring!r})")
            if rv < 1:
                raise ValueError(
                    f"obs_telemetry_ring must be >= 1 (got {rv})")
        flush = getattr(self, "obs_telemetry_flush_s", None)
        if flush is not None:
            try:
                fs = float(flush)
            except (TypeError, ValueError):
                raise ValueError(
                    f"obs_telemetry_flush_s must be a number >= 0 "
                    f"(got {flush!r})")
            if fs < 0:
                raise ValueError(
                    f"obs_telemetry_flush_s must be >= 0 (got {fs})")
        # health-plane knobs (core/obs/health) — a typo'd threshold must
        # fail here, not silently run with the default
        wds = getattr(self, "obs_health_watchdog_s", None)
        if wds is not None:
            try:
                wv = float(wds)
            except (TypeError, ValueError):
                raise ValueError(
                    f"obs_health_watchdog_s must be a number > 0 "
                    f"(got {wds!r})")
            if wv <= 0:
                raise ValueError(
                    f"obs_health_watchdog_s must be > 0 (got {wv})")
        hz = getattr(self, "obs_health_z", None)
        if hz is not None:
            try:
                zv = float(hz)
            except (TypeError, ValueError):
                raise ValueError(
                    f"obs_health_z must be a number > 0 (got {hz!r})")
            if zv <= 0:
                raise ValueError(f"obs_health_z must be > 0 (got {zv})")
        alpha = getattr(self, "obs_health_ewma_alpha", None)
        if alpha is not None:
            try:
                av = float(alpha)
            except (TypeError, ValueError):
                raise ValueError(
                    f"obs_health_ewma_alpha must be a number in (0, 1] "
                    f"(got {alpha!r})")
            if not 0 < av <= 1:
                raise ValueError(
                    f"obs_health_ewma_alpha must be in (0, 1] (got {av})")
        warm = getattr(self, "obs_health_warmup", None)
        if warm is not None:
            try:
                wv = int(warm)
            except (TypeError, ValueError):
                raise ValueError(
                    f"obs_health_warmup must be an integer >= 2 "
                    f"(got {warm!r})")
            if wv < 2:
                raise ValueError(
                    f"obs_health_warmup must be >= 2 (got {wv})")
        # async / buffered-FL knobs (core/async_fl) — a typo'd mode or policy
        # must fail here, not silently run the sync state machine
        mode = getattr(self, "fl_mode", None)
        if mode is not None:
            from .core.async_fl import FL_MODES

            if str(mode).lower() not in FL_MODES:
                raise ValueError(
                    f"fl_mode must be one of {FL_MODES} (got {mode!r})")
        bs = getattr(self, "async_buffer_size", None)
        if bs is not None:
            try:
                bv = int(bs)
            except (TypeError, ValueError):
                raise ValueError(
                    f"async_buffer_size must be an integer >= 1 (got {bs!r})")
            if bv < 1:
                raise ValueError(f"async_buffer_size must be >= 1 (got {bv})")
            k = getattr(self, "client_num_per_round", None)
            if k is not None and bv > int(k):
                raise ValueError(
                    f"async_buffer_size ({bv}) must not exceed "
                    f"client_num_per_round ({k}): a buffer the active cohort "
                    "cannot fill would only ever flush by deadline")
        spol = getattr(self, "async_staleness_policy", None)
        if spol is not None:
            from .core.async_fl import ASYNC_STALENESS_POLICIES

            if str(spol).lower() not in ASYNC_STALENESS_POLICIES:
                raise ValueError(
                    "async_staleness_policy must be one of "
                    f"{ASYNC_STALENESS_POLICIES} (got {spol!r})")
        for knob, floor, kind in (
                ("async_max_staleness", 0, int),
                ("async_hinge_b", 0, int),
                ("async_flush_deadline_s", 0.0, float)):
            v = getattr(self, knob, None)
            if v is None:
                continue
            try:
                cv = kind(v)
            except (TypeError, ValueError):
                raise ValueError(
                    f"{knob} must be a {kind.__name__} >= {floor} (got {v!r})")
            if cv < floor:
                raise ValueError(f"{knob} must be >= {floor} (got {cv})")
        sa = getattr(self, "async_staleness_alpha", None)
        if sa is not None:
            try:
                sav = float(sa)
            except (TypeError, ValueError):
                raise ValueError(
                    f"async_staleness_alpha must be a number > 0 (got {sa!r})")
            if sav <= 0:
                raise ValueError(
                    f"async_staleness_alpha must be > 0 (got {sav})")
        # aggregation-plane knobs (parallel/agg_plane) — a typo'd plane name
        # must not silently fall back to the host loop
        plane = getattr(self, "agg_plane", None)
        if plane is not None:
            from .parallel.agg_plane import AGG_PLANES

            if str(plane).lower() not in AGG_PLANES:
                raise ValueError(
                    f"agg_plane must be one of {AGG_PLANES} (got {plane!r})")
        wire = getattr(self, "agg_wire_dtype", None)
        if wire is not None:
            from .parallel.agg_plane import AGG_WIRE_DTYPES

            if str(wire).lower() not in AGG_WIRE_DTYPES:
                raise ValueError(
                    f"agg_wire_dtype must be one of {AGG_WIRE_DTYPES} "
                    f"(got {wire!r})")
        mb = getattr(self, "agg_microbatch_clients", None)
        if mb is not None:
            try:
                mv = int(mb)
            except (TypeError, ValueError):
                raise ValueError(
                    f"agg_microbatch_clients must be an integer >= 0 "
                    f"(got {mb!r})")
            if mv < 0:
                raise ValueError(
                    f"agg_microbatch_clients must be >= 0 (got {mv})")
        state = getattr(self, "server_state", None)
        if state is not None:
            from .parallel.agg_plane import SERVER_STATES

            if str(state).lower() not in SERVER_STATES:
                raise ValueError(
                    f"server_state must be one of {SERVER_STATES} "
                    f"(got {state!r})")
        # security/privacy stage planes (parallel/sec_plane, core/mpc) — same
        # fail-loud contract: a typo must not silently stay on the host path
        for knob in ("defense_plane", "dp_plane", "secagg_plane"):
            sp = getattr(self, knob, None)
            if sp is not None:
                from .parallel.sec_plane import SEC_PLANES

                if str(sp).lower() not in SEC_PLANES:
                    raise ValueError(
                        f"{knob} must be one of {SEC_PLANES} (got {sp!r})")
        if (str(getattr(self, "defense_plane", "host") or "host").lower()
                == "compiled" and getattr(self, "enable_defense", False)):
            from .parallel.sec_plane import defense_spec

            defense_spec(self)  # raises on defenses the plane can't compile
        for knob, floor in (("server_model_parallel", 0),
                            ("broadcast_shards", 1),
                            ("remesh_max_retries", 1)):
            v = getattr(self, knob, None)
            if v is None:
                continue
            try:
                cv = int(v)
            except (TypeError, ValueError):
                raise ValueError(
                    f"{knob} must be an integer >= {floor} (got {v!r})")
            if cv < floor:
                raise ValueError(f"{knob} must be >= {floor} (got {cv})")
        # a malformed chaos plan should fail at config time, not mid-run when
        # the backend factory first tries to wrap the transport
        plan = getattr(self, "fault_plan", None)
        if plan:
            from .core.distributed.faults import FaultPlan

            FaultPlan.from_dict(plan)
        return self


def _default_yaml_path(training_type: str, comm_backend: str) -> str:
    base = path.join(path.dirname(__file__), "config")
    if training_type == FEDML_TRAINING_PLATFORM_SIMULATION:
        sub = "simulation_sp" if comm_backend == FEDML_SIMULATION_TYPE_SP else "simulation_xla"
    else:
        sub = training_type
    return path.join(base, sub, "fedml_config.yaml")


def load_arguments(
    training_type: Optional[str] = None, comm_backend: Optional[str] = None
) -> Arguments:
    """Reference ``arguments.py:174-196``: parse CLI, then load YAML config."""
    cmd_args = add_args()
    if not cmd_args.yaml_config_file:
        candidate = _default_yaml_path(
            training_type or FEDML_TRAINING_PLATFORM_SIMULATION,
            comm_backend or FEDML_SIMULATION_TYPE_SP,
        )
        if os.path.exists(candidate):
            cmd_args.yaml_config_file = candidate
    args = Arguments(cmd_args, training_type, comm_backend)
    if not hasattr(args, "rank"):
        args.rank = 0
    return args
