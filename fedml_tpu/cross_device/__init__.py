"""Cross-device FL (the reference's Beehive pillar, ``cross_device/``).

Python server + edge devices exchanging *serialized model files* — the role
the MNN graph file plays in the reference (``cross_device/server_mnn/``).
Here the edge interchange format is FTEM (``edge_model.py``), a flat binary
tensor container that both this server and the native C++ edge runtime
(``native/``) read and write.
"""

from .edge_model import load_edge_model, save_edge_model
from .server import ServerDevice

__all__ = ["ServerDevice", "save_edge_model", "load_edge_model"]
