"""Cross-device message vocabulary.

Same round protocol as cross-silo (``..cross_silo.message_define``) with one
difference, mirroring the reference MNN variant
(``cross_device/server_mnn/``): the model travels as a FILE reference
(``model_params_file``), never as an in-memory pytree.
"""

from ..cross_silo.message_define import MyMessage as _Base


class MNNMessage(_Base):
    MSG_ARG_KEY_MODEL_PARAMS_FILE = "model_params_file"
