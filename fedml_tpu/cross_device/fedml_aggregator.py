"""Cross-device server aggregator: file-in, file-out.

Parity with reference ``cross_device/server_mnn/fedml_aggregator.py:17-141``:
clients upload serialized model files; the aggregator weighted-averages the
tensor dicts (``:59``), writes the new global model as a file for
distribution (``get_global_model_params_file`` ``:38``), and evaluates the
global model with the server-side runtime (``:141``) — here the flax module
on TPU instead of the MNN python runtime.
"""

from __future__ import annotations

import logging
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import obs
from ..core.aggregate import ServerRoundUpdater, server_state_mode
from ..ml.aggregator.default_aggregator import DefaultServerAggregator
from ..ml.engine.train import init_variables
from .edge_model import flatten_params, load_edge_model, save_edge_model, unflatten_params

logger = logging.getLogger(__name__)


class FedMLAggregator:
    def __init__(self, args, model, test_global, worker_num: int, model_dir: Optional[str] = None):
        self.args = args
        self.module = model
        self.test_global = test_global
        self.worker_num = int(worker_num)
        self.model_dir = model_dir or os.path.join(
            tempfile.gettempdir(), f"fedml_tpu_edge_{getattr(args, 'run_id', '0')}"
        )
        os.makedirs(self.model_dir, exist_ok=True)

        import jax.numpy as jnp

        sample = jnp.asarray(test_global[0][:1])
        self.variables = init_variables(model, sample, seed=int(getattr(args, "random_seed", 0)))
        self._eval = DefaultServerAggregator(model, args)
        # sharded server state (server_state=sharded): the round updater owns
        # the model-sharded resident params + server-optimizer state; the
        # flat name->array dict IS the pytree (names carry the "params/"
        # prefix the optimizer mask keys on)
        self.round_updater = (ServerRoundUpdater(args)
                              if server_state_mode(args) == "sharded"
                              else None)
        # last sharded round output (object identity = plane residency key);
        # any external global replacement must clear it
        self._round_global: Optional[Dict[str, np.ndarray]] = None

        self.model_file_dict: Dict[int, str] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self.flag_client_model_uploaded_dict: Dict[int, bool] = {}
        self.eval_history: List[Dict[str, Any]] = []

    # -- file plane ----------------------------------------------------------
    def get_global_model_params_file(self, round_idx: int) -> str:
        """Serialize the current global model for device download
        (reference ``fedml_aggregator.py:38``).  Keeps only the latest two
        rounds' files (devices may still be downloading round N-1)."""
        path = os.path.join(self.model_dir, f"global_model_r{round_idx}.ftem")
        save_edge_model(path, self.variables)
        stale = os.path.join(self.model_dir, f"global_model_r{round_idx - 2}.ftem")
        try:
            os.remove(stale)
        except OSError:
            pass
        return path

    def set_global_model_params_from_file(self, path: str) -> None:
        self.variables = unflatten_params(load_edge_model(path))
        self._round_global = None

    # -- crash-recovery persistence (core/checkpoint.ServerRecoveryMixin) ----
    def export_state(self) -> Dict[str, np.ndarray]:
        """The global model as a flat name->array dict (msgpack-ready)."""
        return flatten_params(self.variables)

    def restore_state(self, flat: Dict[str, Any]) -> None:
        self.variables = unflatten_params(
            {str(k): np.asarray(v) for k, v in flat.items()}
        )
        self._round_global = None

    # -- collection (reference :44-58) ---------------------------------------
    def add_local_trained_result(self, index: int, model_file: str, sample_num: float) -> None:
        self.model_file_dict[index] = str(model_file)
        self.sample_num_dict[index] = float(sample_num)
        self.flag_client_model_uploaded_dict[index] = True

    def check_whether_all_receive(self) -> bool:
        if len(self.flag_client_model_uploaded_dict) < self.worker_num:
            return False
        for idx in range(self.worker_num):
            if not self.flag_client_model_uploaded_dict.get(idx, False):
                return False
        self.flag_client_model_uploaded_dict = {}
        return True

    def received_indices(self) -> List[int]:
        """Device slots whose upload arrived this round (unconsumed flags)."""
        return sorted(i for i, f in self.flag_client_model_uploaded_dict.items() if f)

    def consume_received(self, got: Optional[List[int]] = None) -> List[int]:
        """Straggler-tolerant round close: the received slots, flags reset.
        ``got`` lets a caller that already scanned under the lock skip the
        second scan.  Only ``got``'s flags reset (matching the cross-silo
        implementation of this mixin-required API): a caller closing with a
        subset must not discard received-but-unconsumed uploads."""
        if got is None:
            got = self.received_indices()
        for i in got:
            self.flag_client_model_uploaded_dict.pop(i, None)
        return got

    # -- aggregation (reference :59-115) -------------------------------------
    def aggregate(self, indices: Optional[List[int]] = None) -> Dict[str, np.ndarray]:
        """Weighted aggregate over ``indices`` (default: every device — the
        reference's all-received path)."""
        if indices is None:
            indices = list(range(self.worker_num))
        if self.round_updater is not None:
            updates = [(self.sample_num_dict[i],
                        load_edge_model(self.model_file_dict[i]))
                       for i in indices]
            merged = self._install_sharded(
                self.round_updater.round_update(self._sharded_base(), updates,
                                                client_ids=list(indices)))
            for path in self.model_file_dict.values():
                try:
                    os.remove(path)
                except OSError:
                    pass
            self.model_file_dict = {}
            self.sample_num_dict = {}
            return merged
        if str(getattr(self.args, "agg_plane", "host") or "host") == "compiled":
            from ..parallel.agg_plane import plane_for

            updates = [(self.sample_num_dict[i],
                        load_edge_model(self.model_file_dict[i]))
                       for i in indices]
            reduced = plane_for(self.args).aggregate(updates, mode="mean")
            acc: Dict[str, np.ndarray] = {
                name: np.asarray(v) for name, v in reduced.items()}
        else:
            t0 = time.perf_counter()
            total = sum(self.sample_num_dict[i] for i in indices) or 1.0
            acc = {}
            for i in indices:
                flat = load_edge_model(self.model_file_dict[i])
                w = self.sample_num_dict[i] / total
                for name, arr in flat.items():
                    contrib = arr.astype(np.float64) * w
                    acc[name] = contrib if name not in acc else acc[name] + contrib
            obs.histogram_observe(
                "agg.step_seconds", time.perf_counter() - t0,
                labels={"path": "host", "mode": "mean"})
        merged = self._install_merged(acc)
        # uploads are consumed — delete them or a long run fills the disk
        for path in self.model_file_dict.values():
            try:
                os.remove(path)
            except OSError:
                pass
        self.model_file_dict = {}
        self.sample_num_dict = {}
        return merged

    def aggregate_buffered(self, weighted_updates) -> Dict[str, np.ndarray]:
        """Async-flush aggregate: the caller (core/async_fl) supplies
        ``(weight, flat_params)`` pairs directly — params were loaded from
        the upload files at accept time, and the weights already carry the
        ``n_samples * staleness_weight`` discount.  The sync slot tables
        (``model_file_dict`` etc.) are untouched; upload-file cleanup is the
        server manager's ``_async_after_flush`` job, because the files must
        outlive the flush until the successor cycle's snapshot is durable."""
        if self.round_updater is not None:
            merged = self._install_sharded(self.round_updater.round_update(
                self._sharded_base(), list(weighted_updates)))
            logger.info("buffered aggregate of %d deltas plane=sharded",
                        len(weighted_updates))
            return merged
        if str(getattr(self.args, "agg_plane", "host") or "host") == "compiled":
            from ..parallel.agg_plane import plane_for

            reduced = plane_for(self.args).aggregate(
                list(weighted_updates), mode="mean")
            acc: Dict[str, np.ndarray] = {
                name: np.asarray(v) for name, v in reduced.items()}
        else:
            t0 = time.perf_counter()
            total = sum(w for w, _ in weighted_updates) or 1.0
            acc = {}
            for w, flat in weighted_updates:
                frac = w / total
                for name, arr in flat.items():
                    contrib = np.asarray(arr).astype(np.float64) * frac
                    acc[name] = contrib if name not in acc else acc[name] + contrib
            obs.histogram_observe(
                "agg.step_seconds", time.perf_counter() - t0,
                labels={"path": "host", "mode": "mean"})
        logger.info("buffered aggregate of %d deltas plane=%s",
                    len(weighted_updates),
                    getattr(self.args, "agg_plane", "host") or "host")
        return self._install_merged(acc)

    def _sharded_base(self) -> Dict[str, np.ndarray]:
        """The global-params pytree handed to the round plane: the plane's
        own last output when the globals haven't been replaced since (object
        identity keeps the resident device state live — no re-install), the
        freshly flattened globals otherwise (restore / file-set paths)."""
        base = getattr(self, "_round_global", None)
        return base if base is not None else flatten_params(self.variables)

    def _install_sharded(self, merged: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Install the round plane's output as the new global WITHOUT the
        template recast of :meth:`_install_merged` — the plane's out-dtypes
        are authoritative (recasting would desync the resident device state
        and reset the server-optimizer on the next structure check)."""
        out = {name: np.asarray(v) for name, v in merged.items()}
        self.variables = unflatten_params(out)
        self._round_global = merged
        return out

    def export_server_opt_state(self):
        """Numpy snapshot of the sharded optimizer/params state for the
        recovery store (None on the replicated path or before round 1)."""
        return (self.round_updater.export_state()
                if self.round_updater is not None else None)

    def restore_server_opt_state(self, state) -> None:
        """Re-install the restored globals into the round plane and load
        the optimizer state bit-identically (recovery restore path)."""
        if self.round_updater is not None and state is not None:
            self._round_global = None
            self.round_updater.restore_state(flatten_params(self.variables),
                                             state)

    def _install_merged(self, acc: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Cast an accumulated flat dict back through the current global
        dtype template and install it as the new global.  Preserves integer
        leaves (e.g. step counters) — round first: a float64 weighted sum of
        equal ints lands epsilon below the true value and astype truncates."""
        template = flatten_params(self.variables)
        merged = {}
        for name in acc:
            dt = template[name].dtype if name in template else np.dtype(np.float32)
            v = np.rint(acc[name]) if np.issubdtype(dt, np.integer) else acc[name]
            merged[name] = v.astype(dt)
        self.variables = unflatten_params(merged)
        return merged

    # -- eval (reference :141 test_on_server_for_all_clients) ----------------
    def test_on_server_for_all_clients(self, round_idx: int) -> Dict[str, Any]:
        self._eval.set_model_params(self.variables)
        stats = self._eval.test(self.test_global, None, self.args)
        out = {
            "round": round_idx,
            "test_acc": round(float(stats["test_correct"]) / max(float(stats["test_total"]), 1.0), 4),
            "test_loss": round(float(stats["test_loss"]) / max(float(stats["test_total"]), 1.0), 4),
        }
        self.eval_history.append(out)
        logger.info("cross-device eval: %s", out)
        return out
