"""Cross-device server runner (reference ``cross_device/mnn_server.py``
``ServerMNN`` + ``server_mnn_api.py``): composes the file-plane aggregator and
the round state machine; ``run()`` blocks in the receive loop."""

from __future__ import annotations

from .fedml_aggregator import FedMLAggregator
from .fedml_server_manager import FedMLServerManager


class ServerDevice:
    def __init__(self, args, device, dataset, model, server_aggregator=None):
        [
            _train_num,
            _test_num,
            _train_global,
            test_global,
            _local_num_dict,
            _train_local_dict,
            _test_local_dict,
            _class_num,
        ] = dataset
        client_num = int(getattr(args, "client_num_per_round", getattr(args, "client_num_in_total", 1)))
        self.aggregator = FedMLAggregator(
            args, model, test_global, worker_num=client_num,
            model_dir=getattr(args, "edge_model_dir", None),
        )
        self.server_manager = FedMLServerManager(
            args,
            self.aggregator,
            client_rank=0,
            client_num=client_num,
            backend=str(getattr(args, "backend", "LOOPBACK")),
        )

    def run(self):
        self.server_manager.run()
        return self.aggregator.eval_history[-1] if self.aggregator.eval_history else {}
