"""Cross-device server runner (reference ``cross_device/mnn_server.py``
``ServerMNN`` + ``server_mnn_api.py``): composes the file-plane aggregator and
the round state machine; ``run()`` blocks in the receive loop."""

from __future__ import annotations

from .fedml_aggregator import FedMLAggregator
from .fedml_server_manager import FedMLServerManager


class ServerDevice:
    def __init__(self, args, device, dataset, model, server_aggregator=None):
        [
            _train_num,
            _test_num,
            _train_global,
            test_global,
            _local_num_dict,
            _train_local_dict,
            _test_local_dict,
            _class_num,
        ] = dataset
        per_round = int(getattr(args, "client_num_per_round", getattr(args, "client_num_in_total", 1)))
        # the manager handshakes with the FLEET (client_num_in_total devices
        # connect); the population policy picks per_round of them each round,
        # and the aggregator's slot table covers the over-commit invite list
        fleet = int(getattr(args, "client_num_in_total", per_round) or per_round)
        from ..core.population import RoundPacer

        slots = RoundPacer.from_args(args).invite_count(per_round)
        self.aggregator = FedMLAggregator(
            args, model, test_global, worker_num=slots,
            model_dir=getattr(args, "edge_model_dir", None),
        )
        # building the manager may RESUME a crashed run: with
        # args.server_checkpoint_dir set it restores the latest round
        # snapshot, replays the upload journal, and bumps its incarnation
        # epoch (core/checkpoint.ServerRecoveryMixin)
        self.server_manager = FedMLServerManager(
            args,
            self.aggregator,
            client_rank=0,
            client_num=fleet,
            backend=str(getattr(args, "backend", "LOOPBACK")),
        )

    @property
    def resumed(self) -> bool:
        """True when this incarnation restored a crashed predecessor's round
        (supervisors use this to tell resume from cold start)."""
        return int(getattr(self.server_manager, "server_epoch", 0)) > 0

    def run(self):
        self.server_manager.run()
        return self.aggregator.eval_history[-1] if self.aggregator.eval_history else {}
