"""Out-of-process device: the WAN bridge for the native edge agent.

Role of the reference Android split (``android/fedmlsdk``): a Java service
owns the MQTT connection and drives the on-device C++ MobileNN trainer;
here a Python bridge owns the comm-backend connection and drives the
standalone ``fedml_edge_agent`` PROCESS (``native/agent.cpp``) through its
directory protocol — model/update exchange stays FTEM files end to end, and
the training runtime holds no Python.

``AgentBridge`` is the transport-free core (spawn, submit job, await
update, stop); ``AgentDeviceManager`` plugs it into the cross-device round
protocol by overriding the fake device's local-training hook.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import time
from typing import Dict, Optional, Tuple

import numpy as np

from .edge_model import save_edge_model
from .fake_device import FakeDeviceManager


class AgentBridge:
    def __init__(self, workdir: str, poll_s: float = 0.05, spawn: bool = True):
        from .. import native

        self.workdir = os.path.abspath(workdir)
        self.inbox = os.path.join(self.workdir, "inbox")
        self.outbox = os.path.join(self.workdir, "outbox")
        os.makedirs(self.inbox, exist_ok=True)
        os.makedirs(self.outbox, exist_ok=True)
        self.poll_s = float(poll_s)
        self._proc: Optional[subprocess.Popen] = None
        if spawn:
            binary = native.build_agent()
            log = open(os.path.join(self.workdir, "agent.log"), "ab")
            self._proc = subprocess.Popen(
                [binary, "--dir", self.workdir, "--poll-ms", "20"],
                stdout=log, stderr=subprocess.STDOUT,
            )
            log.close()

    def submit(self, round_idx: int, model_path: str, data_path: str,
               batch_size: int, lr: float, epochs: int, seed: int) -> None:
        meta = (f"model={model_path}\ndata={data_path}\nbatch={batch_size}\n"
                f"lr={lr}\nepochs={epochs}\nseed={seed}\n")
        path = os.path.join(self.inbox, f"job_r{round_idx}.meta")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(meta)
        os.replace(tmp, path)

    def await_update(self, round_idx: int, timeout: float = 120.0
                     ) -> Tuple[str, Dict[str, float]]:
        """Blocks until update_r<k>.done (or .err) appears; returns
        (update_ftem_path, metrics)."""
        done = os.path.join(self.outbox, f"update_r{round_idx}.done")
        errf = os.path.join(self.outbox, f"update_r{round_idx}.err")
        deadline = time.time() + timeout
        while time.time() < deadline:
            if os.path.exists(errf):
                with open(errf) as f:
                    raise RuntimeError(f"agent job r{round_idx}: {f.read().strip()}")
            if os.path.exists(done):
                metrics = {}
                with open(done) as f:
                    for line in f:
                        k, _, v = line.strip().partition("=")
                        if v:
                            metrics[k] = float(v)
                return os.path.join(self.outbox, f"update_r{round_idx}.ftem"), metrics
            if self._proc is not None and self._proc.poll() is not None:
                raise RuntimeError(
                    f"agent died (rc={self._proc.returncode}) before r{round_idx}"
                )
            time.sleep(self.poll_s)
        raise TimeoutError(f"agent job r{round_idx} timed out")

    def status(self) -> Dict[str, str]:
        path = os.path.join(self.workdir, "status")
        out: Dict[str, str] = {}
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    k, _, v = line.strip().partition("=")
                    out[k] = v
        return out

    def close(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            with open(os.path.join(self.workdir, "stop"), "w") as f:
                f.write("1")
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        self._proc = None


class AgentDeviceManager(FakeDeviceManager):
    """A cross-device client whose training runs in the agent process."""

    def __init__(self, args, rank, train_data, client_num,
                 backend: str = "LOOPBACK", upload_dir: Optional[str] = None):
        super().__init__(args, rank, train_data, client_num,
                         backend=backend, upload_dir=upload_dir, use_native=False)
        self.bridge = AgentBridge(os.path.join(self.upload_dir, "agent"))
        # device-side data files in both layouts (see FakeDeviceManager)
        y32 = np.asarray(self.y, np.int32)
        x = np.asarray(self.x, np.float32)
        self._agent_data_2d = os.path.join(self.upload_dir, "agent_data_2d.ftem")
        save_edge_model(self._agent_data_2d, {"x": x.reshape(len(x), -1), "y": y32})
        self._agent_data_4d = None
        if x.ndim == 4:
            self._agent_data_4d = os.path.join(self.upload_dir, "agent_data_4d.ftem")
            save_edge_model(self._agent_data_4d, {"x": x, "y": y32})

    def _train_local_file(self, model_file: str, round_idx: int) -> Tuple[str, int]:
        from .edge_model import load_edge_model

        model_flat = load_edge_model(model_file)
        is_conv = any(v.ndim == 4 and k.endswith("/kernel")
                      for k, v in model_flat.items())
        data = self._agent_data_4d if (is_conv and self._agent_data_4d) else self._agent_data_2d
        self.bridge.submit(
            round_idx, model_file, data,
            batch_size=int(getattr(self.args, "batch_size", 32)),
            lr=float(getattr(self.args, "learning_rate", 0.1)),
            epochs=int(getattr(self.args, "epochs", 1)),
            seed=round_idx * 1000 + self.rank,
        )
        update, metrics = self.bridge.await_update(round_idx)
        # the server protocol expects the update under the device upload dir
        out_path = os.path.join(self.upload_dir, f"model_r{round_idx}_c{self.rank}.ftem")
        shutil.copyfile(update, out_path)
        return out_path, int(metrics.get("num_samples", len(self.y)))

    def _on_model(self, msg) -> None:
        from ..core.distributed.communication.message import Message
        from .message_define import MNNMessage

        model_file = msg.get(MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE)
        round_idx = int(msg.get(MNNMessage.MSG_ARG_KEY_ROUND_INDEX) or 0)
        out_path, n = self._train_local_file(model_file, round_idx)
        self.rounds_trained += 1
        m = Message(MNNMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        # round tag: lets a straggler-tolerant server drop uploads that
        # arrive after their round was closed by round_timeout_s
        m.add_params(MNNMessage.MSG_ARG_KEY_ROUND_INDEX, round_idx)
        m.add_params(MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE, out_path)
        m.add_params(MNNMessage.MSG_ARG_KEY_NUM_SAMPLES, n)
        self.send_message(m)

    def finish(self) -> None:
        self.bridge.close()
        super().finish()
