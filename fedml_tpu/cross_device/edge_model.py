"""FTEM — the edge model interchange file.

Role of the serialized MNN graph file in the reference Beehive stack
(``cross_device/server_mnn/fedml_aggregator.py:38``
``get_global_model_params_file``; mobile side
``android/fedmlsdk/MobileNN/``): the unit of model exchange between server
and device is a FILE, not an in-memory pytree, because the device runtime is
not Python.  FTEM is deliberately trivial to parse from C (the native edge
trainer in ``native/`` reads/writes it):

    magic   4 bytes  b"FTEM"
    version u32      1
    count   u32      number of tensors
    per tensor:
        name_len u32, name utf-8 (``/``-joined pytree path)
        dtype    u8   (0 = float32, 1 = int32)
        ndim     u32, dims u32[ndim]
        data     raw little-endian bytes (C order)

All integers little-endian.  Tensors are written in sorted-name order so the
file is a canonical function of its contents.
"""

from __future__ import annotations

import struct
from typing import Any, Dict

import numpy as np

MAGIC = b"FTEM"
VERSION = 1
_DTYPES = {0: np.float32, 1: np.int32}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.int32): 1}


def flatten_params(tree: Any) -> Dict[str, np.ndarray]:
    """Nested-dict pytree -> flat ``{"a/b/c": ndarray}`` (float leaves cast to
    f32, int leaves to i32 — the edge runtime's two dtypes)."""
    import jax

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        arr = np.asarray(leaf)
        arr = arr.astype(np.int32) if np.issubdtype(arr.dtype, np.integer) else arr.astype(np.float32)
        flat[name] = arr
    return flat


def unflatten_params(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Inverse of :func:`flatten_params` for dict pytrees."""
    out: Dict[str, Any] = {}
    for name, arr in flat.items():
        node = out
        parts = name.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return out


def save_edge_model(path: str, params: Any) -> str:
    """Write a pytree (or an already-flat name->array dict) as an FTEM file."""
    flat = params if _is_flat(params) else flatten_params(params)
    with open(path + ".tmp", "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<II", VERSION, len(flat)))
        for name in sorted(flat):
            arr = np.ascontiguousarray(flat[name])
            code = _DTYPE_CODES.get(arr.dtype)
            if code is None:
                arr = arr.astype(np.float32)
                code = 0
            nb = name.encode("utf-8")
            f.write(struct.pack("<I", len(nb)))
            f.write(nb)
            f.write(struct.pack("<BI", code, arr.ndim))
            f.write(struct.pack(f"<{arr.ndim}I", *arr.shape))
            f.write(arr.tobytes())
    import os

    os.replace(path + ".tmp", path)
    return path


def load_edge_model(path: str) -> Dict[str, np.ndarray]:
    """Read an FTEM file back to a flat name->array dict."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != MAGIC:
        raise ValueError(f"{path}: not an FTEM file")
    version, count = struct.unpack_from("<II", data, 4)
    if version != VERSION:
        raise ValueError(f"{path}: unsupported FTEM version {version}")
    off = 12
    flat: Dict[str, np.ndarray] = {}
    for _ in range(count):
        (name_len,) = struct.unpack_from("<I", data, off)
        off += 4
        name = data[off : off + name_len].decode("utf-8")
        off += name_len
        code, ndim = struct.unpack_from("<BI", data, off)
        off += 5
        dims = struct.unpack_from(f"<{ndim}I", data, off)
        off += 4 * ndim
        dtype = np.dtype(_DTYPES[code]).newbyteorder("<")
        count = int(np.prod(dims, dtype=np.int64))  # prod(()) == 1 covers scalars
        arr = np.frombuffer(data, dtype=dtype, count=count, offset=off)
        off += count * dtype.itemsize
        flat[name] = arr.reshape(dims).astype(_DTYPES[code])
    return flat


def _is_flat(obj: Any) -> bool:
    return isinstance(obj, dict) and all(isinstance(v, np.ndarray) for v in obj.values())
