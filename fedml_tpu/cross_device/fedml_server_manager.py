"""Cross-device server round state machine.

Parity with reference ``cross_device/server_mnn/fedml_server_manager.py``:
the same ONLINE-handshake → init-config → collect/aggregate/test/sync loop as
cross-silo, except the model rides as a FILE reference
(``MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE``) that devices download and
upload — the message plane never carries tensors.
"""

from __future__ import annotations

import logging
from typing import Dict, List

from ..core.distributed.comm_manager import FedMLCommManager
from ..core.distributed.communication.message import Message
from .message_define import MNNMessage

logger = logging.getLogger(__name__)


class FedMLServerManager(FedMLCommManager):
    def __init__(self, args, aggregator, comm=None, client_rank: int = 0, client_num: int = 0,
                 backend: str = "LOOPBACK"):
        super().__init__(args, comm, client_rank, client_num + 1, backend)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 1))
        self.args.round_idx = 0
        self.client_num = int(client_num)
        self.client_online_status: Dict[int, bool] = {}
        self.is_initialized = False
        self.client_id_list_in_this_round: List[int] = list(range(1, self.client_num + 1))

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", self._on_connection_ready)
        self.register_message_receive_handler(
            MNNMessage.MSG_TYPE_C2S_CLIENT_STATUS, self._on_client_status
        )
        self.register_message_receive_handler(
            MNNMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self._on_model_from_client
        )

    # -- handshake ------------------------------------------------------------
    def _on_connection_ready(self, msg: Message) -> None:
        for client_id in range(1, self.client_num + 1):
            self.send_message(
                Message(MNNMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self.rank, client_id)
            )

    def _on_client_status(self, msg: Message) -> None:
        if msg.get(MNNMessage.MSG_ARG_KEY_CLIENT_STATUS) == MNNMessage.CLIENT_STATUS_ONLINE:
            self.client_online_status[int(msg.get_sender_id())] = True
        if not self.is_initialized and all(
            self.client_online_status.get(cid, False) for cid in range(1, self.client_num + 1)
        ):
            self.is_initialized = True
            self._send_round(MNNMessage.MSG_TYPE_S2C_INIT_CONFIG)

    # -- round loop -----------------------------------------------------------
    def _send_round(self, msg_type) -> None:
        model_file = self.aggregator.get_global_model_params_file(self.args.round_idx)
        for client_id in self.client_id_list_in_this_round:
            m = Message(msg_type, self.rank, client_id)
            m.add_params(MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE, model_file)
            m.add_params(MNNMessage.MSG_ARG_KEY_CLIENT_INDEX, client_id - 1)
            m.add_params(MNNMessage.MSG_ARG_KEY_ROUND_INDEX, self.args.round_idx)
            self.send_message(m)

    def _on_model_from_client(self, msg: Message) -> None:
        sender = int(msg.get_sender_id())
        model_file = msg.get(MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE)
        n = msg.get(MNNMessage.MSG_ARG_KEY_NUM_SAMPLES)
        self.aggregator.add_local_trained_result(
            self.client_id_list_in_this_round.index(sender), model_file, n
        )
        if not self.aggregator.check_whether_all_receive():
            return
        self.aggregator.aggregate()
        freq = int(getattr(self.args, "frequency_of_the_test", 1) or 0)
        if freq and (self.args.round_idx % freq == 0 or self.args.round_idx == self.round_num - 1):
            self.aggregator.test_on_server_for_all_clients(self.args.round_idx)

        self.args.round_idx += 1
        if self.args.round_idx >= self.round_num:
            for client_id in range(1, self.client_num + 1):
                self.send_message(Message(MNNMessage.MSG_TYPE_S2C_FINISH, self.rank, client_id))
            self.finish()
            return
        self._send_round(MNNMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
