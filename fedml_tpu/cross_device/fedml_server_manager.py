"""Cross-device server round state machine.

Parity with reference ``cross_device/server_mnn/fedml_server_manager.py``:
the same ONLINE-handshake → init-config → collect/aggregate/test/sync loop as
cross-silo, except the model rides as a FILE reference
(``MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE``) that devices download and
upload — the message plane never carries tensors.

Beyond-reference: the same ``round_timeout_s`` straggler tolerance as the
cross-silo server — on a fleet of phones, devices dropping mid-round is the
NORM, not a fault; the timer closes each round with the devices that
uploaded (>= ``round_timeout_min_clients``) and stale uploads are dropped
by round tag.  Default (knob unset) keeps reference wait-forever semantics.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, List, Optional

from ..core import ingest, obs
from ..core.async_fl import AsyncBufferedServerMixin
from ..core.checkpoint import ServerRecoveryMixin
from ..core.distributed.comm_manager import FedMLCommManager
from ..core.distributed.communication.message import Message
from ..core.distributed.straggler import RoundTimeoutMixin
from ..core.obs.rounds import RoundObsMixin
from ..core.population import PopulationPacingMixin
from .edge_model import load_edge_model
from .message_define import MNNMessage

logger = logging.getLogger(__name__)


class FedMLServerManager(RoundObsMixin, ServerRecoveryMixin,
                         AsyncBufferedServerMixin,
                         PopulationPacingMixin, RoundTimeoutMixin,
                         FedMLCommManager):
    def __init__(self, args, aggregator, comm=None, client_rank: int = 0, client_num: int = 0,
                 backend: str = "LOOPBACK"):
        super().__init__(args, comm, client_rank, client_num + 1, backend)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 1))
        self.args.round_idx = 0
        self.client_num = int(client_num)
        # cohort target per round; the fleet (client_num) may be larger —
        # devices not selected for a round just idle until the next select
        self.per_round = int(getattr(args, "client_num_per_round", self.client_num) or self.client_num)
        self.client_online_status: Dict[int, bool] = {}
        self.is_initialized = False
        self.client_id_list_in_this_round: List[int] = list(range(1, self.client_num + 1))
        # straggler tolerance (0 = reference semantics: wait forever) —
        # the shared machinery lives in core/distributed/straggler.py
        self.init_straggler_tolerance(args)
        # fleet registry + selection policy + pacer (core/population)
        self.init_population(args, list(range(1, self.client_num + 1)),
                             rng_style="pcg64")
        # buffered-async execution (fl_mode=async): buffer + staleness
        # scheduler + version-tagged in-flight table (core/async_fl)
        self.init_async_fl(args)
        # accepted-upload file per (sender, version): deleted only once the
        # flush that consumed the delta has a durable successor snapshot
        self._async_files: Dict[tuple, str] = {}
        # broadcast cache: export the global model FILE once per round — the
        # file-plane analog of cross_silo's serialized-payload cache
        self._model_file_cache: tuple = (None, None)
        # zero-copy ingest arenas for the async accept path (the sync path
        # stores file references, nothing to intern)
        self._zero_copy = (ingest.ZeroCopyDecoder()
                           if ingest.pipeline_enabled(args) else None)
        # crash recovery last: a restore overwrites round_idx / participant
        # list / registry columns and replays the open round's journal
        self.init_server_recovery(args)
        if self.is_initialized:
            # restored mid-round: hold the open round's root span without
            # re-emitting its start (the dead incarnation opened it)
            self._obs_adopt_round()
            if self.async_enabled:
                # the snapshot's participants are the run's pool; their
                # ONLINE re-reports resync them into the open cycle
                self._async_active.update(self.client_id_list_in_this_round)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler("connection_ready", self._on_connection_ready)
        self.register_message_receive_handler(
            MNNMessage.MSG_TYPE_C2S_CLIENT_STATUS, self._on_client_status
        )
        self.register_message_receive_handler(
            MNNMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self._on_model_from_client
        )
        self.register_message_receive_handler(
            obs.TOPIC_TELEMETRY, self._on_telemetry
        )

    def _telemetry_merger(self):
        """This server's telemetry fan-in (lazily bound, per-instance);
        merge counters land in flight-recorder dump meta."""
        merger = getattr(self, "_telemetry", None)
        if merger is None:
            merger = obs.make_telemetry_merger()
            self._telemetry = merger
            if merger is not None:
                flight = obs.flight_recorder()
                if flight is not None:
                    flight.meta_provider = merger.counters
        return merger

    def _on_telemetry(self, msg: Message) -> None:
        merger = self._telemetry_merger()
        if merger is not None:
            merger.absorb(msg)

    # -- handshake ------------------------------------------------------------
    def _on_connection_ready(self, msg: Message) -> None:
        for client_id in range(1, self.client_num + 1):
            self._send_safe(
                Message(MNNMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self.rank, client_id)
            )

    def _on_client_status(self, msg: Message) -> None:
        with self._round_lock:
            if msg.get(MNNMessage.MSG_ARG_KEY_CLIENT_STATUS) == MNNMessage.CLIENT_STATUS_ONLINE:
                sender = int(msg.get_sender_id())
                if self._note_client_online(sender, msg.get(MNNMessage.MSG_ARG_KEY_CLIENT_EPOCH)):
                    self._resync_rejoined_client(sender)
            self._handshake_check()
            # restored round whose journal already held the full cohort:
            # close it now that the transport is live
            self._maybe_close_recovered_round()

    def _resync_rejoined_client(self, client_id: int) -> None:
        """(lock held) A device that dropped and came back gets the current
        round's model file immediately — on a phone fleet, churn is the norm
        and waiting for the run to end would waste every rejoining device."""
        if self._finished:
            self._send_safe(Message(MNNMessage.MSG_TYPE_S2C_FINISH, self.rank, client_id))
            return
        if self.async_enabled:
            self._async_resync(client_id)
            return
        if client_id not in self.client_id_list_in_this_round:
            return
        if self.client_id_list_in_this_round.index(client_id) in self.aggregator.received_indices():
            return
        model_file = self._round_model_file()
        m = Message(MNNMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, client_id)
        m.add_params(MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE, model_file)
        m.add_params(MNNMessage.MSG_ARG_KEY_CLIENT_INDEX, client_id - 1)
        m.add_params(MNNMessage.MSG_ARG_KEY_ROUND_INDEX, self.args.round_idx)
        self._send_safe(m)

    def _round_model_file(self) -> str:
        """Export the global model file at most once per round: every invite,
        resync and async dispatch of one round hands out the same path
        instead of re-serializing the identical model per device."""
        key = int(self.args.round_idx)
        cached_key, path = self._model_file_cache
        if cached_key != key:
            path = self.aggregator.get_global_model_params_file(key)
            self._model_file_cache = (key, path)
        return path

    def send_init_msg(self) -> None:
        self._send_round(MNNMessage.MSG_TYPE_S2C_INIT_CONFIG)

    def send_finish_msg(self) -> None:
        for client_id in range(1, self.client_num + 1):
            self._send_safe(Message(MNNMessage.MSG_TYPE_S2C_FINISH, self.rank, client_id))

    # -- round loop -----------------------------------------------------------
    def _send_round(self, msg_type) -> None:
        self._obs_open_round()
        # per-round cohort via the population policy (full participation when
        # per_round == fleet and the policy is uniform — the legacy schedule)
        with self._obs_phase("select", k=self.per_round):
            self.client_id_list_in_this_round = self._population_round_list(
                self.args.round_idx, self.per_round
            )
        model_file = self._round_model_file()
        # durable round-open point: cohort is fixed, no upload accepted yet —
        # a crash from here on resumes this round in a fresh incarnation
        self._save_round_start()
        with self._obs_phase(
                "invite", fanout=len(self.client_id_list_in_this_round)) as inv:
            for client_id in self.client_id_list_in_this_round:
                m = Message(msg_type, self.rank, client_id)
                m.add_params(MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE, model_file)
                m.add_params(MNNMessage.MSG_ARG_KEY_CLIENT_INDEX, client_id - 1)
                m.add_params(MNNMessage.MSG_ARG_KEY_ROUND_INDEX, self.args.round_idx)
                obs.inject(m, inv.ctx)
                self._send_safe(m)
        if self.async_enabled:
            # cycle 0: the wave above is the initial dispatch; from here on
            # the flush loop re-dispatches (no round timer in async mode)
            self._async_note_dispatch_wave(self.client_id_list_in_this_round)
            return
        self._arm_round_timer()

    def _on_model_from_client(self, msg: Message) -> None:
        sender = int(msg.get_sender_id())
        with self._round_lock:
            # best-effort telemetry merge first: even a stale or dropped
            # upload's piggybacked blob is valid observability data
            merger = self._telemetry_merger()
            measured = None
            if merger is not None:
                merger.absorb(msg)
                measured = merger.train_seconds(sender)
            if self._finished:
                return
            if self.async_enabled:
                self._async_on_model(msg, sender, measured_seconds=measured)
                return
            if self._is_stale_upload(msg.get(MNNMessage.MSG_ARG_KEY_ROUND_INDEX, None), sender):
                return
            if sender not in self.client_id_list_in_this_round:
                logger.warning("dropping upload from non-participant device %d", sender)
                return
            model_file = msg.get(MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE)
            n = msg.get(MNNMessage.MSG_ARG_KEY_NUM_SAMPLES)
            # journal-before-slot-table (the ack follows the handler): the
            # message plane carries only the upload FILE path, so that is
            # what the journal records — replay skips entries whose file
            # vanished (the resync path re-invites those devices instead)
            with self._obs_phase("journal.append", parent=obs.extract(msg),
                                 seq=sender, sender=sender) as jsp:
                ok = self._journal_upload(sender, model_file=str(model_file),
                                          n_samples=n)
                if not ok:
                    jsp.event("dup", side="journal", sender=sender)
            if not ok:
                return
            self.aggregator.add_local_trained_result(
                self.client_id_list_in_this_round.index(sender), model_file, n
            )
            self._note_population_report(sender, n, seconds=measured)
            self._close_round_if_complete()

    def _finalize_round(self, indices: Optional[List[int]]) -> None:
        """(lock held) Aggregate the cohort, eval, finish-or-sync."""
        self._gen += 1  # this round's phase closes; its timers go stale
        closing_idx = int(self.args.round_idx)
        closing_ctx = self._obs_round_ctx()
        closing_root = self._obs_round
        with self._obs_phase(
                "aggregate",
                n_uploads=(len(indices) if indices is not None
                           else len(self.client_id_list_in_this_round))):
            self.aggregator.aggregate(indices)
            freq = int(getattr(self.args, "frequency_of_the_test", 1) or 0)
            if freq and (self.args.round_idx % freq == 0 or self.args.round_idx == self.round_num - 1):
                self.aggregator.test_on_server_for_all_clients(self.args.round_idx)
        obs.maybe_export_metrics()

        self.args.round_idx += 1
        if self.args.round_idx >= self.round_num:
            self._finished = True
            with self._obs_phase("broadcast", parent=closing_ctx,
                                 round_idx=closing_idx, final=True):
                self.send_finish_msg()
            self._obs_close_round(reason="run_complete")
            self.finish()
            return
        # span handoff: the closing round's root stays open until its
        # aggregate has been broadcast; _send_round opens the next root and
        # its invite span while the broadcast span sits under the old root
        self._obs_round = None
        bcast = self._obs_phase("broadcast", parent=closing_ctx,
                                round_idx=closing_idx)
        self._send_round(MNNMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
        bcast.end()
        if closing_root is not None:
            closing_root.end(reason="closed")

    # -- AsyncBufferedServerMixin hooks (core/async_fl) ----------------------
    def _async_on_model(self, msg: Message, sender: int,
                        measured_seconds: Optional[float] = None) -> None:
        """(lock held) File-plane async accept: load the uploaded file into
        a flat params dict for the buffer; the journal records only the FILE
        path (``journal_params=False``) like the sync path does.  The file
        outlives the flush that consumes it (see ``_async_after_flush``) —
        a crash between flush and the successor snapshot replays it."""
        model_file = str(msg.get(MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE))
        n = msg.get(MNNMessage.MSG_ARG_KEY_NUM_SAMPLES)
        tag = msg.get(MNNMessage.MSG_ARG_KEY_ROUND_INDEX, None)
        try:
            t_dec = time.perf_counter()
            params = load_edge_model(model_file)
            obs.histogram_observe("upload.decode_seconds",
                                  time.perf_counter() - t_dec,
                                  labels={"plane": "cross_device"})
        except Exception as e:
            logger.warning("dropping unreadable upload file %s from device "
                           "%d: %s", model_file, sender, e)
            return
        key = (int(sender), None if tag is None else int(tag))
        self._async_files[key] = model_file
        accepted = self._async_handle_upload(
            sender, params, n, tag, parent_ctx=obs.extract(msg),
            journal_extra={"model_file": model_file}, journal_params=False,
            measured_seconds=measured_seconds)
        if not accepted:
            # dropped (dup/stale/untagged): its file is dead weight now
            self._async_files.pop(key, None)
            try:
                os.remove(model_file)
            except OSError:
                pass

    def _async_send_model(self, client_id: int, parent_ctx=None) -> None:
        model_file = self._round_model_file()
        m = Message(MNNMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, client_id)
        m.add_params(MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE, model_file)
        m.add_params(MNNMessage.MSG_ARG_KEY_CLIENT_INDEX, client_id - 1)
        m.add_params(MNNMessage.MSG_ARG_KEY_ROUND_INDEX, self.args.round_idx)
        obs.inject(m, parent_ctx)
        self._send_safe(m)

    def _async_eval_round(self, round_idx: int) -> None:
        # appends to the AGGREGATOR's eval_history itself (the manager has
        # none — see _round_start_extras)
        self.aggregator.test_on_server_for_all_clients(int(round_idx))

    def _async_replay_params(self, record: Dict[str, Any]):
        model_file = str(record.get("model_file", ""))
        if not model_file or not os.path.exists(model_file):
            logger.warning("journal replay: upload file %s vanished; device "
                           "%s will be re-synced", model_file or "<missing>",
                           record.get("sender"))
            return None
        try:
            params = load_edge_model(model_file)
        except Exception as e:
            logger.warning("journal replay: unreadable upload file %s: %s",
                           model_file, e)
            return None
        v = int(record.get("version", record.get("round_idx", 0)))
        self._async_files[(int(record["sender"]), v)] = model_file
        return params

    def _async_after_flush(self, entries) -> None:
        for e in entries:
            path = self._async_files.pop((e.sender, e.version), None)
            if path:
                try:
                    os.remove(path)
                except OSError:
                    pass

    # -- ServerRecoveryMixin hooks (core/checkpoint.py) ----------------------
    def _capture_global_params(self):
        return self.aggregator.export_state()

    def _restore_global_params(self, flat) -> None:
        self.aggregator.restore_state(flat)

    def _round_start_extras(self) -> Dict[str, Any]:
        # eval history lives on the cross-device AGGREGATOR (the manager has
        # none); persist it so ServerDevice.run()'s summary survives a crash
        return {"eval_history": list(self.aggregator.eval_history)}

    def _restore_round_extras(self, state: Dict[str, Any]) -> None:
        self.aggregator.eval_history = [
            dict(r) for r in state.get("eval_history", [])
        ]

    def _capture_server_opt_state(self):
        return self.aggregator.export_server_opt_state()

    def _restore_server_opt_state(self, state) -> None:
        self.aggregator.restore_server_opt_state(state)

    def _replay_upload(self, record: Dict[str, Any]) -> bool:
        """Re-insert one journaled upload.  The journal holds the upload's
        FILE path, not its tensors — if the file is gone (tmpdir wipe), the
        entry is dropped and the device is re-synced like any straggler."""
        if self.async_enabled:
            return self._async_replay_upload(record)
        sender = int(record["sender"])
        if sender not in self.client_id_list_in_this_round:
            return False
        model_file = str(record["model_file"])
        if not os.path.exists(model_file):
            logger.warning("journal replay: upload file %s vanished; device "
                           "%d will be re-synced", model_file, sender)
            return False
        self.aggregator.add_local_trained_result(
            self.client_id_list_in_this_round.index(sender), model_file,
            record["n_samples"],
        )
        self._note_population_report(sender, record["n_samples"])
        return True
