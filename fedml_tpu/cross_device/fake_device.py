"""Fake edge device: the Python stand-in for a phone.

Parity with reference ``python/tests/android_protocol_test/`` (the harness
that drives the Android message protocol from Python): a numpy-only client
that speaks the full cross-device round protocol — ONLINE handshake, model
FILE download, on-device training, model FILE upload.  Deliberately uses no
JAX: devices run the native edge runtime (``native/``), and this harness
emulates exactly that boundary (FTEM files in, FTEM files out).

Training supports the edge model family (logistic regression / one-hidden
-layer MLP, reference MobileNN trains LeNet-class models): plain softmax-CE
SGD written in numpy.
"""

from __future__ import annotations

import logging
import os
import tempfile
from typing import Dict, Optional, Tuple

import numpy as np

from ..core import obs
from ..core.distributed.comm_manager import FedMLCommManager
from ..core.distributed.communication.message import Message
from .edge_model import load_edge_model, save_edge_model
from .message_define import MNNMessage

logger = logging.getLogger(__name__)


def _softmax(z: np.ndarray) -> np.ndarray:
    z = z - z.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


def train_numpy(
    flat: Dict[str, np.ndarray],
    x: np.ndarray,
    y: np.ndarray,
    lr: float = 0.1,
    epochs: int = 1,
    batch_size: int = 32,
    seed: int = 0,
) -> Dict[str, np.ndarray]:
    """SGD on a dense stack (kernel/bias pairs, relu between): the numpy twin
    of the native edge trainer's loop."""
    layers = _dense_stack(flat)
    x = x.reshape(x.shape[0], -1).astype(np.float64)
    y = np.asarray(y, np.int64)
    rng = np.random.RandomState(seed)
    params = [(flat[k].astype(np.float64), flat[b].astype(np.float64)) for k, b in layers]
    for _ in range(int(epochs)):
        order = rng.permutation(len(y))
        for s in range(0, len(y), batch_size):
            idx = order[s : s + batch_size]
            xb, yb = x[idx], y[idx]
            # forward
            acts = [xb]
            for li, (W, b) in enumerate(params):
                z = acts[-1] @ W + b
                acts.append(np.maximum(z, 0.0) if li < len(params) - 1 else z)
            probs = _softmax(acts[-1])
            g = probs
            g[np.arange(len(yb)), yb] -= 1.0
            g /= len(yb)
            # backward
            for li in reversed(range(len(params))):
                W, b = params[li]
                gW = acts[li].T @ g
                gb = g.sum(axis=0)
                if li > 0:
                    g = (g @ W.T) * (acts[li] > 0)
                params[li] = (W - lr * gW, b - lr * gb)
    out = dict(flat)
    for (kname, bname), (W, b) in zip(layers, params):
        out[kname] = W.astype(np.float32)
        out[bname] = b.astype(np.float32)
    return out


def _dense_stack(flat: Dict[str, np.ndarray]):
    """Order the kernel/bias pairs by matching input/output dims."""
    pairs = []
    for name in sorted(flat):
        if name.endswith("/kernel") and flat[name].ndim == 2:
            bias = name[: -len("kernel")] + "bias"
            if bias in flat:
                pairs.append((name, bias))
    if not pairs:
        raise ValueError("edge trainer supports dense stacks (kernel/bias pairs) only")
    # chain them: find the pair order where out-dim(i) == in-dim(i+1)
    ordered = [pairs.pop(0)]
    changed = True
    while pairs and changed:
        changed = False
        for p in list(pairs):
            if flat[p[0]].shape[0] == flat[ordered[-1][0]].shape[1]:
                ordered.append(p)
                pairs.remove(p)
                changed = True
            elif flat[p[0]].shape[1] == flat[ordered[0][0]].shape[0]:
                ordered.insert(0, p)
                pairs.remove(p)
                changed = True
    return ordered + pairs


class FakeDeviceManager(FedMLCommManager):
    """One fake phone; give it a (x, y) shard and run it on a thread.

    ``use_native=True`` trains through the C++ edge runtime
    (``fedml_tpu.native.EdgeTrainer`` over libfedml_edge.so) instead of the
    numpy twin — the closest in-process stand-in for a real device."""

    def __init__(self, args, rank: int, train_data: Tuple[np.ndarray, np.ndarray],
                 client_num: int, backend: str = "LOOPBACK", upload_dir: Optional[str] = None,
                 use_native: bool = False):
        super().__init__(args, None, rank, client_num + 1, backend)
        import uuid

        self.x, self.y = train_data
        # per-incarnation epoch: lets the server tell a rejoined device from
        # a duplicate ONLINE and resync it with the current round's model
        self.client_epoch = uuid.uuid4().hex[:8]
        self.upload_dir = upload_dir or tempfile.mkdtemp(prefix=f"fedml_tpu_dev{rank}_")
        os.makedirs(self.upload_dir, exist_ok=True)
        self.rounds_trained = 0
        self.use_native = bool(use_native)
        if self.use_native:  # write the device-side data file once
            from .. import native

            native.build()  # sequential: don't race make across device threads
            # the model family (dense vs conv) is only known when the server
            # sends the model, so write BOTH layouts up front: flat [n, d]
            # for dense trainers, original [n, H, W, C] for conv trainers
            y32 = np.asarray(self.y, np.int32)
            x = np.asarray(self.x, np.float32)
            self._data_path_2d = os.path.join(self.upload_dir, "local_data_2d.ftem")
            save_edge_model(self._data_path_2d, {"x": x.reshape(len(x), -1), "y": y32})
            self._data_path_4d = None
            if x.ndim == 4:
                self._data_path_4d = os.path.join(self.upload_dir, "local_data_4d.ftem")
                save_edge_model(self._data_path_4d, {"x": x, "y": y32})

    def register_message_receive_handlers(self) -> None:
        # announce ONLINE on our own connect too (not only when probed): a
        # device that rejoins mid-run gets no fresh CHECK from the server —
        # its self-announcement with a new epoch is what triggers the resync
        self.register_message_receive_handler("connection_ready", self._on_check_status)
        self.register_message_receive_handler(
            MNNMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self._on_check_status
        )
        self.register_message_receive_handler(
            MNNMessage.MSG_TYPE_S2C_INIT_CONFIG, self._on_model
        )
        self.register_message_receive_handler(
            MNNMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self._on_model
        )
        self.register_message_receive_handler(
            MNNMessage.MSG_TYPE_S2C_FINISH, lambda m: self.finish()
        )

    def _on_check_status(self, msg: Message) -> None:
        m = Message(MNNMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
        m.add_params(MNNMessage.MSG_ARG_KEY_CLIENT_STATUS, MNNMessage.CLIENT_STATUS_ONLINE)
        m.add_params(MNNMessage.MSG_ARG_KEY_CLIENT_EPOCH, self.client_epoch)
        self.send_message(m)

    def _telemetry_capture(self):
        """This device's telemetry ring (lazily bound to the obs plane)."""
        cap = getattr(self, "_telemetry", None)
        if cap is None:
            cap = obs.make_client_telemetry(self.rank)
            self._telemetry = cap
        return cap

    def _on_model(self, msg: Message) -> None:
        import time as _time

        model_file = msg.get(MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE)
        round_idx = int(msg.get(MNNMessage.MSG_ARG_KEY_ROUND_INDEX) or 0)
        invite_ctx = obs.extract(msg)  # server invite span (or None)
        out_path = os.path.join(self.upload_dir, f"model_r{round_idx}_c{self.rank}.ftem")
        t_train0 = _time.monotonic()
        train_span = obs.span("client.train", invite_ctx, round_idx=round_idx,
                              node=self.rank, native=self.use_native)
        if self.use_native:
            from .. import native

            # pick the data layout the received model's family needs
            model_flat = load_edge_model(model_file)
            is_conv = any(v.ndim == 4 and k.endswith("/kernel") for k, v in model_flat.items())
            data_path = self._data_path_4d if (is_conv and self._data_path_4d) else self._data_path_2d
            t = native.EdgeTrainer(
                model_file,
                data_path,
                batch_size=int(getattr(self.args, "batch_size", 32)),
                lr=float(getattr(self.args, "learning_rate", 0.1)),
                epochs=int(getattr(self.args, "epochs", 1)),
                seed=round_idx * 1000 + self.rank,
            )
            t.train()
            t.save(out_path)
            t.close()
        else:
            flat = load_edge_model(model_file)
            trained = train_numpy(
                flat,
                self.x,
                self.y,
                lr=float(getattr(self.args, "learning_rate", 0.1)),
                epochs=int(getattr(self.args, "epochs", 1)),
                batch_size=int(getattr(self.args, "batch_size", 32)),
                seed=round_idx * 1000 + self.rank,
            )
            save_edge_model(out_path, trained)
        train_span.end()
        self.rounds_trained += 1
        cap = self._telemetry_capture()
        if cap is not None:
            # mirror the train interior for the server's cross-host report
            # (same deterministic span ids as the local span above)
            train_ctx = cap.record_span(
                "client.train", _time.monotonic() - t_train0,
                parent=invite_ctx, round_idx=round_idx,
                native=self.use_native)
            cap.record_span("client.train.step",
                            _time.monotonic() - t_train0, parent=train_ctx,
                            round_idx=round_idx)
            cap.sample_resources()
        m = Message(MNNMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        # round tag: lets a straggler-tolerant server drop uploads that
        # arrive after their round was closed by round_timeout_s
        m.add_params(MNNMessage.MSG_ARG_KEY_ROUND_INDEX, round_idx)
        m.add_params(MNNMessage.MSG_ARG_KEY_MODEL_PARAMS_FILE, out_path)
        m.add_params(MNNMessage.MSG_ARG_KEY_NUM_SAMPLES, int(len(self.y)))
        with obs.span("upload", invite_ctx, round_idx=round_idx,
                      node=self.rank) as up:
            obs.inject(m, up.ctx)
            if cap is not None:
                cap.attach(m)  # retransmits re-carry this same blob
            self.send_message(m)
