"""Centralized (non-federated) baselines — reference ``fedml/centralized``."""

from .centralized_trainer import CentralizedTrainer

__all__ = ["CentralizedTrainer"]
