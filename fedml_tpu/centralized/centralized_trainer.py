"""Centralized baseline trainer (reference ``python/fedml/centralized/``,
164 LoC): train the same model on the POOLED data with the same engine, so
federated results have an upper-bound comparison inside one framework."""

from __future__ import annotations

import logging
from typing import Any, Dict

import jax.numpy as jnp

from ..data.data_loader import load_centralized
from ..ml.aggregator.aggregator_creator import create_server_aggregator
from ..ml.engine.train import init_variables
from ..ml.trainer.trainer_creator import create_model_trainer

logger = logging.getLogger(__name__)


class CentralizedTrainer:
    def __init__(self, args, model=None):
        self.args = args
        self.data = load_centralized(args)
        if model is None:
            from ..models import hub

            model = hub.create(args, self.data["class_num"])
        self.module = model
        sample = jnp.asarray(self.data["x_train"][:1])
        self.variables = init_variables(model, sample, seed=int(getattr(args, "random_seed", 0)))
        self.trainer = create_model_trainer(model, args)
        self.aggregator = create_server_aggregator(model, args)

    def train(self) -> Dict[str, Any]:
        epochs_total = int(getattr(self.args, "comm_round", 1)) * int(
            getattr(self.args, "epochs", 1)
        )
        x, y = self.data["x_train"], self.data["y_train"]
        self.trainer.set_model_params(self.variables)
        last: Dict[str, Any] = {}
        for epoch in range(epochs_total):
            self.trainer.round_idx = epoch  # distinct shuffling per epoch
            self.trainer.train((x, y), None, self.args)
            last = self.test(epoch)
        self.variables = self.trainer.get_model_params()
        return last

    def test(self, epoch: int) -> Dict[str, Any]:
        self.aggregator.set_model_params(self.trainer.get_model_params())
        stats = self.aggregator.test(
            (self.data["x_test"], self.data["y_test"]), None, self.args
        )
        out = {
            "epoch": epoch,
            "test_acc": round(stats["test_correct"] / max(stats["test_total"], 1.0), 4),
            "test_loss": round(stats["test_loss"] / max(stats["test_total"], 1.0), 4),
        }
        logger.info("centralized eval: %s", out)
        return out
