"""Platform / backend / federated-optimizer constants.

Parity with the reference's ``python/fedml/constants.py`` (same string values so
user YAML configs written for the reference keep working), plus TPU-native
additions: the ``XLA`` simulation backend (in-mesh collectives over ICI) and
mesh-axis naming conventions used throughout :mod:`fedml_tpu.parallel`.
"""

# ---------------------------------------------------------------------------
# Training platforms (reference: python/fedml/constants.py:1-11)
# ---------------------------------------------------------------------------
FEDML_TRAINING_PLATFORM_SIMULATION = "simulation"
FEDML_TRAINING_PLATFORM_CROSS_SILO = "cross_silo"
FEDML_TRAINING_PLATFORM_CROSS_DEVICE = "cross_device"
FEDML_TRAINING_PLATFORM_DISTRIBUTED = "distributed"

FEDML_TRAINING_PLATFORM_CROSS_SILO_TYPE = 1
FEDML_TRAINING_PLATFORM_SIMULATION_TYPE = 2
FEDML_TRAINING_PLATFORM_DISTRIBUTED_TYPE = 3
FEDML_TRAINING_PLATFORM_CROSS_DEVICE_TYPE = 4

# ---------------------------------------------------------------------------
# Cross-silo scenarios (reference: constants.py:13-15)
# ---------------------------------------------------------------------------
FEDML_CROSS_SILO_SCENARIO_HORIZONTAL = "horizontal"
FEDML_CROSS_SILO_SCENARIO_HIERARCHICAL = "hierarchical"

# ---------------------------------------------------------------------------
# Simulation backends. The reference ships sp / MPI / NCCL
# (constants.py:17-20); this framework's native backend is XLA: simulated
# clients are sharded over a jax.sharding.Mesh and aggregated with in-program
# collectives (lax.psum) over ICI.  "sp", "MPI" and "NCCL" configs are accepted
# and routed to the closest native equivalent (sp -> SP loop; MPI/NCCL -> XLA).
# ---------------------------------------------------------------------------
FEDML_SIMULATION_TYPE_SP = "sp"
FEDML_SIMULATION_TYPE_MPI = "MPI"
FEDML_SIMULATION_TYPE_NCCL = "NCCL"
FEDML_SIMULATION_TYPE_XLA = "XLA"

# Host-side message-plane backends (cross-silo / cross-device).
FEDML_BACKEND_LOOPBACK = "LOOPBACK"
FEDML_BACKEND_GRPC = "GRPC"
FEDML_BACKEND_MQTT_S3 = "MQTT_S3"
FEDML_BACKEND_MQTT_S3_MNN = "MQTT_S3_MNN"
FEDML_BACKEND_TRPC = "TRPC"
FEDML_BACKEND_MPI = "MPI"

# ---------------------------------------------------------------------------
# Data cache
# ---------------------------------------------------------------------------
FEDML_DATA_CACHE_FOLDER = "fedml_data"

# ---------------------------------------------------------------------------
# Federated optimizers (reference: constants.py:27-47, same strings)
# ---------------------------------------------------------------------------
FedML_FEDERATED_OPTIMIZER_BASE_FRAMEWORK = "base_framework"
FedML_FEDERATED_OPTIMIZER_FEDAVG = "FedAvg"
FedML_FEDERATED_OPTIMIZER_FEDOPT = "FedOpt"
FedML_FEDERATED_OPTIMIZER_FEDPROX = "FedProx"
FedML_FEDERATED_OPTIMIZER_CLASSICAL_VFL = "classical_vertical"
FedML_FEDERATED_OPTIMIZER_SPLIT_NN = "split_nn"
FedML_FEDERATED_OPTIMIZER_DECENTRALIZED_FL = "decentralized_fl"
FedML_FEDERATED_OPTIMIZER_FEDGAN = "FedGAN"
FedML_FEDERATED_OPTIMIZER_FEDAVG_ROBUST = "FedAvg_robust"
FedML_FEDERATED_OPTIMIZER_FEDAVG_SEQ = "FedAvg_seq"
FedML_FEDERATED_OPTIMIZER_FEDOPT_SEQ = "FedOpt_seq"
FedML_FEDERATED_OPTIMIZER_FEDGKT = "FedGKT"
FedML_FEDERATED_OPTIMIZER_FEDNAS = "FedNAS"
FedML_FEDERATED_OPTIMIZER_FEDSEG = "FedSeg"
FedML_FEDERATED_OPTIMIZER_TURBO_AGGREGATE = "turbo_aggregate"
FedML_FEDERATED_OPTIMIZER_FEDNOVA = "FedNova"
FedML_FEDERATED_OPTIMIZER_HIERACHICAL_FL = "HierarchicalFL"
FedML_FEDERATED_OPTIMIZER_FEDSGD = "FedSGD"
FedML_FEDERATED_OPTIMIZER_FEDLOCALSGD = "FedLocalSGD"
FedML_FEDERATED_OPTIMIZER_ASYNC_FEDAVG = "Async_FedAvg"
FedML_FEDERATED_OPTIMIZER_FEDDYN = "FedDyn"
FedML_FEDERATED_OPTIMIZER_SCAFFOLD = "SCAFFOLD"
FedML_FEDERATED_OPTIMIZER_MIME = "Mime"
# decentralized multi-task GNN (reference research/SpreadGNN)
FedML_FEDERATED_OPTIMIZER_SPREADGNN = "SpreadGNN"

# ---------------------------------------------------------------------------
# TPU mesh-axis naming conventions (native additions).
#   client: simulated-FL client data parallelism (Parrot-XLA)
#   dp/fsdp: batch data parallelism inside one silo ("Cheetah")
#   tp: tensor parallelism; sp: sequence/context parallelism (ring attention)
#   pp: pipeline stages; ep: expert parallelism
# ---------------------------------------------------------------------------
MESH_AXIS_CLIENT = "client"
MESH_AXIS_DP = "dp"
MESH_AXIS_FSDP = "fsdp"
MESH_AXIS_TP = "tp"
MESH_AXIS_SP = "sp"
MESH_AXIS_PP = "pp"
MESH_AXIS_EP = "ep"

# FedProx default proximal term when the optimizer is selected without an
# explicit mu (shared by every backend so configs train the same objective)
FEDPROX_DEFAULT_MU = 0.1
