"""Device discovery (reference ``device/device.py`` + ``get_jax_device``).

On TPU the interesting object is not a single device but the mesh; this
returns the default jax device for eager host work and exposes mesh helpers
via fedml_tpu.parallel.
"""

from __future__ import annotations

import logging

import jax

logger = logging.getLogger(__name__)


def get_device(args=None):
    devices = jax.devices()
    dev = devices[0]
    logger.info("jax devices: %d x %s (using %s)", len(devices), dev.platform, dev)
    return dev
