"""ctypes bindings for the native edge runtime (``native/``).

The reference's JNI bridge (``android/fedmlsdk/src/main/jni/``) connects the
Java edge SDK to the C++ MobileNN trainer; here ctypes connects the Python
host stack to ``libfedml_edge.so`` (pybind11 is not in the image).  The
library is built on demand with ``make`` — g++ is part of the baked-in
toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libfedml_edge.so")
_lib: Optional[ctypes.CDLL] = None
_load_lock = __import__("threading").Lock()

PROGRESS_CB = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_double)


def build(force: bool = False) -> str:
    """Build libfedml_edge.so if missing or stale; returns its path.
    Serialized: concurrent callers must not race `make` on the same objects."""
    with _load_lock:
        return _build_locked(force)


def _build_locked(force: bool) -> str:
    stale = force or not os.path.exists(_LIB_PATH)
    if not stale:
        lib_mtime = os.path.getmtime(_LIB_PATH)
        for name in os.listdir(_NATIVE_DIR):
            if name.endswith((".cpp", ".hpp")) and os.path.getmtime(
                os.path.join(_NATIVE_DIR, name)
            ) > lib_mtime:
                stale = True
                break
    if stale:
        proc = subprocess.run(["make", "-C", _NATIVE_DIR], capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(f"native build failed:\n{proc.stdout}\n{proc.stderr}")
    return _LIB_PATH


_AGENT_PATH = os.path.join(_NATIVE_DIR, "fedml_edge_agent")


def build_agent(force: bool = False) -> str:
    """Build the standalone device-agent binary (``make agent``); returns its
    path.  Same staleness rule and serialization as :func:`build`."""
    with _load_lock:
        stale = force or not os.path.exists(_AGENT_PATH)
        if not stale:
            bin_mtime = os.path.getmtime(_AGENT_PATH)
            for name in os.listdir(_NATIVE_DIR):
                if name.endswith((".cpp", ".hpp")) and os.path.getmtime(
                    os.path.join(_NATIVE_DIR, name)
                ) > bin_mtime:
                    stale = True
                    break
        if stale:
            proc = subprocess.run(["make", "-C", _NATIVE_DIR, "agent"],
                                  capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(f"agent build failed:\n{proc.stdout}\n{proc.stderr}")
        return _AGENT_PATH


def load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _load_lock:  # device threads may race here: build exactly once
        if _lib is not None:
            return _lib
        return _load_locked()


def _load_locked() -> ctypes.CDLL:
    global _lib
    lib = ctypes.CDLL(_build_locked(force=False))  # lock already held

    lib.fedml_last_error.restype = ctypes.c_char_p
    lib.fedml_mnist_idx_to_ftem.argtypes = [ctypes.c_char_p] * 3 + [ctypes.c_int]
    lib.fedml_cifar10_bin_to_ftem.argtypes = [ctypes.c_char_p] * 2 + [ctypes.c_int]

    lib.fedml_trainer_create.restype = ctypes.c_void_p
    lib.fedml_trainer_create.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_double,
        ctypes.c_int, ctypes.c_ulonglong,
    ]
    lib.fedml_trainer_set_callback.argtypes = [ctypes.c_void_p, PROGRESS_CB]
    lib.fedml_trainer_train.argtypes = [ctypes.c_void_p]
    lib.fedml_trainer_epoch_loss.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_double),
    ]
    lib.fedml_trainer_stop.argtypes = [ctypes.c_void_p]
    lib.fedml_trainer_num_samples.restype = ctypes.c_longlong
    lib.fedml_trainer_num_samples.argtypes = [ctypes.c_void_p]
    lib.fedml_trainer_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.fedml_trainer_eval.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
    ]
    lib.fedml_trainer_destroy.argtypes = [ctypes.c_void_p]

    lib.fedml_lsa_chunk.argtypes = [ctypes.c_int] * 3
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    lib.fedml_lsa_mask_encoding.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, i64p,
        ctypes.c_ulonglong, i64p,
    ]
    lib.fedml_lsa_aggregate_decode.argtypes = [
        i64p, i32p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, i64p,
    ]

    lib.fedml_client_create.restype = ctypes.c_void_p
    lib.fedml_client_create.argtypes = lib.fedml_trainer_create.argtypes
    lib.fedml_client_train.argtypes = [ctypes.c_void_p]
    lib.fedml_client_save_model.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.fedml_client_save_masked_model.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_ulonglong, ctypes.c_char_p,
    ]
    lib.fedml_client_mask_dim.restype = ctypes.c_longlong
    lib.fedml_client_mask_dim.argtypes = [ctypes.c_void_p]
    lib.fedml_client_encode_mask.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_ulonglong, i64p,
    ]
    lib.fedml_client_destroy.argtypes = [ctypes.c_void_p]

    _lib = lib
    return lib


def _check(rc: int) -> None:
    if rc != 0:
        raise RuntimeError(load().fedml_last_error().decode())


def mnist_idx_to_ftem(images: str, labels: str, out: str, limit: int = 0) -> str:
    _check(load().fedml_mnist_idx_to_ftem(images.encode(), labels.encode(), out.encode(), limit))
    return out


def cifar10_bin_to_ftem(bin_path: str, out: str, limit: int = 0) -> str:
    """CIFAR-10 binary batch -> FTEM {"x": [n,32,32,3] f32, "y": [n] i32}
    (reference MobileNN/src/MNN/cifar10.cpp role)."""
    _check(load().fedml_cifar10_bin_to_ftem(bin_path.encode(), out.encode(), limit))
    return out


class EdgeTrainer:
    """Native FedMLBaseTrainer handle (train / epoch+loss / stop / save)."""

    def __init__(self, model_path: str, data_path: str, batch_size: int = 32,
                 lr: float = 0.01, epochs: int = 1, seed: int = 0):
        self._lib = load()
        self._h = self._lib.fedml_trainer_create(
            model_path.encode(), data_path.encode(), batch_size, lr, epochs, seed
        )
        if not self._h:
            raise RuntimeError(self._lib.fedml_last_error().decode())
        self._cb_ref = None  # keep the callback alive for the handle's lifetime

    def set_progress_callback(self, fn) -> None:
        self._cb_ref = PROGRESS_CB(fn)
        self._lib.fedml_trainer_set_callback(self._h, self._cb_ref)

    def train(self) -> None:
        _check(self._lib.fedml_trainer_train(self._h))

    def epoch_and_loss(self):
        e, l = ctypes.c_int(), ctypes.c_double()
        self._lib.fedml_trainer_epoch_loss(self._h, ctypes.byref(e), ctypes.byref(l))
        return e.value, l.value

    def stop_training(self) -> None:
        self._lib.fedml_trainer_stop(self._h)

    @property
    def num_samples(self) -> int:
        return int(self._lib.fedml_trainer_num_samples(self._h))

    def save(self, out_path: str) -> str:
        _check(self._lib.fedml_trainer_save(self._h, out_path.encode()))
        return out_path

    def evaluate(self):
        acc, loss = ctypes.c_double(), ctypes.c_double()
        _check(self._lib.fedml_trainer_eval(self._h, ctypes.byref(acc), ctypes.byref(loss)))
        return acc.value, loss.value

    def close(self) -> None:
        if self._h:
            self._lib.fedml_trainer_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class EdgeClientManager:
    """Native FedMLClientManager handle: trainer + LightSecAgg upload pair."""

    def __init__(self, model_path: str, data_path: str, batch_size: int = 32,
                 lr: float = 0.01, epochs: int = 1, seed: int = 0):
        self._lib = load()
        self._h = self._lib.fedml_client_create(
            model_path.encode(), data_path.encode(), batch_size, lr, epochs, seed
        )
        if not self._h:
            raise RuntimeError(self._lib.fedml_last_error().decode())

    def train(self) -> None:
        _check(self._lib.fedml_client_train(self._h))

    def save_model(self, out_path: str) -> str:
        _check(self._lib.fedml_client_save_model(self._h, out_path.encode()))
        return out_path

    @property
    def mask_dim(self) -> int:
        return int(self._lib.fedml_client_mask_dim(self._h))

    def save_masked_model(self, q_bits: int, mask_seed: int, out_path: str) -> str:
        _check(self._lib.fedml_client_save_masked_model(self._h, q_bits, mask_seed, out_path.encode()))
        return out_path

    def encode_mask(self, n: int, t: int, u: int, mask_seed: int) -> np.ndarray:
        chunk = load().fedml_lsa_chunk(self.mask_dim, t, u)
        if chunk <= 0:
            raise ValueError(f"invalid LightSecAgg params: need t < u <= n (t={t}, u={u})")
        out = np.zeros((n, chunk), np.int64)
        _check(self._lib.fedml_client_encode_mask(self._h, n, t, u, mask_seed, out))
        return out

    def close(self) -> None:
        if self._h:
            self._lib.fedml_client_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def lsa_mask_encoding(d: int, n: int, t: int, u: int, mask: np.ndarray, seed: int) -> np.ndarray:
    lib = load()
    chunk = lib.fedml_lsa_chunk(d, t, u)
    if chunk <= 0:
        raise ValueError(f"invalid LightSecAgg params: need d > 0 and t < u (t={t}, u={u})")
    out = np.zeros((n, chunk), np.int64)
    _check(lib.fedml_lsa_mask_encoding(d, n, t, u, np.ascontiguousarray(mask, np.int64), seed, out))
    return out


def lsa_aggregate_decode(rows: np.ndarray, ids, t: int, u: int, d: int) -> np.ndarray:
    """rows: [n_ids, chunk] sorted by id; ids 1-based."""
    lib = load()
    rows = np.ascontiguousarray(rows, np.int64)
    ids_arr = np.ascontiguousarray(ids, np.int32)
    out = np.zeros(d, np.int64)
    _check(lib.fedml_lsa_aggregate_decode(rows, ids_arr, len(ids_arr), t, u, d, rows.shape[1], out))
    return out
