"""One-line quick start (reference
``quick_start/parrot/torch_fedavg_mnist_lr_one_line_example.py``)."""

import fedml_tpu

if __name__ == "__main__":
    fedml_tpu.run_simulation()
