"""Custom data + model quick start (reference
``quick_start/parrot/torch_fedavg_mnist_lr_custum_data_and_model_example.py``):
bring your own arrays and flax module; everything else is the framework."""

import flax.linen as nn
import numpy as np

import fedml_tpu
from fedml_tpu import FedMLRunner
from fedml_tpu.core.data.noniid_partition import homo_partition


class TwoLayerMLP(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x.reshape((x.shape[0], -1))
        h = nn.relu(nn.Dense(64)(h))
        return nn.Dense(self.num_classes)(h)


def load_custom_data(args):
    """Return the reference-shaped 8-tuple from your own arrays."""
    rng = np.random.RandomState(0)
    n, d, classes = 2000, 64, 10
    protos = rng.randn(classes, d).astype(np.float32) * 2
    y = rng.randint(0, classes, n).astype(np.int32)
    x = protos[y] + rng.randn(n, d).astype(np.float32)
    n_tr = int(0.8 * n)
    (x_tr, y_tr), (x_te, y_te) = (x[:n_tr], y[:n_tr]), (x[n_tr:], y[n_tr:])

    clients = int(args.client_num_in_total)
    tr_map = homo_partition(n_tr, clients, seed=0)
    te_map = homo_partition(n - n_tr, clients, seed=1)
    train_local = {i: (x_tr[tr_map[i]], y_tr[tr_map[i]]) for i in range(clients)}
    test_local = {i: (x_te[te_map[i]], y_te[te_map[i]]) for i in range(clients)}
    nums = {i: len(tr_map[i]) for i in range(clients)}
    dataset = [n_tr, n - n_tr, (x_tr, y_tr), (x_te, y_te), nums, train_local,
               test_local, classes]
    return dataset, classes


if __name__ == "__main__":
    args = fedml_tpu.init()
    device = fedml_tpu.device.get_device(args)
    dataset, output_dim = load_custom_data(args)
    model = TwoLayerMLP(num_classes=output_dim)
    FedMLRunner(args, device, dataset, model).run()
