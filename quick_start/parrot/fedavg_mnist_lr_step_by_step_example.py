"""Step-by-step quick start (reference
``quick_start/parrot/torch_fedavg_mnist_lr_step_by_step_example.py``)."""

import fedml_tpu
from fedml_tpu import FedMLRunner

if __name__ == "__main__":
    # init the framework (reads --cf fedml_config.yaml)
    args = fedml_tpu.init()

    # init device (TPU chip / virtual CPU mesh)
    device = fedml_tpu.device.get_device(args)

    # load data (mounted real files, else shape-faithful synthetic)
    dataset, output_dim = fedml_tpu.data.load(args)

    # load model
    model = fedml_tpu.models.create(args, output_dim)

    # start training
    FedMLRunner(args, device, dataset, model).run()
