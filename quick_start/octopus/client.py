"""Cross-silo client one-liner (reference quick_start/octopus)."""

import fedml_tpu

if __name__ == "__main__":
    fedml_tpu.run_cross_silo_client()
