"""Cross-device server one-liner (reference quick_start/beehive)."""

import fedml_tpu

if __name__ == "__main__":
    fedml_tpu.run_mnn_server()
