"""Load ``fedml_tpu/core/analysis`` as a standalone package.

The lint tools must stay stdlib-only and fast: importing the package the
normal way (``import fedml_tpu.core.analysis``) executes
``fedml_tpu/__init__.py`` and drags in jax/numpy for what is a pure-AST
tool.  The analysis package only uses intra-package relative imports, so it
loads cleanly under a private top-level name instead.
"""

from __future__ import annotations

import importlib.util
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYSIS_DIR = os.path.join(REPO_ROOT, "fedml_tpu", "core", "analysis")
_PKG_NAME = "_fedlint_analysis"


def load_analysis():
    """The analysis package, imported once under a private module name."""
    if _PKG_NAME in sys.modules:
        return sys.modules[_PKG_NAME]
    spec = importlib.util.spec_from_file_location(
        _PKG_NAME, os.path.join(_ANALYSIS_DIR, "__init__.py"),
        submodule_search_locations=[_ANALYSIS_DIR])
    module = importlib.util.module_from_spec(spec)
    sys.modules[_PKG_NAME] = module
    try:
        spec.loader.exec_module(module)
    except Exception:
        sys.modules.pop(_PKG_NAME, None)
        raise
    return module
