#!/usr/bin/env python
"""Determinism lint: no global-NumPy-RNG use in library code.

Thin shim over the unified analysis plane (``fedml_tpu/core/analysis``,
see ``tools/fedlint.py`` and ``docs/STATIC_ANALYSIS.md``): the contract,
the ``# lint_rng: allow`` pragma, and this CLI are unchanged, but matching
is now AST-based — the pass resolves import aliases, so renamed modules
can't dodge it, and docstrings/comments can't false-positive.

The reproducibility contract: every schedule-affecting draw comes from a
LOCAL, explicitly-seeded generator (``np.random.RandomState``,
``np.random.default_rng``) — seeding or drawing from the process-global
NumPy RNG makes round schedules depend on import order.

Usage::

    python tools/lint_rng.py            # lint the repo's fedml_tpu/
    python tools/lint_rng.py --root DIR # lint DIR instead (tests use this)
"""

from __future__ import annotations

import argparse
import os
import sys

from _analysis_loader import REPO_ROOT, load_analysis

_analysis = load_analysis()
_ANALYZER = _analysis.passes.RngAnalyzer()
_PRAGMA = "lint_rng: allow"


def lint_file(path: str) -> list:
    src = _analysis.SourceFile(path)
    findings = _analysis.analyze_file(src, [_ANALYZER])
    return [(path, f.lineno, f.source) for f in findings]


def lint_tree(root: str) -> list:
    violations = []
    for path in _analysis.iter_python_files(root):
        violations.extend(lint_file(path))
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.join(REPO_ROOT, "fedml_tpu"),
                    help="directory tree to lint (default: the library)")
    args = ap.parse_args(argv)

    violations = lint_tree(args.root)
    for path, lineno, line in violations:
        rel = os.path.relpath(path, args.root)
        print(f"lint_rng: {rel}:{lineno}: global NumPy RNG use: {line.strip()}",
              flush=True)
    if violations:
        print(f"lint_rng: {len(violations)} violation(s) — use a local "
              "np.random.RandomState / default_rng, or mark an approved "
              f"seam with '# {_PRAGMA}'", flush=True)
        return 1
    print("lint_rng: clean", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
