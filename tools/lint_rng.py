#!/usr/bin/env python
"""Determinism lint: no global-NumPy-RNG use in library code.

The repo's reproducibility contract is that every schedule-affecting draw
comes from a LOCAL, explicitly-seeded generator (``np.random.RandomState``,
``np.random.default_rng``) — seeding or drawing from the process-global
NumPy RNG makes round schedules depend on import order and on every other
consumer of the stream (the bug ``core/sampling.py`` historically had).

This tool greps ``fedml_tpu/`` for global-RNG calls (``np.random.seed``,
bare ``np.random.choice`` / ``.rand`` / ``.shuffle`` / ...), with comments
stripped so prose mentions don't false-positive and module aliases
(``_np``, ``numpy``) covered.  The one approved seam — run-entry seeding in
``fedml_tpu/__init__.py`` — carries a ``# lint_rng: allow`` pragma on the
flagged line.  Wired into tier-1 via ``tests/test_lint_rng.py`` so the
contract is machine-enforced, not convention.

Usage::

    python tools/lint_rng.py            # lint the repo's fedml_tpu/
    python tools/lint_rng.py --root DIR # lint DIR instead (tests use this)
"""

from __future__ import annotations

import argparse
import io
import os
import re
import sys
import tokenize

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# global-RNG entry points: seeding plus every draw method that reads the
# global stream.  RandomState(...) / default_rng(...) / Generator are LOCAL
# constructors and deliberately not listed.
_DRAWS = (
    "seed|choice|rand|randn|randint|random_integers|random_sample|random|"
    "ranf|sample|permutation|shuffle|bytes|normal|standard_normal|uniform|"
    "binomial|poisson|exponential|laplace|gumbel|beta|gamma|dirichlet|"
    "multinomial|multivariate_normal|get_state|set_state"
)
_PATTERN = re.compile(
    r"(?<![\w.])(?:np|_np|numpy)\.random\.(?:%s)\s*\(" % _DRAWS
)
_PRAGMA = "lint_rng: allow"


def _code_lines(source: str) -> list:
    """The file's lines with comments and string literals (docstrings,
    prose mentions, log formats) blanked via ``tokenize`` — only actual
    code can trip the pattern."""
    lines = source.splitlines()
    kept = list(lines)
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return kept  # unparseable: lint the raw lines rather than skip
    for tok in tokens:
        if tok.type not in (tokenize.COMMENT, tokenize.STRING):
            continue
        (srow, scol), (erow, ecol) = tok.start, tok.end
        for row in range(srow, erow + 1):
            line = kept[row - 1]
            lo = scol if row == srow else 0
            hi = ecol if row == erow else len(line)
            kept[row - 1] = line[:lo] + " " * (hi - lo) + line[hi:]
    return kept


def lint_file(path: str) -> list:
    violations = []
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        source = f.read()
    raw_lines = source.splitlines()
    for lineno, code in enumerate(_code_lines(source), 1):
        raw = raw_lines[lineno - 1]
        if _PRAGMA in raw:
            continue
        if _PATTERN.search(code):
            violations.append((path, lineno, raw.rstrip()))
    return violations


def lint_tree(root: str) -> list:
    violations = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                violations.extend(lint_file(os.path.join(dirpath, name)))
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.join(REPO_ROOT, "fedml_tpu"),
                    help="directory tree to lint (default: the library)")
    args = ap.parse_args(argv)

    violations = lint_tree(args.root)
    for path, lineno, line in violations:
        rel = os.path.relpath(path, args.root)
        print(f"lint_rng: {rel}:{lineno}: global NumPy RNG use: {line.strip()}",
              flush=True)
    if violations:
        print(f"lint_rng: {len(violations)} violation(s) — use a local "
              "np.random.RandomState / default_rng, or mark an approved "
              f"seam with '# {_PRAGMA}'", flush=True)
        return 1
    print("lint_rng: clean", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
