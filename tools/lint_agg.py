#!/usr/bin/env python
"""Aggregation lint: no new host-side tree_map-loop aggregation.

Thin shim over the unified analysis plane (``fedml_tpu/core/analysis``,
see ``tools/fedlint.py`` and ``docs/STATIC_ANALYSIS.md``): the contract,
the ``# lint_agg: allow`` pragma, the ``core/aggregate.py`` exemption, and
this CLI are unchanged, but matching is now AST-based (a star-lambda as
``tree_map``'s first argument, wherever tree_map is imported from).

The contract: with ``core/aggregate.py`` (host) and
``parallel/agg_plane.py`` (compiled GSPMD) in place, there is exactly one
place client-update math may live — a hand-rolled
``tree_map(lambda *xs: ...)`` fold misses structure validation, the
``agg_plane`` knob, and the ``agg.*`` metrics.

Usage::

    python tools/lint_agg.py            # lint the repo's fedml_tpu/
    python tools/lint_agg.py --root DIR # lint DIR instead (tests use this)
"""

from __future__ import annotations

import argparse
import os
import sys

from _analysis_loader import REPO_ROOT, load_analysis

_analysis = load_analysis()
_ANALYZER = _analysis.passes.AggAnalyzer()
_PRAGMA = "lint_agg: allow"

_KINDS = {"agg-host-treemap": "host tree_map aggregation loop"}


def lint_file(path: str) -> list:
    src = _analysis.SourceFile(path)
    findings = _analysis.analyze_file(src, [_ANALYZER])
    return [(path, f.lineno, _KINDS[f.rule], f.source) for f in findings]


def lint_tree(root: str) -> list:
    violations = []
    for path in _analysis.iter_python_files(root):
        violations.extend(lint_file(path))
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.join(REPO_ROOT, "fedml_tpu"),
                    help="directory tree to lint (default: the library)")
    args = ap.parse_args(argv)

    violations = lint_tree(args.root)
    for path, lineno, kind, line in violations:
        rel = os.path.relpath(path, args.root)
        print(f"lint_agg: {rel}:{lineno}: {kind}: {line.strip()}", flush=True)
    if violations:
        print(f"lint_agg: {len(violations)} violation(s) — use "
              "core/aggregate (tree_stack/weighted_mean) or the compiled "
              "agg plane (parallel/agg_plane), or mark an approved seam "
              f"with '# {_PRAGMA}'", flush=True)
        return 1
    print("lint_agg: clean", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
