#!/usr/bin/env python
"""Aggregation lint: no new host-side tree_map-loop aggregation.

With ``core/aggregate.py`` (the one host implementation) and
``parallel/agg_plane.py`` (the compiled GSPMD reduction) in place, there is
exactly one place client-update math may live.  A module that hand-rolls
``tree_map(lambda *xs: ...)`` over per-client pytrees reinvents the
stacking/reduction loop outside both surfaces: it misses the structure
validation (``flatten_checked``'s clear client/leaf errors), never routes
through the ``agg_plane`` knob, and emits no ``agg.*`` metrics — precisely
the drift that made the reference repo grow four per-engine aggregators.

This tool greps ``fedml_tpu/`` for star-lambda ``tree_map`` calls (the
canonical multi-tree fold/stack construction) with comments/strings
stripped.  ``core/aggregate.py`` — the layer that IS the host surface — is
exempt; anything else needing an exception carries a ``# lint_agg: allow``
pragma on the flagged line.  Wired into tier-1 via
``tests/test_lint_agg.py``.

Usage::

    python tools/lint_agg.py            # lint the repo's fedml_tpu/
    python tools/lint_agg.py --root DIR # lint DIR instead (tests use this)
"""

from __future__ import annotations

import argparse
import io
import os
import re
import sys
import tokenize

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# multi-tree fold: tree_map(lambda *xs, ...) — the construction every
# hand-rolled host aggregation loop starts from (stack, sum, elementwise
# combine over a client list).  Single-tree maps (lambda x: ...) are fine.
_TREEMAP_STAR = re.compile(r"tree_map\s*\(\s*lambda\s*\*")
_PRAGMA = "lint_agg: allow"

# the one module that implements the host aggregation surface
_EXEMPT_FILES = (os.path.join("core", "aggregate.py"),)


def _exempt(path: str) -> bool:
    norm = os.path.normpath(os.path.abspath(path))
    return any(norm.endswith(os.sep + part) for part in _EXEMPT_FILES)


def _code_lines(source: str) -> list:
    """Lines with comments and string literals blanked via ``tokenize`` —
    only actual code can trip the pattern (same approach as lint_obs)."""
    lines = source.splitlines()
    kept = list(lines)
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return kept  # unparseable: lint the raw lines rather than skip
    for tok in tokens:
        if tok.type not in (tokenize.COMMENT, tokenize.STRING):
            continue
        (srow, scol), (erow, ecol) = tok.start, tok.end
        for row in range(srow, erow + 1):
            line = kept[row - 1]
            lo = scol if row == srow else 0
            hi = ecol if row == erow else len(line)
            kept[row - 1] = line[:lo] + " " * (hi - lo) + line[hi:]
    return kept


def lint_file(path: str) -> list:
    if _exempt(path):
        return []
    violations = []
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        source = f.read()
    raw_lines = source.splitlines()
    for lineno, code in enumerate(_code_lines(source), 1):
        raw = raw_lines[lineno - 1]
        if _PRAGMA in raw:
            continue
        if _TREEMAP_STAR.search(code):
            violations.append(
                (path, lineno, "host tree_map aggregation loop", raw.rstrip()))
    return violations


def lint_tree(root: str) -> list:
    violations = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                violations.extend(lint_file(os.path.join(dirpath, name)))
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.join(REPO_ROOT, "fedml_tpu"),
                    help="directory tree to lint (default: the library)")
    args = ap.parse_args(argv)

    violations = lint_tree(args.root)
    for path, lineno, kind, line in violations:
        rel = os.path.relpath(path, args.root)
        print(f"lint_agg: {rel}:{lineno}: {kind}: {line.strip()}", flush=True)
    if violations:
        print(f"lint_agg: {len(violations)} violation(s) — use "
              "core/aggregate (tree_stack/weighted_mean) or the compiled "
              "agg plane (parallel/agg_plane), or mark an approved seam "
              f"with '# {_PRAGMA}'", flush=True)
        return 1
    print("lint_agg: clean", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
