"""Generate the committed golden byte fixtures for tests/test_loaders_golden.py.

Each fixture is a REAL on-disk format instance (idx-ubyte, CIFAR pickle,
LEAF json, ImageFolder PNGs, landmarks CSVs, NUS-WIDE txt/dat, NIfTI-1,
edge-case pkl) written with stdlib/PIL primitives — independent of the
parsers in fedml_tpu/data/loaders.py — holding small DETERMINISTIC arrays
(seeded numpy).  Run once; the bytes are committed under
tests/fixtures/golden so parser correctness is severed from any dataset
mount.  Reference formats: data/MNIST/data_loader.py:16 (LEAF json),
data/cifar10 pickles, data/Landmarks/data_loader.py:123-150,
data/NUS_WIDE/nus_wide_dataset.py:8-60, data/FeTS2021, and
data/edge_case_examples/data_loader.py.
"""

from __future__ import annotations

import gzip
import json
import os
import pickle
import struct

import numpy as np

ROOT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "tests", "fixtures", "golden")


def write_idx(path: str, arr: np.ndarray, gz: bool = False) -> None:
    magic = (0x08 << 8) | arr.ndim  # 0x08 = ubyte
    body = struct.pack(">I", magic)
    for d in arr.shape:
        body += struct.pack(">I", d)
    body += arr.astype(np.uint8).tobytes()
    op = gzip.open if gz else open
    with op(path + (".gz" if gz else ""), "wb") as f:
        f.write(body)


def main() -> None:
    os.makedirs(ROOT, exist_ok=True)

    # -- MNIST idx (train plain, test gzipped: both openers exercised) ------
    d = os.path.join(ROOT, "mnist")
    os.makedirs(d, exist_ok=True)
    r = np.random.RandomState(10)
    xt = r.randint(0, 256, (10, 28, 28)).astype(np.uint8)
    yt = r.randint(0, 10, (10,)).astype(np.uint8)
    xe = r.randint(0, 256, (4, 28, 28)).astype(np.uint8)
    ye = r.randint(0, 10, (4,)).astype(np.uint8)
    write_idx(os.path.join(d, "train-images-idx3-ubyte"), xt)
    write_idx(os.path.join(d, "train-labels-idx1-ubyte"), yt)
    write_idx(os.path.join(d, "t10k-images-idx3-ubyte"), xe, gz=True)
    write_idx(os.path.join(d, "t10k-labels-idx1-ubyte"), ye, gz=True)

    # -- CIFAR-10 pickle batches (2 train batches x 3 records + 2 test) -----
    d = os.path.join(ROOT, "cifar10")
    os.makedirs(d, exist_ok=True)
    r = np.random.RandomState(11)
    for name, n in (("data_batch_1", 3), ("data_batch_2", 3), ("test_batch", 2)):
        batch = {b"data": r.randint(0, 256, (n, 3072)).astype(np.uint8),
                 b"labels": r.randint(0, 10, (n,)).tolist()}
        with open(os.path.join(d, name), "wb") as f:
            pickle.dump(batch, f)

    # -- LEAF json (femnist layout: 2 users train, 1 user test) -------------
    d = os.path.join(ROOT, "femnist")
    r = np.random.RandomState(12)
    for split, users in (("train", ["f_00", "f_01"]), ("test", ["f_00"])):
        os.makedirs(os.path.join(d, split), exist_ok=True)
        blob = {"users": users, "num_samples": [], "user_data": {}}
        for u in users:
            n = 3 if split == "train" else 2
            blob["num_samples"].append(n)
            blob["user_data"][u] = {
                "x": r.rand(n, 784).round(6).tolist(),
                "y": r.randint(0, 62, (n,)).tolist(),
            }
        with open(os.path.join(d, split, "all_data_0.json"), "w") as f:
            json.dump(blob, f)

    # -- CINIC-10 ImageFolder (2 classes x 2 PNGs per split) ----------------
    from PIL import Image

    d = os.path.join(ROOT, "cinic10")
    r = np.random.RandomState(13)
    for split in ("train", "valid"):
        for cname in ("airplane", "automobile"):
            cdir = os.path.join(d, split, cname)
            os.makedirs(cdir, exist_ok=True)
            for i in range(2):
                arr = r.randint(0, 256, (32, 32, 3)).astype(np.uint8)
                Image.fromarray(arr).save(os.path.join(cdir, f"img{i}.png"))

    # -- UCI-style labeled CSV ----------------------------------------------
    d = os.path.join(ROOT, "uci")
    os.makedirs(d, exist_ok=True)
    r = np.random.RandomState(14)
    for name, n in (("train.csv", 8), ("test.csv", 3)):
        with open(os.path.join(d, name), "w") as f:
            f.write("f0,f1,f2,label\n")
            for _ in range(n):
                row = r.rand(3).round(4)
                f.write(",".join(map(str, row)) + f",{r.randint(0, 2)}\n")

    # -- Google Landmarks CSVs + jpgs (smooth gradients: JPEG-friendly so
    # the golden pixel check has a tight bound — noise is JPEG's worst case)
    d = os.path.join(ROOT, "gld23k")
    os.makedirs(os.path.join(d, "images"), exist_ok=True)
    rows_tr, rows_te = [], []
    for i in range(4):
        g = (np.add.outer(np.arange(32) * 4, np.arange(32) * 3) + i * 20) % 256
        arr = np.stack([g, (g + 40) % 256, (g + 90) % 256], -1).astype(np.uint8)
        Image.fromarray(arr).save(os.path.join(d, "images", f"im{i}.jpg"),
                                  quality=95)
        (rows_tr if i < 3 else rows_te).append((f"u{i % 2}", f"im{i}", i % 3))
    with open(os.path.join(d, "mini_gld_train_split.csv"), "w") as f:
        f.write("user_id,image_id,class\n")
        for u, im, c in rows_tr:
            f.write(f"{u},{im},{c}\n")
    with open(os.path.join(d, "mini_gld_test.csv"), "w") as f:
        f.write("user_id,image_id,class\n")
        for u, im, c in rows_te:
            f.write(f"{u},{im},{c}\n")

    # -- NUS-WIDE labels + low-level features -------------------------------
    d = os.path.join(ROOT, "nuswide")
    lab = os.path.join(d, "Groundtruth", "TrainTestLabels")
    feat = os.path.join(d, "Low_Level_Features")
    os.makedirs(lab, exist_ok=True)
    os.makedirs(feat, exist_ok=True)
    r = np.random.RandomState(16)
    for nm in ("sky", "water"):
        np.savetxt(os.path.join(lab, f"Labels_{nm}_Train.txt"),
                   r.randint(0, 2, (6,)), fmt="%d")
        np.savetxt(os.path.join(lab, f"Labels_{nm}_Test.txt"),
                   r.randint(0, 2, (3,)), fmt="%d")
    np.savetxt(os.path.join(feat, "Normalized_CH_Train_x.dat"),
               r.rand(6, 4).round(6), fmt="%.6f")
    np.savetxt(os.path.join(feat, "Normalized_CH_Test_x.dat"),
               r.rand(3, 4).round(6), fmt="%.6f")

    # -- FeTS 2021 NIfTI subjects -------------------------------------------
    d = os.path.join(ROOT, "fets2021")
    r = np.random.RandomState(17)
    for s in ("FeTS21_001", "FeTS21_002"):
        sdir = os.path.join(d, s)
        os.makedirs(sdir, exist_ok=True)
        for mod, dt, code in (("_t1", np.int16, 4), ("_t1ce", np.int16, 4),
                              ("_t2", np.int16, 4), ("_flair", np.int16, 4),
                              ("_seg", np.uint8, 2)):
            shape = (8, 8, 4)
            if mod == "_seg":
                vol = r.choice([0, 1, 2, 4], size=shape).astype(dt)
            else:
                vol = r.randint(0, 1000, shape).astype(dt)
            hdr = bytearray(352)
            struct.pack_into("<i", hdr, 0, 348)               # sizeof_hdr
            struct.pack_into("<8h", hdr, 40, 3, *shape, 1, 1, 1, 1)  # dim
            struct.pack_into("<h", hdr, 70, code)             # datatype
            struct.pack_into("<f", hdr, 108, 352.0)           # vox_offset
            body = bytes(hdr) + vol.tobytes(order="F")
            with gzip.open(os.path.join(sdir, f"{s}{mod}.nii.gz"), "wb") as f:
                f.write(body)

    # -- edge-case example pool (ARDIS-shaped pkl) --------------------------
    d = os.path.join(ROOT, "edge_case")
    os.makedirs(d, exist_ok=True)
    r = np.random.RandomState(18)
    with open(os.path.join(d, "ardis_7.pkl"), "wb") as f:
        pickle.dump(r.randint(0, 256, (5, 28, 28, 1)).astype(np.uint8), f)
    with open(os.path.join(d, "southwest.pkl"), "wb") as f:
        pickle.dump({"data": r.rand(4, 32, 32, 3).astype(np.float32)}, f)

    print(f"fixtures written under {ROOT}")


if __name__ == "__main__":
    main()
