#!/usr/bin/env python
"""Anti-flake gate for the chaos suite.

Runs the fast chaos matrix plus the server-kill/restart tests
(``tests/test_fault_tolerance.py``), the trace-integrity chaos tests
(``tests/test_obs.py`` — every completed round must reconstruct as one
closed span tree even under drop/dup/delay/server_kill) AND the
compiled-aggregation chaos tests (``tests/test_agg_plane.py`` —
retransmit/dup chaos with ``agg_plane=compiled`` must converge
bit-identical to the fault-free host run) AND the buffered-async chaos
tests (``tests/test_async_fl.py`` — drop/dup/delay plus ``server_kill``
mid-buffer must converge deterministically with exactly-once delta
accounting) AND the staged-ingest chaos tests (``tests/test_ingest.py`` —
the full chaos plan and the server kill with ``ingest_pipeline=True`` and
group commit must converge bit-identical to the host-path model, with
every traced round still one closed span tree) AND the telemetry-plane
chaos tests (``tests/test_telemetry.py`` — drop/dup/delay/server_kill
with ``obs_telemetry=1`` must converge bit-identical to the
telemetry-off run, with the remote spans grafted and the seq gap/dup
accounting exact) AND the sharded-server-state chaos leg
(``tests/test_fault_tolerance.py -k sharded_state`` — a server kill
AFTER the first FedOpt round with ``server_state=sharded`` must restore
the model-sharded optimizer state bit-identically) AND the elastic leg
(``tests/test_fault_tolerance.py -k elastic`` plus the
``TestElasticRemesh`` suite in ``tests/test_agg_plane.py`` — a
``mesh_shrink`` topology fault mid-round, and a server kill restarted
with the model axis shrunk 4→2, must both re-shard through the portable
state codec and converge bit-identical to the fixed-mesh run with
exactly-once accounting) AND the defense leg
(``tests/test_security_plane.py -k secagg_dropout`` — a SecAgg round
with a client dropped mid-upload plus a server kill mid-round must
unmask BIT-IDENTICALLY to the uninterrupted round, with exactly-once
duplicate accounting, and abort below the reconstruction threshold)
AND the hierarchy leg (``tests/test_hierarchy.py -k hierarchy`` — 2- and
3-level edge-aggregator trees under the full drop/dup/delay/reset chaos
plan, plus an edge kill mid-round, must close the round BIT-IDENTICALLY
to the flat topology with exactly-once forward accounting at the root)
AND the chunked-upload leg (``tests/test_chunking.py -k chunk`` — the
full drop/dup/delay/reset/torn-frame/``mid_message_disconnect`` plan
over the ``comm_chunk`` vocabulary plus a server kill BETWEEN chunks of
live streams must converge BIT-IDENTICALLY to the whole-message run,
resuming interrupted uploads from the last acked chunk with exactly-once
replay accounting) AND the health leg (``tests/test_health.py -k health``
— an injected ingest-queue stall, a killed chunk-pump thread, and a
silent edge aggregator must each fire the RIGHT detector at its exact
deadline on the injected clock with EXACTLY ONE flight dump per
incident, and a fault-free run with ``obs_health=1`` must converge
bit-identical to the plane-off run with every round's span tree closed)
N consecutive times in
fresh interpreter processes and fails on the FIRST non-green run.
A fault-injection suite that only mostly passes is worse than none —
operators stop believing red — so new fault kinds / backends must hold up
under this before they land unmarked.

Before the pytest loop it runs the **perf gate** over the checked-in
bench trajectory, advisory-then-strict: first ``tools/perf_gate.py
--advisory`` on the FULL trajectory (the historical BENCH_r03-r05 dark
window prints loudly every time, so it can't fade into folklore), then
strict with ``--known-dark 3,4,5`` grandfathering exactly that window —
any NEW dark round or regression fails the chaos gate before a single
pytest process spawns.  ``--skip-perf-gate`` opts out (e.g. a checkout
without bench artifacts).

It also runs the **fedlint leg** (``tools/fedlint.py``) the same way:
advisory first (the full report prints, including pragma/baseline
accounting, so suppressions stay visible), then strict — any finding
from the race / ack-ordering / purity analyzers or the four ported lint
contracts fails the gate before a single pytest process spawns.
``--skip-fedlint`` opts out.

Usage::

    python tools/chaos_check.py --runs 5
    python tools/chaos_check.py --runs 3 -k "chaos_matrix"
    python tools/chaos_check.py --runs 3 -k "server_kill"
    python tools/chaos_check.py --runs 3 -k "trace_integrity"
    python tools/chaos_check.py --runs 3 -k "agg_plane"
    python tools/chaos_check.py --runs 3 -k "async_fl"
    python tools/chaos_check.py --runs 3 -k "ingest"
    python tools/chaos_check.py --runs 3 -k "telemetry"
    python tools/chaos_check.py --runs 3 -k "sharded_state"
    python tools/chaos_check.py --runs 3 -k "elastic or mesh_shrink"
    python tools/chaos_check.py --runs 3 -k "secagg_dropout"
    python tools/chaos_check.py --runs 3 -k "hierarchy"
    python tools/chaos_check.py --runs 3 -k "chunk"
    python tools/chaos_check.py --runs 3 -k "health"
    python tools/chaos_check.py --runs 3 --skip-perf-gate
    python tools/chaos_check.py --runs 3 --skip-fedlint
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the historical dark window (BENCH_r03-r05 probe timeouts) — grandfathered
# in the strict leg; anything dark beyond these rounds fails the gate
KNOWN_DARK = "3,4,5"


def run_perf_gate(timeout: float) -> int:
    """Advisory pass over the full trajectory, then strict with the
    historical dark rounds grandfathered.  Returns the strict leg's rc."""
    import glob
    if not glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json")):
        print("chaos_check: perf gate skipped — no BENCH_r*.json "
              "trajectory in this checkout", flush=True)
        return 0
    gate = [sys.executable, os.path.join(REPO_ROOT, "tools", "perf_gate.py")]
    try:
        print("chaos_check: perf gate (advisory, full trajectory)",
              flush=True)
        subprocess.run(gate + ["--advisory"], cwd=REPO_ROOT, timeout=timeout)
        print(f"chaos_check: perf gate (strict, --known-dark {KNOWN_DARK})",
              flush=True)
        strict = subprocess.run(gate + ["--known-dark", KNOWN_DARK],
                                cwd=REPO_ROOT, timeout=timeout)
    except subprocess.TimeoutExpired:
        print("chaos_check: perf gate TIMED OUT", flush=True)
        return 2
    return strict.returncode


def run_fedlint(timeout: float) -> int:
    """Advisory pass (full report, suppressions visible), then strict.
    Returns the strict leg's rc — mirrors run_perf_gate."""
    fedlint = [sys.executable, os.path.join(REPO_ROOT, "tools", "fedlint.py")]
    try:
        print("chaos_check: fedlint (advisory, full report)", flush=True)
        subprocess.run(fedlint + ["--advisory"], cwd=REPO_ROOT,
                       timeout=timeout)
        print("chaos_check: fedlint (strict)", flush=True)
        strict = subprocess.run(fedlint, cwd=REPO_ROOT, timeout=timeout)
    except subprocess.TimeoutExpired:
        print("chaos_check: fedlint TIMED OUT", flush=True)
        return 2
    return strict.returncode


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--runs", "-n", type=int, default=3,
                    help="consecutive green runs required (default 3)")
    ap.add_argument(
        "-k", dest="keyword",
        default="chaos or server_kill or trace_integrity or agg_plane "
                "or async_fl or ingest or telemetry or sharded_state "
                "or elastic or mesh_shrink or secagg_dropout or hierarchy "
                "or chunk or health",
        help='pytest -k selector (default: "chaos or server_kill or '
             'trace_integrity or agg_plane or async_fl or ingest or '
             'telemetry or sharded_state or elastic or mesh_shrink or '
             'secagg_dropout or hierarchy or chunk or health")')
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-run wall-clock bound in seconds")
    ap.add_argument("--skip-perf-gate", action="store_true",
                    help="skip the bench-trajectory perf gate leg")
    ap.add_argument("--skip-fedlint", action="store_true",
                    help="skip the static-analysis (fedlint) leg")
    args = ap.parse_args(argv)

    if not args.skip_perf_gate:
        gate_rc = run_perf_gate(args.timeout)
        if gate_rc != 0:
            print(f"chaos_check: PERF GATE FAILED (rc={gate_rc}) — a new "
                  "dark round or regression in the bench trajectory",
                  flush=True)
            return 1

    if not args.skip_fedlint:
        lint_rc = run_fedlint(args.timeout)
        if lint_rc != 0:
            print(f"chaos_check: FEDLINT FAILED (rc={lint_rc}) — fix the "
                  "finding or carry a justified pragma "
                  "(docs/STATIC_ANALYSIS.md)", flush=True)
            return 1

    env = dict(os.environ, JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    cmd = [sys.executable, "-m", "pytest", "tests/test_fault_tolerance.py",
           "tests/test_obs.py", "tests/test_agg_plane.py",
           "tests/test_async_fl.py", "tests/test_ingest.py",
           "tests/test_telemetry.py", "tests/test_security_plane.py",
           "tests/test_hierarchy.py", "tests/test_chunking.py",
           "tests/test_health.py",
           "-q", "-k", args.keyword, "-p", "no:cacheprovider"]
    for i in range(1, args.runs + 1):
        t0 = time.time()
        try:
            proc = subprocess.run(cmd, cwd=REPO_ROOT, env=env,
                                  timeout=args.timeout)
        except subprocess.TimeoutExpired:
            print(f"chaos_check: run {i}/{args.runs} TIMED OUT "
                  f"after {args.timeout:.0f}s", flush=True)
            return 2
        if proc.returncode != 0:
            print(f"chaos_check: FLAKE — run {i}/{args.runs} exited "
                  f"{proc.returncode} after {time.time() - t0:.1f}s", flush=True)
            return 1
        print(f"chaos_check: run {i}/{args.runs} green "
              f"({time.time() - t0:.1f}s)", flush=True)
    print(f"chaos_check: {args.runs} consecutive green runs")
    return 0


if __name__ == "__main__":
    sys.exit(main())
