#!/usr/bin/env python
"""Perf-regression gate over the checked-in bench trajectory.

BENCH_r03-r05 went dark (probe timeouts, ``parsed: null``) and nobody
noticed until a human read the JSON tails — three rounds of perf work
shipped unmeasured.  This gate turns that prose complaint into a failing
check.  It parses every ``BENCH_rNN.json`` driver record (``{"n", "cmd",
"rc", "tail"}`` with the bench's single metric JSON line embedded in
``tail``) plus ``BASELINE.json`` and fails on:

* **dark rounds** — nonzero rc or no parseable metric line.  Historical
  dark rounds are grandfathered explicitly via ``--known-dark 3,4,5``;
  a NEW dark round always fails.
* **schema violations** — bench.py stamps ``bench_schema`` / ``mode`` /
  ``degraded_reason`` / ``git_rev`` (schema 2); a schema-stamped record
  missing its required keys fails, as does a legacy record without
  ``metric``/numeric ``value``.
* **regressions** — for each relative key (``vs_baseline``,
  ``agg_speedup``, ``uploads_per_s``, ``async_flushes_per_s``,
  ``async_deltas_per_s``, ``telemetry_rounds_per_s``,
  ``fanin_uploads_per_s_flat`` / ``fanin_uploads_per_s_edge``) the LATEST value
  must stay within ``--tolerance`` of the median of the prior rounds
  that report the key (keys absent in older-schema rounds are simply
  not banded yet).  ``obs_overhead_frac`` and ``telemetry_overhead_frac``
  are lower-better and capped absolutely by ``--obs-overhead-max``.
  ``resize_downtime_s`` / ``remesh_recompile_s`` (elastic resize) are
  lower-better and banded RELATIVELY: the latest value must stay under
  ``(1 + tolerance) x`` the prior-round median.
  ``BASELINE.json``'s ``published`` map, when populated, bands the same
  way against the published numbers.

``--advisory`` prints every violation but exits 0 — the chaos gate runs
advisory over the full trajectory (the known-dark window shows up loudly)
and then strict with the historical dark rounds grandfathered.

Usage::

    python tools/perf_gate.py                       # BENCH_r*.json + BASELINE.json
    python tools/perf_gate.py BENCH_r01.json BENCH_r02.json
    python tools/perf_gate.py --known-dark 3,4,5
    python tools/perf_gate.py --advisory --format json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# must match bench.BENCH_SCHEMA (pinned by tests/test_perf_gate.py so the
# two can't drift); the gate itself stays importable without jax
BENCH_SCHEMA_CURRENT = 2

# higher-is-better relative keys banded against the prior-round median
RELATIVE_KEYS = ("vs_baseline", "agg_speedup", "round_update_speedup",
                 "broadcast_shrink", "uploads_per_s",
                 "uploads_per_s_host", "uploads_per_s_pipelined",
                 "async_flushes_per_s", "async_deltas_per_s",
                 "telemetry_rounds_per_s", "defended_round_speedup",
                 "fanin_uploads_per_s_flat", "fanin_uploads_per_s_edge",
                 "chunked_goodput_frac_lossy",
                 "rounds_per_s", "clients_simulated_per_s")
# lower-is-better: absolute cap (observability must stay cheap — spans,
# registry, exposition, and now the telemetry plane all share the budget)
OVERHEAD_KEYS = ("obs_overhead_frac", "telemetry_overhead_frac",
                 "dp_overhead_frac", "chunk_overhead_frac",
                 "health_overhead_frac")
# per-key overrides of --obs-overhead-max: the DP stage pays real compute
# (per-client clip + counter-based noise over the whole update matrix), so
# against the small synthetic bench round its frac is a few x, not a few %.
# The wide cap is a runaway backstop (a recompile-per-round or accidentally
# quadratic stage); creep is caught by the trajectory band below.
# Chunk framing is pure wire bookkeeping — at the bench's representative
# 64 KiB chunks the headers must stay under 5% of the payload or the
# resumability win is being eaten by the framing itself.
OVERHEAD_BUDGETS = {"dp_overhead_frac": 25.0, "chunk_overhead_frac": 0.05}
# lower-is-better relative keys banded against the prior-round median
# (elastic resize: downtime of an in-place remesh and its recompile slice
# must not creep — a topology change should stay a sub-round blip; same
# contract for the SecAgg mask/unmask cycle and the DP stage's relative
# cost)
LATENCY_KEYS = ("resize_downtime_s", "remesh_recompile_s",
                "secagg_mask_s", "dp_overhead_frac")

_MODES = ("full", "degraded", "failed")


def extract_metric_line(tail: str) -> Optional[Dict[str, Any]]:
    """The LAST line of ``tail`` that parses to a dict with a ``metric``
    key — the bench contract is exactly one such line on stdout."""
    found = None
    for line in str(tail or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            found = obj
    return found


def load_round(path: str, position: int) -> Dict[str, Any]:
    """One normalized trajectory entry: ``{"path", "round", "rc",
    "parsed"}``.  Accepts the driver wrapper format or a bare metric
    record (synthetic gate inputs)."""
    with open(path, "r", encoding="utf-8") as f:
        obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if "tail" in obj or "rc" in obj:
        return {
            "path": path,
            "round": int(obj.get("n", position)),
            "rc": int(obj.get("rc", 0)),
            "parsed": extract_metric_line(obj.get("tail", "")),
        }
    # bare metric record
    return {"path": path, "round": int(obj.get("round", position)),
            "rc": 0, "parsed": obj if "metric" in obj else None}


def validate_record(entry: Dict[str, Any]) -> List[str]:
    """Schema-contract violations for one light round's parsed record."""
    rec = entry["parsed"]
    out: List[str] = []
    where = f"round {entry['round']} ({os.path.basename(entry['path'])})"
    schema = rec.get("bench_schema")
    if schema is None:
        # legacy (pre-schema) record: minimum viable contract
        if not isinstance(rec.get("value"), (int, float)):
            out.append(f"{where}: legacy record has non-numeric value "
                       f"{rec.get('value')!r}")
        return out
    if not isinstance(schema, int) or not 1 <= schema <= BENCH_SCHEMA_CURRENT:
        out.append(f"{where}: unknown bench_schema {schema!r} "
                   f"(gate understands <= {BENCH_SCHEMA_CURRENT})")
        return out
    mode = rec.get("mode")
    if mode not in _MODES:
        out.append(f"{where}: mode must be one of {_MODES}, got {mode!r}")
    if mode in ("degraded", "failed") and not rec.get("degraded_reason"):
        out.append(f"{where}: {mode} record missing degraded_reason")
    if mode == "full" and rec.get("degraded_reason") not in (None, ""):
        out.append(f"{where}: full record carries degraded_reason "
                   f"{rec.get('degraded_reason')!r}")
    if "git_rev" not in rec:
        out.append(f"{where}: schema-{schema} record missing git_rev")
    if mode != "failed" and not isinstance(rec.get("value"), (int, float)):
        out.append(f"{where}: non-numeric value {rec.get('value')!r}")
    return out


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def check_trajectory(entries: List[Dict[str, Any]], tolerance: float,
                     obs_overhead_max: float,
                     known_dark: Optional[set] = None,
                     baseline: Optional[Dict[str, Any]] = None,
                     ) -> List[str]:
    """Every violation in the trajectory (empty = gate passes)."""
    known_dark = known_dark or set()
    violations: List[str] = []
    light: List[Dict[str, Any]] = []
    for entry in entries:
        dark = entry["rc"] != 0 or entry["parsed"] is None
        if dark:
            if entry["round"] in known_dark:
                continue
            why = (f"rc={entry['rc']}" if entry["rc"] != 0
                   else "no parseable metric line in tail")
            violations.append(
                f"round {entry['round']} "
                f"({os.path.basename(entry['path'])}): DARK ROUND — {why}")
            continue
        violations.extend(validate_record(entry))
        light.append(entry)

    # tolerance bands: latest vs median of the prior rounds carrying the key
    for key in RELATIVE_KEYS:
        series = [(e["round"], float(e["parsed"][key])) for e in light
                  if isinstance(e["parsed"].get(key), (int, float))]
        if len(series) < 2:
            continue
        *prior, (rnd, latest) = series
        med = _median([v for _, v in prior])
        floor = (1.0 - tolerance) * med
        if latest < floor:
            violations.append(
                f"round {rnd}: REGRESSION — {key}={latest:g} fell below "
                f"{floor:g} ({(1.0 - tolerance):.0%} of prior median "
                f"{med:g})")
    # lower-is-better bands: latest must stay under the mirrored ceiling
    for key in LATENCY_KEYS:
        series = [(e["round"], float(e["parsed"][key])) for e in light
                  if isinstance(e["parsed"].get(key), (int, float))]
        if len(series) < 2:
            continue
        *prior, (rnd, latest) = series
        med = _median([v for _, v in prior])
        ceiling = (1.0 + tolerance) * med
        if latest > ceiling:
            violations.append(
                f"round {rnd}: REGRESSION — {key}={latest:g} rose above "
                f"{ceiling:g} ({(1.0 + tolerance):.0%} of prior median "
                f"{med:g})")
    for e in light:
        for key in OVERHEAD_KEYS:
            frac = e["parsed"].get(key)
            cap = OVERHEAD_BUDGETS.get(key, obs_overhead_max)
            if isinstance(frac, (int, float)) and frac > cap:
                violations.append(
                    f"round {e['round']}: OBS OVERHEAD — {key}="
                    f"{frac:g} exceeds the {cap:g} budget")

    published = (baseline or {}).get("published") or {}
    if light and isinstance(published, dict):
        latest = light[-1]["parsed"]
        for key, ref in published.items():
            got = latest.get(key)
            if (isinstance(ref, (int, float))
                    and isinstance(got, (int, float))
                    and got < (1.0 - tolerance) * float(ref)):
                violations.append(
                    f"round {light[-1]['round']}: REGRESSION vs published "
                    f"baseline — {key}={got:g} < {(1.0 - tolerance):.0%} "
                    f"of {ref:g}")
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="BENCH round files in trajectory order "
                         "(default: BENCH_r*.json in the repo root)")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO_ROOT, "BASELINE.json"),
                    help="baseline metadata file (published reference keys)")
    ap.add_argument("--tolerance", type=float, default=0.5,
                    help="allowed fractional drop of a relative key vs the "
                         "prior-round median (default 0.5 — CPU-degraded "
                         "relative measures are noisy)")
    ap.add_argument("--obs-overhead-max", type=float, default=0.25,
                    help="absolute cap on obs_overhead_frac (default 0.25)")
    ap.add_argument("--known-dark", default="",
                    help="comma-separated round indices grandfathered as "
                         "dark (the historical r03-r05 window)")
    ap.add_argument("--advisory", action="store_true",
                    help="report violations but exit 0")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    paths = args.paths or sorted(
        glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json")))
    if not paths:
        print("perf_gate: no bench files found", flush=True)
        return 2
    known_dark = {int(x) for x in args.known_dark.split(",") if x.strip()}
    try:
        entries = [load_round(p, i + 1) for i, p in enumerate(paths)]
    except (OSError, ValueError) as e:
        print(f"perf_gate: unreadable trajectory: {e}", flush=True)
        return 2
    baseline = None
    try:
        with open(args.baseline, "r", encoding="utf-8") as f:
            baseline = json.load(f)
    except (OSError, ValueError):
        pass  # baseline metadata is optional context, not a gate input

    violations = check_trajectory(
        entries, args.tolerance, args.obs_overhead_max,
        known_dark=known_dark, baseline=baseline)
    failed = bool(violations) and not args.advisory
    if args.format == "json":
        print(json.dumps({
            "ok": not violations,
            "advisory": bool(args.advisory),
            "n_rounds": len(entries),
            "known_dark": sorted(known_dark),
            "violations": violations,
            "rounds": [{"round": e["round"], "rc": e["rc"],
                        "path": os.path.basename(e["path"]),
                        "dark": e["rc"] != 0 or e["parsed"] is None,
                        "mode": (e["parsed"] or {}).get("mode"),
                        "metric": (e["parsed"] or {}).get("metric"),
                        "value": (e["parsed"] or {}).get("value")}
                       for e in entries],
        }, sort_keys=True))
    else:
        for v in violations:
            print(f"perf_gate: {v}", flush=True)
        if violations:
            mode = "ADVISORY" if args.advisory else "FAIL"
            print(f"perf_gate: {mode} — {len(violations)} violation(s) "
                  f"across {len(entries)} round(s)", flush=True)
        else:
            print(f"perf_gate: OK — {len(entries)} round(s), no dark "
                  "rounds, no regressions", flush=True)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
