#!/usr/bin/env python
"""Render the live health & SLO plane's state — snapshot or flight dump.

Two input shapes, sniffed automatically:

* a **health snapshot JSON** — what the MetricsExporter writes next to its
  OpenMetrics snapshot (``<obs_export_path>.health.json``, refreshed on
  every export and finalized at shutdown) and what ``GET /healthz``
  serves;
* a **flight dump** — a crc-framed JSONL ring written by the flight
  recorder.  Health-triggered dumps carry the plane's compact snapshot on
  the ``flight_meta`` line, and the ring itself holds the ``health.*``
  span events (anomalies, expirations, status transitions) leading up to
  the trigger.  Torn/corrupt lines are dropped, never fatal — same
  tolerance as ``FlightRecorder.load``.

The report shows the current status, every firing anomaly (z-score
windows and silence monitors), and the watchdog table with per-component
last-heartbeat age.  ``--assert-healthy`` is the CI gate: exit 1 unless
the status is ``ok``.  ``--json`` emits the merged machine-readable view
instead of text.

Usage::

    python tools/health_report.py metrics.prom.health.json
    python tools/health_report.py flight-run-001-health.anomaly.jsonl
    python tools/health_report.py snap.health.json --assert-healthy
    python tools/health_report.py snap.health.json --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from fedml_tpu.core.obs.flight import parse_line  # noqa: E402


def load_input(path: str) -> Dict[str, Any]:
    """``{"snapshot": {...} | None, "events": [...], "source": ...}`` from
    either input shape."""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{"):
        try:
            snap = json.loads(text)
        except ValueError as e:
            raise SystemExit(f"error: {path}: not valid JSON ({e})")
        if not isinstance(snap, dict):
            raise SystemExit(f"error: {path}: expected a JSON object")
        return {"snapshot": snap, "events": [], "source": "snapshot",
                "n_bad_lines": 0}
    # crc-framed flight dump: meta line first, ring records after
    snap: Optional[Dict[str, Any]] = None
    events: List[Dict[str, Any]] = []
    n_bad = 0
    reason = None
    for line in text.splitlines():
        line = line.rstrip("\n")
        if not line:
            continue
        rec = parse_line(line)
        if rec is None:
            n_bad += 1
            continue
        topic = rec.get("topic")
        if topic == "flight_meta":
            reason = rec.get("reason")
            health = rec.get("health")
            if isinstance(health, dict):
                snap = health
        elif (topic == "span_event"
                and str(rec.get("event", "")).startswith("health.")):
            events.append(rec)
    return {"snapshot": snap, "events": events, "source": "flight_dump",
            "reason": reason, "n_bad_lines": n_bad}


def _status_of(view: Dict[str, Any]) -> str:
    snap = view.get("snapshot") or {}
    status = snap.get("status")
    if status is not None:
        return str(status)
    # dump without a health meta (pre-health build, or non-health trigger):
    # infer the worst status the ring's events describe
    worst = "ok"
    for ev in view.get("events", ()):
        name = str(ev.get("event", ""))
        if name == "health.watchdog_expired":
            worst = "critical"
        elif name == "health.anomaly" and worst == "ok":
            worst = "degraded"
        elif name == "health.status":
            worst = str(ev.get("to", worst))
    return worst


def _fmt_age(age: Any) -> str:
    if age is None:
        return "-"
    return f"{float(age):8.2f}s"


def render_text(view: Dict[str, Any]) -> str:
    lines: List[str] = []
    status = _status_of(view)
    lines.append(f"health status: {status.upper()}")
    if view["source"] == "flight_dump":
        lines.append(f"source: flight dump (reason={view.get('reason')!r}, "
                     f"{view['n_bad_lines']} torn lines dropped)")
    snap = view.get("snapshot") or {}
    watchdogs = snap.get("watchdogs") or {}
    if watchdogs:
        lines.append("")
        lines.append("watchdogs (component · mode · last-beat age · "
                     "deadline · state):")
        for name in sorted(watchdogs):
            wd = watchdogs[name]
            state = ("EXPIRED" if wd.get("expired")
                     else ("armed" if wd.get("armed") else "idle"))
            lines.append(
                f"  {name:<28} {wd.get('mode', '?'):<9} "
                f"{_fmt_age(wd.get('last_beat_age_s'))} "
                f"{float(wd.get('deadline_s', 0)):7.1f}s  {state}")
    firing: List[str] = []
    for series, w in sorted((snap.get("windows") or {}).items()):
        if w.get("firing"):
            firing.append(
                f"  {series:<28} zscore   last={w.get('last')} "
                f"mean={w.get('mean')} std={w.get('std')} n={w.get('n')}")
    for series, m in sorted((snap.get("silences") or {}).items()):
        if m.get("firing"):
            firing.append(
                f"  {series:<28} silence  age={_fmt_age(m.get('age_s'))} "
                f"max={float(m.get('max_age_s', 0)):.1f}s")
    lines.append("")
    if firing:
        lines.append("firing anomalies:")
        lines.extend(firing)
    else:
        lines.append("firing anomalies: none")
    events = view.get("events") or []
    if events:
        lines.append("")
        lines.append(f"health events in the ring ({len(events)}):")
        for ev in events[-20:]:
            name = ev.get("event")
            detail = {k: v for k, v in ev.items()
                      if k not in ("topic", "event", "trace_id", "span_id",
                                   "node")}
            lines.append(f"  {name}: {json.dumps(detail, sort_keys=True)}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="health snapshot JSON or flight dump")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged machine-readable view")
    ap.add_argument("--assert-healthy", action="store_true",
                    help="exit 1 unless the status is 'ok' (CI gate)")
    args = ap.parse_args(argv)
    view = load_input(args.path)
    status = _status_of(view)
    if args.json:
        out = dict(view)
        out["status"] = status
        print(json.dumps(out, sort_keys=True, default=str))
    else:
        print(render_text(view))
    if args.assert_healthy and status != "ok":
        print(f"assert-healthy: status is {status!r}, not 'ok'",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
