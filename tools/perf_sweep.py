"""Ablation sweep for the in-mesh round's execution strategies.

Run on a real chip (default env, main thread):

    python tools/perf_sweep.py [--rounds 6] [--cpr 32]

Measures samples/s/chip for {padded, packed} x {while, scan} x
{per-step gather, pregather} and prints one JSON line per configuration
plus a final "best" line.  Use it to pick bench.py's flags after any
engine change (see PERF.md for the current measured table)."""

from __future__ import annotations

import argparse
import json
import sys


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=6)
    p.add_argument("--cpr", type=int, default=32)
    p.add_argument("--model", default=None, help="override bench model (CPU smoke: lr)")
    p.add_argument("--train-size", type=int, default=0,
                   help="override synthetic train size (CPU smoke)")
    flags = p.parse_args()

    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax

    import bench
    import fedml_tpu
    from fedml_tpu import data
    from fedml_tpu.simulation.xla.fed_sim import XLASimulator

    n_chips = len(jax.devices())
    # padded baseline + the packed lever grid shared with bench._autotune
    # (one definition: the grids cannot drift)
    configs = [dict(xla_pack=False)] + [
        dict({"xla_pack": True}, **v) for v in bench.AUTOTUNE_VARIANTS
    ]
    best = (None, 0.0)
    for overrides in configs:
        args = bench._bench_args(n_chips)
        args.xla_pack = False  # reset the bench default before applying
        args.comm_round = int(flags.rounds)
        args.client_num_per_round = min(100, int(flags.cpr))
        if flags.model:
            args.model = flags.model
        if flags.train_size:
            args.synthetic_train_size = int(flags.train_size)
        for k, v in overrides.items():
            setattr(args, k, v)
        args = fedml_tpu.init(args, should_init_logs=False)
        dataset, out_dim = data.load(args)
        model = fedml_tpu.models.create(args, out_dim)
        sim = XLASimulator(args, dataset, model)
        sim.train()
        sps = sim.throughput()["samples_per_sec"] / max(n_chips, 1)
        row = dict(overrides, sps_per_chip=round(sps, 1))
        print(json.dumps(row), flush=True)
        if sps > best[1]:
            best = (overrides, sps)
    print(json.dumps({"best": best[0], "sps_per_chip": round(best[1], 1)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
