#!/usr/bin/env python
"""fedlint: run the unified static-analysis plane over a source tree.

One framework (``fedml_tpu/core/analysis``), eight passes: the four ported
lint contracts (rng / obs / agg / perf) plus the thread-ownership race
detector, the ack-durability ordering checker, the JAX
purity/determinism pass, and the mesh-staleness (compiled-program cache)
checker.  See ``docs/STATIC_ANALYSIS.md`` for the rule
catalog and the pragma/baseline policy.

Exit codes: 0 clean (or everything suppressed), 1 findings, 2 usage or
internal error.  ``--advisory`` always exits 0 (the chaos harness runs an
advisory leg first so new rules can land before the tree is fully clean).

Usage::

    python tools/fedlint.py                    # lint the repo's fedml_tpu/
    python tools/fedlint.py --root DIR         # lint DIR instead
    python tools/fedlint.py --json             # machine-readable output
    python tools/fedlint.py --select races,ack # only these analyzers
    python tools/fedlint.py --list-rules       # rule catalog
    python tools/fedlint.py --write-baseline   # grandfather current findings
"""

from __future__ import annotations

import argparse
import os
import sys

from _analysis_loader import REPO_ROOT, load_analysis

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "fedlint_baseline.json")


def _pick_analyzers(analysis, select, ignore):
    analyzers = analysis.build_analyzers()
    names = {a.name for a in analyzers}
    for opt, label in ((select, "--select"), (ignore, "--ignore")):
        unknown = set(opt or ()) - names
        if unknown:
            raise SystemExit(
                f"fedlint: error: unknown analyzer(s) for {label}: "
                f"{', '.join(sorted(unknown))} (have: "
                f"{', '.join(sorted(names))})")
    if select:
        analyzers = [a for a in analyzers if a.name in select]
    if ignore:
        analyzers = [a for a in analyzers if a.name not in ignore]
    return analyzers


def _csv(value):
    return [part.strip() for part in value.split(",") if part.strip()]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.join(REPO_ROOT, "fedml_tpu"),
                    help="directory tree to lint (default: the library)")
    ap.add_argument("--json", action="store_true",
                    help="emit the versioned JSON report instead of text")
    ap.add_argument("--select", type=_csv, default=None, metavar="NAMES",
                    help="comma-separated analyzer names to run")
    ap.add_argument("--ignore", type=_csv, default=None, metavar="NAMES",
                    help="comma-separated analyzer names to skip")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE, metavar="PATH",
                    help="baseline suppression file (default: "
                         "tools/fedlint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline file entirely")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings (minus race/ack rules, "
                         "which may not be baselined) to --baseline and "
                         "exit 0")
    ap.add_argument("--advisory", action="store_true",
                    help="report findings but always exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    try:
        analysis = load_analysis()
        analyzers = _pick_analyzers(analysis, args.select, args.ignore)
        if args.list_rules:
            print(analysis.render_rule_catalog(analyzers), flush=True)
            return 0
        if not os.path.isdir(args.root):
            print(f"fedlint: error: --root {args.root} is not a directory",
                  file=sys.stderr, flush=True)
            return 2
        baseline = None
        if not args.no_baseline and not args.write_baseline \
                and os.path.exists(args.baseline):
            baseline = analysis.Baseline.load(args.baseline)
        result = analysis.analyze_tree(args.root, analyzers,
                                       baseline=baseline)
        if args.write_baseline:
            with open(args.baseline, "w", encoding="utf-8") as f:
                f.write(analysis.Baseline.render(result.findings,
                                                 result.root))
            kept = sum(1 for fi in result.findings if not fi.rule.startswith(
                analysis.NO_BASELINE_PREFIXES))
            print(f"fedlint: wrote {kept} baseline entr(y/ies) to "
                  f"{args.baseline}", flush=True)
            return 0
        if args.json:
            print(analysis.render_json(result), flush=True)
        else:
            print(analysis.render_text(result), flush=True)
        if result.findings and not args.advisory:
            return 1
        return 0
    except SystemExit:
        raise
    except Exception as exc:  # internal error -> exit 2, per the contract
        print(f"fedlint: internal error: {exc!r}", file=sys.stderr,
              flush=True)
        return 2


if __name__ == "__main__":
    sys.exit(main())
