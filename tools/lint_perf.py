#!/usr/bin/env python
"""Ingest-path perf lint: no stray fsyncs, no hot-path msgpack codecs.

PR 10's staged ingest pipeline moved the two expensive per-upload
operations behind dedicated seams:

* ``os.fsync`` — the durability seam.  ``core/checkpoint.py`` owns every
  journal/snapshot fsync (group commit amortizes one fsync over a whole
  batch of acks); ``core/obs`` fsyncs its own export/flight-recorder
  files.  An fsync anywhere else reintroduces a per-record disk stall on
  some hot path, silently undoing the ``uploads_per_s_pipelined`` win the
  perf gate bands.
* msgpack encode/decode (``msgpack_serialize`` / ``msgpack_restore`` /
  ``msgpack.packb`` / ``msgpack.unpackb``) — the codec seam.
  ``core/checkpoint.py`` codes journal frames; ``core/ingest.py`` is the
  zero-copy decoder.  Library code calling the codec directly puts a
  blocking (de)serialization back on the dispatcher thread, which is
  exactly what the pipeline's io/dispatch/commit staging exists to avoid.

This tool greps ``fedml_tpu/`` for these patterns with comments/strings
stripped.  The seam owners (``core/checkpoint.py``, ``core/ingest.py``,
``core/obs``) are exempt; anything else needing an exception carries a
``# lint_perf: allow`` pragma on the flagged line.  Wired into tier-1 via
``tests/test_lint_perf.py``.

Usage::

    python tools/lint_perf.py            # lint the repo's fedml_tpu/
    python tools/lint_perf.py --root DIR # lint DIR instead (tests use this)
"""

from __future__ import annotations

import argparse
import io
import os
import re
import sys
import tokenize

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# per-record disk stall: every fsync outside the durability/obs seams is a
# hot-path suspect — there is no legitimate third fsync site in the library
_STRAY_FSYNC = re.compile(r"(?<![\w.])os\s*\.\s*fsync\s*\(")
# hot-path codec: flax's msgpack entry points and the raw msgpack module —
# payload (de)serialization belongs to the journal framer and the zero-copy
# decoder, not to whatever thread happens to be dispatching
_HOT_CODEC = re.compile(
    r"(?<![\w.])(?:msgpack_restore|msgpack_serialize)\s*\("
    r"|(?<![\w.])msgpack\s*\.\s*(?:packb|unpackb)\s*\(")
_PRAGMA = "lint_perf: allow"

# the seam owners may fsync and run the codec freely
_EXEMPT_PARTS = (
    os.path.join("core", "obs"),
    os.path.join("core", "checkpoint.py"),
    os.path.join("core", "ingest.py"),
)


def _exempt(path: str) -> bool:
    norm = os.path.normpath(os.path.abspath(path))
    return any(os.sep + part + os.sep in norm or
               norm.endswith(os.sep + part) for part in _EXEMPT_PARTS)


def _code_lines(source: str) -> list:
    """Lines with comments and string literals blanked via ``tokenize`` —
    only actual code can trip the patterns (same approach as lint_obs)."""
    lines = source.splitlines()
    kept = list(lines)
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return kept  # unparseable: lint the raw lines rather than skip
    for tok in tokens:
        if tok.type not in (tokenize.COMMENT, tokenize.STRING):
            continue
        (srow, scol), (erow, ecol) = tok.start, tok.end
        for row in range(srow, erow + 1):
            line = kept[row - 1]
            lo = scol if row == srow else 0
            hi = ecol if row == erow else len(line)
            kept[row - 1] = line[:lo] + " " * (hi - lo) + line[hi:]
    return kept


def lint_file(path: str) -> list:
    if _exempt(path):
        return []
    violations = []
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        source = f.read()
    raw_lines = source.splitlines()
    for lineno, code in enumerate(_code_lines(source), 1):
        raw = raw_lines[lineno - 1]
        if _PRAGMA in raw:
            continue
        if _STRAY_FSYNC.search(code):
            violations.append(
                (path, lineno, "per-record fsync outside the durability seam",
                 raw.rstrip()))
        if _HOT_CODEC.search(code):
            violations.append(
                (path, lineno, "hot-path msgpack codec outside the seams",
                 raw.rstrip()))
    return violations


def lint_tree(root: str) -> list:
    violations = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                violations.extend(lint_file(os.path.join(dirpath, name)))
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.join(REPO_ROOT, "fedml_tpu"),
                    help="directory tree to lint (default: the library)")
    args = ap.parse_args(argv)

    violations = lint_tree(args.root)
    for path, lineno, kind, line in violations:
        rel = os.path.relpath(path, args.root)
        print(f"lint_perf: {rel}:{lineno}: {kind}: {line.strip()}", flush=True)
    if violations:
        print(f"lint_perf: {len(violations)} violation(s) — route durability "
              "through core/checkpoint (UpdateJournal group commit), payload "
              "(de)serialization through core/checkpoint framing or the "
              "core/ingest ZeroCopyDecoder, or mark an approved seam "
              f"with '# {_PRAGMA}'", flush=True)
        return 1
    print("lint_perf: clean", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
