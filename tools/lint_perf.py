#!/usr/bin/env python
"""Ingest-path perf lint: no stray fsyncs, no hot-path msgpack codecs.

Thin shim over the unified analysis plane (``fedml_tpu/core/analysis``,
see ``tools/fedlint.py`` and ``docs/STATIC_ANALYSIS.md``): the contracts,
the ``# lint_perf: allow`` pragma, the seam exemptions
(``core/checkpoint.py``, ``core/ingest.py``, ``core/obs``), and this CLI
are unchanged, but matching is now AST-based with import-alias resolution
— ``from os import fsync as f`` and ``import msgpack as mp`` no longer
dodge it, while ``self.msgpack_restore(...)`` lookalike methods no longer
need special-casing.

The contracts (PR 10's staged ingest pipeline): ``os.fsync`` belongs to
the durability seam (group commit amortizes one fsync over a batch of
acks); msgpack encode/decode belongs to the journal framer and the
zero-copy decoder — not to whatever thread happens to be dispatching.

Usage::

    python tools/lint_perf.py            # lint the repo's fedml_tpu/
    python tools/lint_perf.py --root DIR # lint DIR instead (tests use this)
"""

from __future__ import annotations

import argparse
import os
import sys

from _analysis_loader import REPO_ROOT, load_analysis

_analysis = load_analysis()
_ANALYZER = _analysis.passes.PerfAnalyzer()
_PRAGMA = "lint_perf: allow"

_KINDS = {
    "perf-stray-fsync": "per-record fsync outside the durability seam",
    "perf-hot-codec": "hot-path msgpack codec outside the seams",
}


def lint_file(path: str) -> list:
    src = _analysis.SourceFile(path)
    findings = _analysis.analyze_file(src, [_ANALYZER])
    findings.sort(key=lambda f: (f.lineno, _ANALYZER.rule_by_id(f.rule).order))
    return [(path, f.lineno, _KINDS[f.rule], f.source) for f in findings]


def lint_tree(root: str) -> list:
    violations = []
    for path in _analysis.iter_python_files(root):
        violations.extend(lint_file(path))
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.join(REPO_ROOT, "fedml_tpu"),
                    help="directory tree to lint (default: the library)")
    args = ap.parse_args(argv)

    violations = lint_tree(args.root)
    for path, lineno, kind, line in violations:
        rel = os.path.relpath(path, args.root)
        print(f"lint_perf: {rel}:{lineno}: {kind}: {line.strip()}", flush=True)
    if violations:
        print(f"lint_perf: {len(violations)} violation(s) — route durability "
              "through core/checkpoint (UpdateJournal group commit), payload "
              "(de)serialization through core/checkpoint framing or the "
              "core/ingest ZeroCopyDecoder, or mark an approved seam "
              f"with '# {_PRAGMA}'", flush=True)
        return 1
    print("lint_perf: clean", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
