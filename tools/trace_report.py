#!/usr/bin/env python
"""Offline round-trace reconstruction and critical-path reporting.

Reads the ``span_start`` / ``span_end`` / ``span_event`` records that
``fedml_tpu.core.obs`` emits through the mlops JSONL sink and rebuilds one
span tree per (run, round) trace:

* **Integrity** — every trace must have exactly one root span (the round),
  no span may reference a parent that never started, and every started
  span must close.  A crash-restarted server closes its predecessor's
  round span under the same deterministic id, so a clean recovery still
  reads as closed here.  ``--assert-closed`` turns violations into exit
  code 2 (the chaos gate).
* **Critical path** — walk from the round root to the leaf that closed
  last; the chain of spans on that walk is where the round's wall time
  went (the slowest silo's train+upload leg, a retransmit storm, ...).
* **Straggler ranking** — ``client.train`` spans sorted by duration;
  anything slower than ``--slow-factor`` x the round's median is flagged
  (the same factor ``obs_slow_round_factor`` uses online).
* **Async mode** — a trace whose round span carries an async ``mode`` (or
  any ``buffer.flush`` span) reports per-flush staleness distribution and
  buffer occupancy columns, and ranks stragglers by TIME-TO-REPORT (span
  close relative to the cycle open) instead of train duration: under
  buffered execution a slow client hurts by *when its delta lands*, not
  by how long its local step ran.
* **Per-client attribution** (``--clients``) — with the telemetry plane on,
  remote ``client.train`` sub-spans are grafted into the tree, so each
  participant gets a compute / network / deferred split: compute is the
  remote train span, network is the ``upload`` span's SELF time (duration
  minus nested server-side children), deferred is the async gap between
  the last report and the cycle open not explained by either.  The
  dominant phase is the participant's straggler class.
* **Run diff** (``--diff A B``) — compare two runs' per-phase attribution
  and critical-path wall time; phases whose mean self-time regressed past
  ``--diff-tolerance`` are printed and exit code 1.

Durations prefer the end record's monotonic ``duration_s``; adopted ends
(crash recovery) carry none and fall back to the sink wall-timestamp delta.

Usage::

    python tools/trace_report.py run.jsonl
    python tools/trace_report.py run.jsonl --round 3 --clients
    python tools/trace_report.py a.jsonl b.jsonl --assert-closed
    python tools/trace_report.py --diff before.jsonl after.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Iterable, List, Optional, Tuple

SPAN_TOPICS = ("span_start", "span_end", "span_event")


def load_records(path: str) -> List[Dict[str, Any]]:
    """The file's span-topic records, in file order (other topics skipped;
    unparseable lines skipped — a torn tail write is not a trace error)."""
    out: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("topic") in SPAN_TOPICS:
                out.append(rec)
    return out


class SpanNode:
    """One reconstructed span: paired start/end records plus events."""

    __slots__ = ("span_id", "start", "end", "events", "children")

    def __init__(self, span_id: str):
        self.span_id = span_id
        self.start: Optional[Dict[str, Any]] = None
        self.end: Optional[Dict[str, Any]] = None
        self.events: List[Dict[str, Any]] = []
        self.children: List["SpanNode"] = []

    @property
    def name(self) -> str:
        for rec in (self.start, self.end):
            if rec is not None and rec.get("name"):
                return str(rec["name"])
        return "?"

    @property
    def node(self) -> Any:
        return (self.start or {}).get("node", "?")

    @property
    def parent_span_id(self) -> Optional[str]:
        return (self.start or {}).get("parent_span_id")

    @property
    def round_idx(self) -> Optional[int]:
        for rec in (self.start, self.end):
            if rec is not None and "round_idx" in rec:
                return int(rec["round_idx"])
        return None

    def duration_s(self) -> float:
        """Monotonic duration when the closer measured one; wall-ts delta
        for cross-process (adopted) closes; 0 when unclosed."""
        if self.end is not None and isinstance(
                self.end.get("duration_s"), (int, float)):
            return float(self.end["duration_s"])
        if (self.start is not None and self.end is not None
                and isinstance(self.start.get("ts"), (int, float))
                and isinstance(self.end.get("ts"), (int, float))):
            return max(0.0, float(self.end["ts"]) - float(self.start["ts"]))
        return 0.0

    def end_ts(self) -> float:
        if self.end is not None and isinstance(self.end.get("ts"), (int, float)):
            return float(self.end["ts"])
        if self.start is not None and isinstance(self.start.get("ts"), (int, float)):
            return float(self.start["ts"]) + self.duration_s()
        return 0.0


class Trace:
    """All spans sharing one trace_id (= one round of one run)."""

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.spans: Dict[str, SpanNode] = {}

    def _node(self, span_id: str) -> SpanNode:
        sn = self.spans.get(span_id)
        if sn is None:
            sn = self.spans[span_id] = SpanNode(span_id)
        return sn

    def add(self, rec: Dict[str, Any]) -> None:
        topic = rec.get("topic")
        sn = self._node(str(rec.get("span_id")))
        if topic == "span_start":
            # duplicate starts (a re-delivered record) keep the FIRST copy:
            # ids are deterministic, so first-wins is order-stable
            if sn.start is None:
                sn.start = rec
        elif topic == "span_end":
            if sn.end is None:
                sn.end = rec
        else:
            sn.events.append(rec)

    def link(self) -> None:
        for sn in self.spans.values():
            sn.children = []
        for sn in self.spans.values():
            pid = sn.parent_span_id
            if pid is not None and pid in self.spans:
                self.spans[pid].children.append(sn)

    def roots(self) -> List[SpanNode]:
        return [sn for sn in self.spans.values()
                if sn.start is not None and sn.parent_span_id is None]

    def round_idx(self) -> Optional[int]:
        for sn in self.spans.values():
            ri = sn.round_idx
            if ri is not None:
                return ri
        return None

    def problems(self) -> List[str]:
        """Integrity violations: orphans, unclosed spans, ends that never
        started, zero-or-many roots."""
        out: List[str] = []
        roots = self.roots()
        if len(roots) != 1:
            out.append(f"{len(roots)} root spans (expected exactly 1: the round)")
        elif roots[0].name != "round":
            out.append(f"root span is {roots[0].name!r} (expected 'round')")
        for sn in sorted(self.spans.values(), key=lambda s: s.span_id):
            if sn.start is None and sn.end is not None:
                out.append(f"span {sn.span_id} ({sn.name}) ended without starting")
            if sn.start is not None and sn.end is None:
                out.append(f"span {sn.span_id} ({sn.name}, node={sn.node}) "
                           "never closed")
            pid = sn.parent_span_id
            if pid is not None and pid not in self.spans:
                out.append(f"span {sn.span_id} ({sn.name}) is an orphan "
                           f"(parent {pid} unknown)")
        return out

    def critical_path(self) -> List[SpanNode]:
        """Root-to-leaf chain following, at each level, the child that
        closed LAST — the spans the round's wall time actually waited on."""
        roots = self.roots()
        if not roots:
            return []
        self.link()
        path = [roots[0]]
        seen = {roots[0].span_id}
        while path[-1].children:
            nxt = max(path[-1].children, key=lambda s: (s.end_ts(), s.span_id))
            if nxt.span_id in seen:  # defensive: corrupt parent links
                break
            seen.add(nxt.span_id)
            path.append(nxt)
        return path

    def is_async(self) -> bool:
        """Buffered-async trace: the round span's ``mode`` says so, or a
        ``buffer.flush`` span is present (server-lifetime traces)."""
        for root in self.roots():
            if "async" in str((root.start or {}).get("mode", "")):
                return True
        return any(sn.name == "buffer.flush" for sn in self.spans.values())

    def flushes(self) -> List[SpanNode]:
        """``buffer.flush`` spans in close order (one per drained buffer)."""
        return sorted(
            (sn for sn in self.spans.values()
             if sn.name == "buffer.flush" and sn.start is not None),
            key=lambda s: (s.end_ts(), s.span_id))

    def _root_start_ts(self) -> float:
        roots = self.roots()
        if roots and isinstance((roots[0].start or {}).get("ts"), (int, float)):
            return float(roots[0].start["ts"])
        return 0.0

    def attribution(self) -> Optional[Dict[str, Any]]:
        """Where the round's wall time went: per-name SELF seconds (span
        duration minus its children's — concurrent children can legitimately
        sum past the round wall), plus the compile-vs-execute split the
        simulator attached to the round-end record when available."""
        roots = self.roots()
        if not roots:
            return None
        self.link()
        root = roots[0]
        by_name: Dict[str, float] = {}
        seen = set()

        def walk(sn: SpanNode) -> None:
            if sn.span_id in seen:  # defensive: corrupt parent links
                return
            seen.add(sn.span_id)
            child_sum = 0.0
            for c in sn.children:
                child_sum += c.duration_s()
                walk(c)
            self_s = max(0.0, sn.duration_s() - child_sum)
            by_name[sn.name] = by_name.get(sn.name, 0.0) + self_s

        walk(root)
        end = root.end or {}
        out: Dict[str, Any] = {
            "round": self.round_idx(),
            "round_s": round(root.duration_s(), 6),
            "n_spans": len(self.spans),
            "self_seconds": {
                k: round(v, 6)
                for k, v in sorted(by_name.items(), key=lambda kv: -kv[1])},
        }
        for key in ("compile_s", "execute_s"):
            if isinstance(end.get(key), (int, float)):
                out[key] = float(end[key])
        return out

    def stragglers(self, slow_factor: float) -> List[Tuple[SpanNode, float, bool]]:
        """``client.train`` spans ranked slowest-first with their duration
        (sync) or time-to-report since cycle open (async) and a flag for
        > slow_factor x median."""
        trains = [sn for sn in self.spans.values()
                  if sn.name == "client.train" and sn.start is not None]
        if not trains:
            return []
        if self.is_async():
            t0 = self._root_start_ts()
            metric = lambda sn: max(0.0, sn.end_ts() - t0)  # noqa: E731
        else:
            metric = lambda sn: sn.duration_s()  # noqa: E731
        vals = sorted(metric(sn) for sn in trains)
        median = vals[len(vals) // 2]
        ranked = sorted(trains, key=lambda s: -metric(s))
        return [(sn, metric(sn),
                 median > 0 and metric(sn) > slow_factor * median)
                for sn in ranked]

    def clients(self) -> List[Dict[str, Any]]:
        """Per-participant compute/network/deferred attribution and the
        dominant-phase straggler class.  Participants are keyed by the
        ``client`` attr when present (sp simulation) else the emitting
        ``node`` (distributed ranks); network is the ``upload`` span's
        self-time (its duration minus nested children — the server-side
        receive work parents under the upload context); deferred is, in
        async traces, the report latency since cycle open that neither
        compute nor network explains (buffer residency)."""
        self.link()

        def key_of(sn: SpanNode) -> Any:
            st = sn.start or {}
            return st.get("client", st.get("node", "?"))

        per: Dict[Any, Dict[str, float]] = {}

        def slot(k: Any) -> Dict[str, float]:
            return per.setdefault(k, {"compute_s": 0.0, "network_s": 0.0,
                                      "deferred_s": 0.0, "_last_end": 0.0})

        for sn in self.spans.values():
            if sn.start is None:
                continue
            if sn.name == "client.train":
                d = slot(key_of(sn))
                d["compute_s"] += sn.duration_s()
            elif sn.name == "upload":
                d = slot(key_of(sn))
                child_s = sum(c.duration_s() for c in sn.children)
                d["network_s"] += max(0.0, sn.duration_s() - child_s)
            else:
                continue
            d["_last_end"] = max(d["_last_end"], sn.end_ts())
        t0 = self._root_start_ts()
        is_async = self.is_async()
        out: List[Dict[str, Any]] = []
        for k in sorted(per, key=str):
            d = per[k]
            if is_async and t0 > 0 and d["_last_end"] > 0:
                ttr = max(0.0, d["_last_end"] - t0)
                d["deferred_s"] = max(
                    0.0, ttr - d["compute_s"] - d["network_s"])
            del d["_last_end"]
            phases = {"compute": d["compute_s"], "network": d["network_s"],
                      "deferred": d["deferred_s"]}
            cls = max(phases, key=phases.get)  # ties: compute wins (order)
            out.append({"client": k,
                        "compute_s": round(d["compute_s"], 6),
                        "network_s": round(d["network_s"], 6),
                        "deferred_s": round(d["deferred_s"], 6),
                        "class": cls})
        return out


def build_traces(records: Iterable[Dict[str, Any]]) -> Dict[str, Trace]:
    traces: Dict[str, Trace] = {}
    for rec in records:
        tid = str(rec.get("trace_id"))
        tr = traces.get(tid)
        if tr is None:
            tr = traces[tid] = Trace(tid)
        tr.add(rec)
    for tr in traces.values():
        tr.link()
    return traces


def _fmt_path(path: List[SpanNode]) -> str:
    return " > ".join(
        f"{sn.name}[node={sn.node}, {sn.duration_s():.3f}s]" for sn in path
    )


def trace_payload(tr: Trace, slow_factor: float) -> Dict[str, Any]:
    """One trace as machine-readable data (the ``--format json`` shape —
    same numbers as the text report, so perf tooling and CI consume this
    instead of screen-scraping)."""
    problems = tr.problems()
    roots = tr.roots()
    metric_name = "time_to_report" if tr.is_async() else "dur"
    return {
        "trace_id": tr.trace_id,
        "round": tr.round_idx(),
        "duration_s": round(roots[0].duration_s(), 6) if roots else 0.0,
        "n_spans": len(tr.spans),
        "async": tr.is_async(),
        "critical_path": [
            {"name": sn.name, "node": sn.node,
             "duration_s": round(sn.duration_s(), 6)}
            for sn in tr.critical_path()],
        "stragglers": [
            {"node": sn.node, "metric": metric_name,
             "value": round(d, 6), "slow": bool(slow)}
            for sn, d, slow in tr.stragglers(slow_factor)],
        "flushes": [
            {"round": fl.round_idx,
             "n_deltas": (fl.start or {}).get("n_deltas"),
             "capacity": (fl.start or {}).get("capacity"),
             "reason": (fl.start or {}).get("reason"),
             "duration_s": round(fl.duration_s(), 6)}
            for fl in tr.flushes()],
        "events": [
            {k: v for k, v in sorted(ev.items())
             if k not in ("topic", "trace_id", "span_id")}
            for sn in tr.spans.values() for ev in sn.events],
        "attribution": tr.attribution(),
        "clients": tr.clients(),
        "problems": problems,
    }


def _ordered(traces: Dict[str, Trace]) -> List[Trace]:
    return sorted(
        traces.values(),
        key=lambda t: (t.round_idx() if t.round_idx() is not None else -1,
                       t.trace_id),
    )


def report_json(traces: Dict[str, Trace], slow_factor: float,
                round_filter: Optional[int] = None, out=None) -> int:
    """Emit the whole report as one JSON document; returns problem count."""
    out = out if out is not None else sys.stdout
    payloads = [trace_payload(tr, slow_factor) for tr in _ordered(traces)
                if round_filter is None or tr.round_idx() == round_filter]
    n_problems = sum(len(p["problems"]) for p in payloads)
    json.dump({"n_traces": len(payloads), "n_problems": n_problems,
               "traces": payloads}, out, sort_keys=True)
    out.write("\n")
    return n_problems


def phase_profile(traces: Dict[str, Trace]) -> Dict[str, float]:
    """Mean per-round self-seconds by span name (phases absent in a round
    count as zero, so the means are comparable across runs with different
    round counts)."""
    samples: Dict[str, float] = {}
    n = 0
    for tr in _ordered(traces):
        att = tr.attribution()
        if not att:
            continue
        n += 1
        for name, secs in att["self_seconds"].items():
            samples[name] = samples.get(name, 0.0) + float(secs)
    if n == 0:
        return {}
    return {k: v / n for k, v in samples.items()}


def _round_seconds(traces: Dict[str, Trace]) -> float:
    durs = sorted(
        tr.roots()[0].duration_s() for tr in traces.values() if tr.roots())
    return durs[len(durs) // 2] if durs else 0.0


def diff_report(path_a: str, path_b: str, tolerance: float,
                out=None) -> int:
    """Compare run B against baseline run A: median round wall time and
    mean per-phase self-seconds.  Returns the number of REGRESSED phases
    (mean self-time grew by more than ``tolerance`` fractionally AND by an
    absolute floor that ignores sub-millisecond jitter)."""
    out = out if out is not None else sys.stdout
    ta = build_traces(load_records(path_a))
    tb = build_traces(load_records(path_b))
    prof_a, prof_b = phase_profile(ta), phase_profile(tb)
    ra, rb = _round_seconds(ta), _round_seconds(tb)
    print(f"diff: A={path_a} ({len(ta)} traces)  "
          f"B={path_b} ({len(tb)} traces)", file=out)
    print(f"  round median: A={ra:.3f}s  B={rb:.3f}s  "
          f"delta={rb - ra:+.3f}s", file=out)
    regressed = 0
    for name in sorted(set(prof_a) | set(prof_b)):
        a, b = prof_a.get(name, 0.0), prof_b.get(name, 0.0)
        flag = ""
        if b > a * (1.0 + tolerance) and b - a > 1e-3:
            flag = "  << REGRESSED"
            regressed += 1
        pct = (100.0 * (b - a) / a) if a > 0 else float("inf") if b > 0 else 0.0
        print(f"  {name:<20s} A={a:8.4f}s  B={b:8.4f}s  "
              f"{pct:+7.1f}%{flag}", file=out)
    if regressed:
        print(f"trace_report: {regressed} regressed phase(s)", file=out)
    return regressed


def report(traces: Dict[str, Trace], slow_factor: float,
           round_filter: Optional[int] = None, out=None,
           attribution: bool = False, clients: bool = False) -> int:
    """Print the per-round report; returns the total problem count."""
    # bind the stream late: a def-time sys.stdout default would dodge any
    # redirection installed after import (test capture, CLI piping)
    out = out if out is not None else sys.stdout
    n_problems = 0
    for tr in _ordered(traces):
        ri = tr.round_idx()
        if round_filter is not None and ri != round_filter:
            continue
        problems = tr.problems()
        n_problems += len(problems)
        roots = tr.roots()
        dur = roots[0].duration_s() if roots else 0.0
        print(f"trace {tr.trace_id}  round={ri}  spans={len(tr.spans)}  "
              f"duration={dur:.3f}s", file=out)
        path = tr.critical_path()
        if path:
            print(f"  critical path: {_fmt_path(path)}", file=out)
        is_async = tr.is_async()
        for fl in tr.flushes():
            st = fl.start or {}
            n = st.get("n_deltas", "?")
            cap = st.get("capacity", None)
            occ = (f"{int(n) / int(cap):.2f}"
                   if isinstance(n, int) and isinstance(cap, int) and cap
                   else "?")
            stal = "/".join(
                str(st.get(k, "?")) for k in
                ("staleness_min", "staleness_mean", "staleness_max"))
            print(f"  flush round={fl.round_idx} n_deltas={n} "
                  f"capacity={cap} occupancy={occ} "
                  f"reason={st.get('reason', '?')} "
                  f"staleness(min/mean/max)={stal} "
                  f"dur={fl.duration_s():.3f}s", file=out)
        if attribution:
            att = tr.attribution()
            if att:
                split = ""
                if "compile_s" in att:
                    split = (f"  compile={att['compile_s']:.3f}s "
                             f"execute={att.get('execute_s', 0.0):.3f}s")
                print(f"  attribution: round={att['round_s']:.3f}s"
                      f"{split}", file=out)
                for name, secs in att["self_seconds"].items():
                    if secs <= 0.0:
                        continue
                    pct = (100.0 * secs / att["round_s"]
                           if att["round_s"] > 0 else 0.0)
                    print(f"    {name:<20s} {secs:8.3f}s  {pct:5.1f}%",
                          file=out)
        if clients:
            rows = tr.clients()
            if rows:
                print("  client     compute_s  network_s  deferred_s  class",
                      file=out)
                for row in rows:
                    print(f"  {str(row['client']):<9s}"
                          f"  {row['compute_s']:9.4f}"
                          f"  {row['network_s']:9.4f}"
                          f"  {row['deferred_s']:10.4f}"
                          f"  {row['class']}", file=out)
        metric_name = "time_to_report" if is_async else "dur"
        for sn, d, slow in tr.stragglers(slow_factor):
            flag = "  << STRAGGLER" if slow else ""
            print(f"  client.train node={sn.node}: "
                  f"{metric_name}={d:.3f}s{flag}", file=out)
        events = [ev for sn in tr.spans.values() for ev in sn.events]
        for ev in events:
            print(f"  event {ev.get('event')}: node={ev.get('node')} "
                  + " ".join(f"{k}={v}" for k, v in sorted(ev.items())
                             if k not in ("topic", "trace_id", "span_id",
                                          "event", "node", "ts")),
                  file=out)
        for p in problems:
            print(f"  PROBLEM: {p}", file=out)
    return n_problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="mlops JSONL file(s)")
    ap.add_argument("--round", type=int, default=None,
                    help="report only this round index")
    ap.add_argument("--slow-factor", type=float, default=2.0,
                    help="straggler flag threshold vs round median (default 2.0)")
    ap.add_argument("--assert-closed", action="store_true",
                    help="exit 2 if any trace has orphan/unclosed spans")
    ap.add_argument("--attribution", action="store_true",
                    help="per-round wall-clock attribution: self-time by "
                         "span name + the simulator's compile/execute split")
    ap.add_argument("--clients", action="store_true",
                    help="per-participant compute/network/deferred table "
                         "with the dominant-phase straggler class")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"), default=None,
                    help="compare run B against baseline run A: median "
                         "round time and mean per-phase self-seconds; "
                         "exit 1 when any phase regressed")
    ap.add_argument("--diff-tolerance", type=float, default=0.25,
                    help="fractional growth in a phase's mean self-time "
                         "counted as a regression (default 0.25)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="json emits one machine-readable document with the "
                         "same data as the text report")
    args = ap.parse_args(argv)
    if args.diff is not None:
        return 1 if diff_report(args.diff[0], args.diff[1],
                                args.diff_tolerance) else 0
    if not args.paths:
        ap.error("at least one JSONL path is required (or use --diff A B)")

    records: List[Dict[str, Any]] = []
    for path in args.paths:
        records.extend(load_records(path))
    if not records:
        if args.format == "json":
            print(json.dumps({"n_traces": 0, "n_problems": 0, "traces": []}))
        else:
            print("trace_report: no span records found", flush=True)
        return 0
    traces = build_traces(records)
    if args.format == "json":
        n_problems = report_json(traces, args.slow_factor, args.round)
        return 2 if n_problems and args.assert_closed else 0
    n_problems = report(traces, args.slow_factor, args.round,
                        attribution=args.attribution, clients=args.clients)
    if n_problems:
        print(f"trace_report: {n_problems} integrity problem(s)", flush=True)
        if args.assert_closed:
            return 2
    else:
        print(f"trace_report: {len(traces)} trace(s), all closed", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
