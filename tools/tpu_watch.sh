#!/bin/bash
# Round-4 chip watcher: probe the tunneled TPU at a gentle cadence; the
# moment it answers, run the perf sweep + the transformer proof-point ONCE
# and leave the results in /tmp/tpu_results/.  Probes are short and plain
# (jax.devices() only — no compiles) so a wedged relay is never made worse.
set -u
OUT=/tmp/tpu_results
mkdir -p "$OUT"
while true; do
  if timeout 60 python -c "import jax; d = jax.devices()[0]; assert 'cpu' not in (d.platform or '').lower(), d" >/dev/null 2>&1; then
    echo "$(date -u) tunnel OK — running sweep" >> "$OUT/watch.log"
    cd /root/repo
    python tools/perf_sweep.py --rounds 6 --cpr 32 \
      > "$OUT/sweep.json" 2> "$OUT/sweep.err"
    rc=$?
    echo "$(date -u) sweep rc=$rc" >> "$OUT/watch.log"
    if [ "$rc" -ne 0 ]; then
      # tunnel died mid-sweep: wait out the wedge and try again
      sleep 900
      continue
    fi
    BENCH_TF_STEPS=12 python - > "$OUT/transformer.json" 2> "$OUT/transformer.err" <<'EOF'
import json, sys
sys.path.insert(0, "/root/repo")
import bench
print(json.dumps(bench._measure_transformer()))
EOF
    echo "$(date -u) transformer rc=$?" >> "$OUT/watch.log"
    exit 0
  fi
  echo "$(date -u) tunnel down" >> "$OUT/watch.log"
  sleep 600
done
