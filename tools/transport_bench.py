"""Transport micro-benchmark — counterpart of the reference's
``python/tests/grpc_benchmark/`` (gRPC vs torch-RPC throughput harness,
SURVEY.md §4): round-trip latency and model-payload throughput for the
in-repo message backends, two endpoints on localhost.

    python tools/transport_bench.py [--backends loopback,tcp,grpc]
                                    [--sizes 1024,1048576,8388608]
                                    [--iters 30]

Prints one JSON line per (backend, payload-size) with msgs/s and MB/s, and
a final summary line.  The payload mimics a model sync: a dict of float32
numpy arrays, pickled by the transport exactly as a real round would.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _payload(total_bytes: int):
    n = max(1, total_bytes // 4)
    return {"w": np.arange(n, dtype=np.float32)}


def _make_pair(backend: str, base_port: int):
    """Two connected endpoints (rank 0 and 1) of the given backend."""
    if backend == "loopback":
        from fedml_tpu.core.distributed.communication.loopback import (
            LoopbackCommManager,
            LoopbackHub,
        )

        LoopbackHub.reset()
        return (LoopbackCommManager("tb", 0, 2), LoopbackCommManager("tb", 1, 2))
    if backend == "tcp":
        from fedml_tpu.core.distributed.communication.tcp.tcp_comm_manager import (
            TCPCommManager,
        )

        return (TCPCommManager(base_port=base_port, rank=0, size=2),
                TCPCommManager(base_port=base_port, rank=1, size=2))
    if backend == "grpc":
        from fedml_tpu.core.distributed.communication.grpc.grpc_comm_manager import (
            GRPCCommManager,
        )

        return (GRPCCommManager(port=base_port, client_id=0, client_num=2,
                                base_port=base_port),
                GRPCCommManager(port=base_port + 1, client_id=1, client_num=2,
                                base_port=base_port))
    raise ValueError(backend)


class _Echo:
    """Rank-1 observer: echo every PING back to rank 0."""

    def __init__(self, mgr):
        self.mgr = mgr

    def receive_message(self, msg_type, msg) -> None:
        from fedml_tpu.core.distributed.communication.message import Message

        if msg.get_type() == "ping":
            m = Message("pong", 1, 0)
            m.add_params("payload", msg.get("payload"))
            self.mgr.send_message(m)


class _Collect:
    """Rank-0 observer: queue of received PONGS only (transports also emit
    a connection_ready self-notification at startup; counting it would
    offset the timed loop by one in-flight message)."""

    def __init__(self):
        self.q: "queue.Queue" = queue.Queue()

    def receive_message(self, msg_type, msg) -> None:
        if msg.get_type() == "pong":
            self.q.put(msg)


def bench_backend(backend: str, sizes, iters: int, base_port: int):
    from fedml_tpu.core.distributed.communication.message import Message

    a, b = _make_pair(backend, base_port)
    col = _Collect()
    a.add_observer(col)
    b.add_observer(_Echo(b))
    ta = threading.Thread(target=a.handle_receive_message, daemon=True)
    tb = threading.Thread(target=b.handle_receive_message, daemon=True)
    ta.start()
    tb.start()
    time.sleep(0.3)
    rows = []
    try:
        for size in sizes:
            payload = _payload(size)
            # warmup
            m = Message("ping", 0, 1)
            m.add_params("payload", payload)
            a.send_message(m)
            col.q.get(timeout=30)
            t0 = time.time()
            for _ in range(iters):
                m = Message("ping", 0, 1)
                m.add_params("payload", payload)
                a.send_message(m)
                col.q.get(timeout=60)
            dt = time.time() - t0
            row = {
                "backend": backend,
                "payload_bytes": int(size),
                "round_trips_per_s": round(iters / dt, 2),
                "mb_per_s": round(2 * size * iters / dt / 1e6, 2),  # both legs
                "rtt_ms": round(dt / iters * 1e3, 3),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
    finally:
        a.stop_receive_message()
        b.stop_receive_message()
    return rows


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--backends", default="loopback,tcp,grpc")
    p.add_argument("--sizes", default="1024,1048576,8388608")
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--base-port", type=int, default=0)
    flags = p.parse_args()
    sizes = [int(s) for s in flags.sizes.split(",")]
    def _free_pair() -> int:
        """A base port whose base AND base+1 are both bindable (the
        two-endpoint backends use base+rank)."""
        import socket

        for _ in range(64):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            base = s.getsockname()[1]
            s.close()
            try:
                s2 = socket.socket()
                s2.bind(("127.0.0.1", base + 1))
                s2.close()
                return base
            except OSError:
                continue
        raise RuntimeError("no free port pair found")

    all_rows = []
    for i, backend in enumerate(flags.backends.split(",")):
        base_port = flags.base_port + 10 * i if flags.base_port else _free_pair()
        all_rows += bench_backend(backend.strip(), sizes, flags.iters, base_port)
    best = max(all_rows, key=lambda r: r["mb_per_s"])
    print(json.dumps({"summary": "best_throughput", **best}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
