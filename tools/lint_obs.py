#!/usr/bin/env python
"""Observability lint: no bare counter bags, no direct sink emits.

With ``core/obs`` in place there is exactly one metrics surface
(``obs.counter_inc`` / ``gauge_set`` / ``histogram_observe`` — labeled,
capped, exportable) and one emission seam (the mlops sink fan).  Library
code that grows its own ``defaultdict(int)`` counter bag or calls
``<sink>.emit(...)`` directly bypasses both: those numbers never reach the
registry export and never ride the sink fan's JSONL/broker legs.

Two more patterns guard the exposition seam: ``print(json.dumps(...))``
(the bench driver's stdout metric contract — library code printing JSON
blobs races the exactly-one-metric-line guarantee) and
``render_openmetrics(...)`` outside ``core/obs`` (exposition belongs to
the exporter, not ad-hoc render calls).

One pattern guards the telemetry wire seam: the piggybacked telemetry
blob rides messages under exactly one Message-param key, owned by
``core/obs/telemetry.py`` (attach/absorb).  Any other module spelling
that key constructs or reads telemetry params off-seam — it would dodge
the seq/dedup protocol and the best-effort contract.  Unlike the other
rules this one scans RAW lines (the key is a string literal) and applies
even inside ``core/obs``; only ``core/obs/telemetry.py`` is exempt.

This tool greps ``fedml_tpu/`` for these patterns with comments/strings
stripped.  ``core/obs`` and ``core/mlops`` — the two layers that ARE the
seam — are exempt; anything else needing an exception carries a
``# lint_obs: allow`` pragma on the flagged line.  Wired into tier-1 via
``tests/test_lint_obs.py``.

Usage::

    python tools/lint_obs.py            # lint the repo's fedml_tpu/
    python tools/lint_obs.py --root DIR # lint DIR instead (tests use this)
"""

from __future__ import annotations

import argparse
import io
import os
import re
import sys
import tokenize

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# counter bags: defaultdict(int) is the canonical "private metrics dict"
# constructor (Counter() would be next, but the stdlib Counter has heavy
# non-metrics use, so only the unambiguous form is banned)
_COUNTER_BAG = re.compile(r"(?<![\w.])defaultdict\s*\(\s*int\s*\)")
# direct sink emission: any attribute/variable whose name contains "sink"
# (or the mlops fan) calling .emit(...) — metrics and spans go through the
# obs facade; records go through core/mlops helpers
_SINK_EMIT = re.compile(r"(?i)\w*(?:sink|fan)\w*\s*\.\s*emit\s*\(")
# stdout metric emission: print(json.dumps(...)) is the bench driver's
# contract line and NOBODY else's — a library module printing JSON blobs
# races the bench's exactly-one-metric-line stdout guarantee and is
# invisible to the registry export
_PRINTED_JSON = re.compile(r"(?<![\w.])print\s*\(\s*json\s*\.\s*dumps\s*\(")
# direct exposition: rendering the registry to OpenMetrics text belongs to
# the exporter inside core/obs — library code calling render_openmetrics
# (or reaching for the exposition module) forks the export seam
_DIRECT_RENDER = re.compile(r"(?<![\w.])render_openmetrics\s*\(")
# the telemetry wire key: one Message-param seam, owned by
# core/obs/telemetry.py (attach/absorb).  Built by concatenation so this
# linter's own source never trips the rule if it is ever linted.
_TELEMETRY_WIRE = re.compile("__obs_" + "telemetry__")
_PRAGMA = "lint_obs: allow"

# the two layers that implement the seam may touch sinks/registries freely
_EXEMPT_PARTS = (
    os.path.join("core", "obs"),
    os.path.join("core", "mlops"),
)

_TELEMETRY_SEAM = os.path.join("core", "obs", "telemetry.py")


def _exempt(path: str) -> bool:
    norm = os.path.normpath(os.path.abspath(path))
    return any(os.sep + part + os.sep in norm or
               norm.endswith(os.sep + part) for part in _EXEMPT_PARTS)


def _is_telemetry_seam(path: str) -> bool:
    norm = os.path.normpath(os.path.abspath(path))
    return norm.endswith(os.sep + _TELEMETRY_SEAM)


def _code_lines(source: str) -> list:
    """Lines with comments and string literals blanked via ``tokenize`` —
    only actual code can trip the patterns (same approach as lint_rng)."""
    lines = source.splitlines()
    kept = list(lines)
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return kept  # unparseable: lint the raw lines rather than skip
    for tok in tokens:
        if tok.type not in (tokenize.COMMENT, tokenize.STRING):
            continue
        (srow, scol), (erow, ecol) = tok.start, tok.end
        for row in range(srow, erow + 1):
            line = kept[row - 1]
            lo = scol if row == srow else 0
            hi = ecol if row == erow else len(line)
            kept[row - 1] = line[:lo] + " " * (hi - lo) + line[hi:]
    return kept


def lint_file(path: str) -> list:
    exempt = _exempt(path)
    seam = _is_telemetry_seam(path)
    if exempt and seam:
        return []
    violations = []
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        source = f.read()
    raw_lines = source.splitlines()
    for lineno, code in enumerate(_code_lines(source), 1):
        raw = raw_lines[lineno - 1]
        if _PRAGMA in raw:
            continue
        if not exempt:
            if _COUNTER_BAG.search(code):
                violations.append(
                    (path, lineno, "bare counter bag", raw.rstrip()))
            if _SINK_EMIT.search(code):
                violations.append(
                    (path, lineno, "direct sink emit", raw.rstrip()))
            if _PRINTED_JSON.search(code):
                violations.append(
                    (path, lineno, "printed metric json", raw.rstrip()))
            if _DIRECT_RENDER.search(code):
                violations.append(
                    (path, lineno, "direct registry render", raw.rstrip()))
        # the wire key is a string literal, so this rule reads the RAW
        # line — and pierces the core/obs blanket exemption: only the
        # telemetry module itself may spell the key
        if not seam and _TELEMETRY_WIRE.search(raw):
            violations.append(
                (path, lineno, "telemetry wire key", raw.rstrip()))
    return violations


def lint_tree(root: str) -> list:
    violations = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                violations.extend(lint_file(os.path.join(dirpath, name)))
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.join(REPO_ROOT, "fedml_tpu"),
                    help="directory tree to lint (default: the library)")
    args = ap.parse_args(argv)

    violations = lint_tree(args.root)
    for path, lineno, kind, line in violations:
        rel = os.path.relpath(path, args.root)
        print(f"lint_obs: {rel}:{lineno}: {kind}: {line.strip()}", flush=True)
    if violations:
        print(f"lint_obs: {len(violations)} violation(s) — use "
              "obs.counter_inc/gauge_set/histogram_observe for metrics, "
              "the core/mlops helpers for records, the core/obs "
              "exporter for exposition, and ClientTelemetry.attach / "
              "TelemetryMerger.absorb for the telemetry wire key, or "
              f"mark an approved seam with '# {_PRAGMA}'", flush=True)
        return 1
    print("lint_obs: clean", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
