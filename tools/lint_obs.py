#!/usr/bin/env python
"""Observability lint: no bare counter bags, no direct sink emits.

Thin shim over the unified analysis plane (``fedml_tpu/core/analysis``,
see ``tools/fedlint.py`` and ``docs/STATIC_ANALYSIS.md``): the contracts,
the ``# lint_obs: allow`` pragma, the seam exemptions (``core/obs``,
``core/mlops``; the telemetry-wire-key rule still pierces them — only
``core/obs/telemetry.py`` may spell the key), and this CLI are unchanged,
but matching is now AST-based.  The telemetry-key rule is the framework's
one ``raw=True`` rule: it scans RAW lines because the key is a string
literal.

The contracts: metrics go through ``obs.counter_inc`` / ``gauge_set`` /
``histogram_observe``; records ride the mlops sink fan; stdout JSON is the
bench driver's line alone; exposition belongs to the core/obs exporter.

Usage::

    python tools/lint_obs.py            # lint the repo's fedml_tpu/
    python tools/lint_obs.py --root DIR # lint DIR instead (tests use this)
"""

from __future__ import annotations

import argparse
import os
import sys

from _analysis_loader import REPO_ROOT, load_analysis

_analysis = load_analysis()
_ANALYZER = _analysis.passes.ObsAnalyzer()
_PRAGMA = "lint_obs: allow"

_KINDS = {
    "obs-counter-bag": "bare counter bag",
    "obs-sink-emit": "direct sink emit",
    "obs-printed-json": "printed metric json",
    "obs-direct-render": "direct registry render",
    "obs-telemetry-key": "telemetry wire key",
}


def lint_file(path: str) -> list:
    src = _analysis.SourceFile(path)
    findings = _analysis.analyze_file(src, [_ANALYZER])
    findings.sort(key=lambda f: (f.lineno, _ANALYZER.rule_by_id(f.rule).order))
    return [(path, f.lineno, _KINDS[f.rule], f.source) for f in findings]


def lint_tree(root: str) -> list:
    violations = []
    for path in _analysis.iter_python_files(root):
        violations.extend(lint_file(path))
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.join(REPO_ROOT, "fedml_tpu"),
                    help="directory tree to lint (default: the library)")
    args = ap.parse_args(argv)

    violations = lint_tree(args.root)
    for path, lineno, kind, line in violations:
        rel = os.path.relpath(path, args.root)
        print(f"lint_obs: {rel}:{lineno}: {kind}: {line.strip()}", flush=True)
    if violations:
        print(f"lint_obs: {len(violations)} violation(s) — use "
              "obs.counter_inc/gauge_set/histogram_observe for metrics, "
              "the core/mlops helpers for records, the core/obs "
              "exporter for exposition, and ClientTelemetry.attach / "
              "TelemetryMerger.absorb for the telemetry wire key, or "
              f"mark an approved seam with '# {_PRAGMA}'", flush=True)
        return 1
    print("lint_obs: clean", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
